"""Rolling libtpu upgrade orchestration.

The UpgradeReconciler analog (controllers/upgrade_controller.go:81-353 +
the vendored NVIDIA/k8s-operator-libs/pkg/upgrade state machine): because
driver DaemonSets roll with ``OnDelete``, nothing upgrades until this
controller walks each node through a safety FSM persisted in the
``tpu.graft.dev/upgrade.state`` node label:

    upgrade-required -> cordon-required -> drain-required ->
    pod-restart-required -> validation-required -> uncordon-required ->
    done   (drain/validation deadlines branch to `failed`, retried with
    backoff)

Two behaviors the reference's per-node walk never needed (SURVEY.md
section 7 "genuinely new design"):

- **Slice-grouped upgrades.** Multi-host slices (one v5p-64 = 16 hosts
  wired by ICI) must never run mixed libtpu versions: the FSM's unit of
  progress is an *upgrade unit* — all hosts of a multi-host slice (keyed
  by accelerator x topology x gke-nodepool, matching
  topology/manager.py's grouped agreement), or a single host elsewhere.
  Every node of a unit transitions together, and
  upgradePolicy.maxParallelUpgrades counts units, not nodes.
- **Eviction-based drain with a failure path.** Drain goes through the
  Eviction API (client.evict), which PodDisruptionBudgets can block; the
  drain deadline (drainTimeoutSeconds) then either falls back to pod
  deletion (drainForce) or fails the unit. Validation likewise times out
  (validationTimeoutSeconds) into `failed` — reachable, alertable via
  tpu_operator_upgrade_state_nodes{state="failed"}, and retried after
  failedRetryBackoffSeconds (upgrade_controller.go:157-187 drain-spec
  semantics).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..api import labels as L
from ..api.clusterpolicy import KIND_CLUSTER_POLICY, V1, TPUClusterPolicySpec
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime import (
    Controller,
    EvictionBlockedError,
    Manager,
    Reconciler,
    Request,
    Result,
    WatchEvent,
    any_event,
    generation_changed,
)
from ..runtime.client import ListOptions, NotFoundError
from ..runtime.objects import get_nested, labels_of, name_of, namespace_of
from ..state.nodepool import get_node_pools
from ..utils.hash import object_hash

log = logging.getLogger("tpu_operator.upgrade")

REQUEUE_PERIODIC_S = 120.0  # upgrade_controller.go:59,197
REQUEUE_ACTIVE_S = 5.0

STATE_DONE = "done"
STATE_UPGRADE_REQUIRED = "upgrade-required"
STATE_CORDON = "cordon-required"
# elastic-slice stage between cordon and drain: placed slices on the
# unit get the slice-intent handshake (checkpoint → rebind onto
# replacement capacity) before their pods are evicted; past
# migrationTimeoutSeconds the unit degrades to the plain hard drain
STATE_MIGRATE = "migrate-required"
STATE_DRAIN = "drain-required"
STATE_POD_RESTART = "pod-restart-required"
STATE_VALIDATION = "validation-required"
STATE_UNCORDON = "uncordon-required"
STATE_FAILED = "failed"

# states that count against the parallel-upgrade budget
IN_PROGRESS_STATES = {STATE_CORDON, STATE_MIGRATE, STATE_DRAIN,
                      STATE_POD_RESTART, STATE_VALIDATION, STATE_UNCORDON}

# stage ordering used to heal a unit whose members diverged (a wiped
# label, an operator restart mid-transition): the unit resumes from the
# EARLIEST stage any member is in
_STAGE_ORDER = [STATE_UPGRADE_REQUIRED, STATE_CORDON, STATE_MIGRATE,
                STATE_DRAIN, STATE_POD_RESTART, STATE_VALIDATION,
                STATE_UNCORDON, STATE_DONE]


def desired_revision(client, ds: dict) -> str:
    """Current pod-template revision for a DaemonSet: the newest owned
    ControllerRevision when the control plane maintains them, else a local
    template hash (which is exactly what the fake kubelet stamps)."""
    try:
        revs = [r for r in client.list("apps/v1", "ControllerRevision",
                                       ListOptions(namespace=namespace_of(ds)))
                if any(ref.get("uid") == get_nested(ds, "metadata", "uid")
                       for ref in get_nested(r, "metadata", "ownerReferences",
                                             default=[]) or [])]
    except NotFoundError:
        revs = []
    if revs:
        newest = max(revs, key=lambda r: r.get("revision", 0))
        return get_nested(newest, "metadata", "labels",
                          "controller-revision-hash",
                          default=name_of(newest).rsplit("-", 1)[-1])
    return object_hash(get_nested(ds, "spec", "template", default={}))


@dataclass
class _Member:
    """One node's view within an upgrade unit."""

    node: dict
    pod: Optional[dict]          # its driver pod (None = nothing to upgrade)
    want: Optional[str]          # desired driver revision
    have: Optional[str]          # running driver revision
    pod_ready: bool

    @property
    def name(self) -> str:
        return name_of(self.node)

    @property
    def state(self) -> Optional[str]:
        return labels_of(self.node).get(L.UPGRADE_STATE)

    @property
    def at_new_revision(self) -> bool:
        return self.pod is None or self.have == self.want


class UpgradeReconciler(Reconciler):
    name = "tpu-upgrade"
    primary_kind = "TPUClusterPolicy"  # requests name the owning policy

    def __init__(self, client, namespace: str = "tpu-operator",
                 now=time.time, recorder=None):
        from ..runtime.events import EventRecorder

        self.client = client
        self.namespace = namespace
        self.now = now  # injectable clock for deadline tests
        # node Events on every FSM transition (the reference's upgrade
        # lib does the same, drain_manager.go:105-129): kubectl describe
        # node is where operators look first when a node misbehaves
        self.recorder = recorder or EventRecorder(client,
                                                  namespace=namespace)

    def setup_controller(self, controller: Controller, manager: Manager):
        from ..runtime import label_changed

        controller.watch(V1, KIND_CLUSTER_POLICY, predicate=generation_changed,
                         mapper=self._enqueue_policy)
        controller.watch("apps/v1", "DaemonSet", predicate=any_event,
                         mapper=self._enqueue_policy)
        # edge triggers for the FSM's two wait states: a driver/validator
        # pod landing (or turning Ready) unblocks pod-restart-required /
        # validation-required immediately, and an upgrade-state label
        # flip on any node lets the budget admit the next unit in the
        # same tick — instead of burning a REQUEUE_ACTIVE_S poll per hop
        controller.watch("v1", "Pod", predicate=any_event,
                         mapper=self._enqueue_policy)
        controller.watch("v1", "Node",
                         predicate=label_changed(L.UPGRADE_STATE),
                         mapper=self._enqueue_policy)

    def _enqueue_policy(self, event: WatchEvent):
        for cr in self.client.list(V1, KIND_CLUSTER_POLICY):
            yield Request(name=name_of(cr))

    # -- helpers -----------------------------------------------------------

    def _driver_daemonsets(self) -> List[dict]:
        return self.client.list(
            "apps/v1", "DaemonSet",
            ListOptions(namespace=self.namespace,
                        label_selector={"tpu.graft.dev/component":
                                        "libtpu-driver"}))

    def _driver_pods_by_node(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for pod in self.client.list(
                "v1", "Pod",
                ListOptions(namespace=self.namespace,
                            label_selector={"tpu.graft.dev/component":
                                            "libtpu-driver"})):
            if get_nested(pod, "metadata", "deletionTimestamp"):
                # a Terminating old-revision pod must not shadow its
                # replacement in the one-pod-per-node map
                continue
            node = get_nested(pod, "spec", "nodeName")
            if node:
                out[node] = pod
        return out

    VALIDATOR_APPS = ("tpu-operator-validator", "tpu-isolated-validator")

    def _validator_pods_by_node(self) -> Dict[str, List[dict]]:
        """node -> its validation-gate pods — operator-validator on
        container nodes, isolated-validator on isolated/virtual nodes
        (the reference validates upgrades via its
        app=nvidia-operator-validator pods, cmd/gpu-operator/main.go:151).
        One LIST per app per reconcile; Terminating pods are excluded —
        a dying validator's Ready=True is the OLD proof, not a
        re-validation against the new driver."""
        out: Dict[str, List[dict]] = {}
        for app in self.VALIDATOR_APPS:
            for pod in self.client.list(
                    "v1", "Pod",
                    ListOptions(namespace=self.namespace,
                                label_selector={"app": app})):
                if get_nested(pod, "metadata", "deletionTimestamp"):
                    continue
                node = get_nested(pod, "spec", "nodeName")
                if node:
                    out.setdefault(node, []).append(pod)
        return out

    def _validator_ds_exists(self) -> bool:
        """Whether any validation-gate DaemonSet is deployed at all — with
        the validator state disabled there are no gate pods to wait for
        and upgrade validation falls back to driver-pod readiness."""
        return any(
            get_nested(ds, "metadata", "labels", "app") in self.VALIDATOR_APPS
            for ds in self.client.list(
                "apps/v1", "DaemonSet",
                ListOptions(namespace=self.namespace)))

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        from ..runtime.objects import pod_ready

        return pod_ready(pod)

    @staticmethod
    def _drainable(pod: dict, names: tuple) -> bool:
        """True when this pod holds TPU chips and must leave before a
        libtpu swap — the reference's gpuPodSpecFilter (main.go:198-209):
        prefix-matched resource requests (isolated google.com/tpu-isolated
        and fractional google.com/vtpu consumers count too), completed
        pods / daemon pods / the driver itself excluded."""
        if get_nested(pod, "metadata", "deletionTimestamp"):
            return False
        # completed pods hold no chips (main.go:209 phase filter)
        if get_nested(pod, "status", "phase",
                      default="Running") in ("Succeeded", "Failed"):
            return False
        if labels_of(pod).get(L.UPGRADE_SKIP_DRAIN) == "true":
            return False
        if labels_of(pod).get("tpu.graft.dev/component") == "libtpu-driver":
            return False
        # daemon pods are not drained (kubectl drain --ignore-daemonsets)
        owners = get_nested(pod, "metadata", "ownerReferences",
                            default=[]) or []
        if any(o.get("kind") == "DaemonSet" for o in owners):
            return False
        requests = {}
        for ctr in get_nested(pod, "spec", "containers", default=[]) or []:
            requests.update(get_nested(ctr, "resources", "requests",
                                       default={}) or {})
        return any(str(r).startswith(n) for r in requests for n in names)

    def _tpu_workload_pods_by_node(
            self, resource_names: Optional[tuple] = None,
    ) -> Dict[str, List[dict]]:
        """node -> pods consuming TPU resources — the drain set (the
        reference drains with a GPU-pod selector, main.go:105-117). One
        cluster-wide LIST per reconcile, not one per draining node.
        ``resource_names`` carries the policy's configured plugin
        resource names (shared/isolated/vTPU can all be renamed); the
        defaults always apply too."""
        names = tuple(resource_names or ()) + (L.TPU_RESOURCE,
                                               L.VTPU_RESOURCE)
        out: Dict[str, List[dict]] = {}
        for pod in self.client.list("v1", "Pod"):
            node_name = get_nested(pod, "spec", "nodeName")
            if node_name and self._drainable(pod, names):
                out.setdefault(node_name, []).append(pod)
        return out

    def _tpu_workload_pods_on(self, node_name: str,
                              resource_names: Optional[tuple] = None,
    ) -> Optional[List[dict]]:
        """Index fast path for the drain set: when the client is a
        CachedClient, its pod-by-node index answers "which pods hold
        chips on THIS node" in O(pods-on-node) — no cluster-wide scan.
        Returns None when the client has no such index (the caller falls
        back to :meth:`_tpu_workload_pods_by_node`)."""
        index = getattr(self.client, "index", None)
        if index is None or not self.client.has_index("v1", "Pod", "by-node"):
            return None
        names = tuple(resource_names or ()) + (L.TPU_RESOURCE,
                                               L.VTPU_RESOURCE)
        return [pod for pod in index("v1", "Pod", "by-node", node_name)
                if self._drainable(pod, names)]

    # -- node label/annotation writes --------------------------------------

    def _set_node_state(self, node: dict, state: Optional[str]) -> None:
        self.client.patch("v1", "Node", name_of(node),
                          {"metadata": {"labels": {L.UPGRADE_STATE: state}}})

    def _annotate(self, node: dict, **kv) -> None:
        self.client.patch("v1", "Node", name_of(node),
                          {"metadata": {"annotations": dict(kv)}})

    def _cordon(self, node: dict, on: bool) -> None:
        self.client.patch("v1", "Node", name_of(node),
                          {"spec": {"unschedulable": True if on else None}})

    def _release_node(self, node: dict) -> None:
        """Strip a node's FSM label/annotations and undo any cordon the
        FSM applied — a node paused mid-rollout (after STATE_CORDON,
        before STATE_UNCORDON) must not be left unschedulable forever."""
        state = labels_of(node).get(L.UPGRADE_STATE)
        # any FSM-owned state may hold a cordon (failed units stay
        # cordoned; a retrying unit can sit in upgrade-required cordoned
        # while the budget is full) — DONE already uncordoned
        if state not in (None, STATE_DONE) and get_nested(
                node, "spec", "unschedulable", default=False):
            self._cordon(node, False)
        self._annotate(node, **{L.UPGRADE_STAGE_STARTED: None,
                                L.UPGRADE_FAILED_AT: None,
                                L.UPGRADE_FAILED_REASON: None})
        self._set_node_state(node, None)

    def remove_upgrade_state_labels(self) -> None:
        """Auto-upgrade disabled: strip FSM labels (+ leftover cordons)
        (removeNodeUpgradeStateLabels analog, upgrade_controller.go:103-121)."""
        for node in self.client.list("v1", "Node"):
            if L.UPGRADE_STATE in labels_of(node):
                self._release_node(node)

    # -- unit machinery ----------------------------------------------------

    def _upgrade_units(self, nodes: Dict[str, dict]) -> List[List[str]]:
        """Partition eligible nodes into upgrade units: every host of a
        multi-host slice moves as one unit (slice identity = accelerator x
        topology x gke-nodepool, the same grouping topology/manager.py
        uses for grouped slice-config agreement); single-host nodes are
        their own unit."""
        from ..state.nodepool import slices_of

        units: List[List[str]] = []
        grouped = set()
        for pool in get_node_pools(list(nodes.values())):
            if pool.multi_host:
                by_slice = slices_of(pool, nodes)
                for _, members in sorted(by_slice.items()):
                    units.append(sorted(members))
            else:
                for node_name in pool.nodes:
                    units.append([node_name])
            grouped.update(pool.nodes)
        # nodes outside any TPU pool (no accelerator label) can still run
        # a driver pod in odd setups; treat them as singleton units
        for name in sorted(set(nodes) - grouped):
            units.append([name])
        units.sort(key=lambda u: u[0])
        return units

    def _unit_state(self, members: List[_Member]) -> Optional[str]:
        """Aggregate FSM state of a unit: failed dominates; otherwise the
        earliest stage any member is in (heals divergence after partial
        writes/restarts)."""
        states = [m.state for m in members]
        if any(s == STATE_FAILED for s in states):
            return STATE_FAILED
        present = [s for s in states if s in _STAGE_ORDER]
        if not present:
            return None
        return min(present, key=_STAGE_ORDER.index)

    def _set_unit_state(self, members: List[_Member], state: str) -> None:
        from ..runtime.timeline import TIMELINE
        from ..runtime.tracing import TRACER

        if TIMELINE.enabled:
            TIMELINE.record("UpgradeUnit", members[0].name, "fsm:" + state,
                            {"controller": self.name,
                             "nodes": len(members)})
        with TRACER.span("fsm:" + state, unit=members[0].name,
                         nodes=len(members)):
            for m in members:
                if m.state != state:
                    self._set_node_state(m.node, state)
                    # keep the in-pass snapshot truthful: the divergence
                    # heal can move a member BACKWARD, and the later
                    # same-pass forward transitions compare against
                    # m.state — a stale label would make them skip the
                    # write and leave the unit split again
                    refreshed = self.client.get_or_none(
                        "v1", "Node", m.name)
                    if refreshed is not None:
                        m.node = refreshed

    def _stage_started(self, members: List[_Member]) -> Optional[float]:
        stamps = []
        for m in members:
            v = (get_nested(m.node, "metadata", "annotations",
                            default={}) or {}).get(L.UPGRADE_STAGE_STARTED)
            try:
                stamps.append(float(v))
            except (TypeError, ValueError):
                pass
        return min(stamps) if stamps else None

    def _stamp_stage(self, members: List[_Member]) -> None:
        stamp = str(self.now())
        for m in members:
            self._annotate(m.node, **{L.UPGRADE_STAGE_STARTED: stamp})

    def _fail_unit(self, members: List[_Member], reason: str) -> None:
        from ..runtime.timeline import TIMELINE
        from ..runtime.tracing import TRACER

        if TIMELINE.enabled:
            TIMELINE.record("UpgradeUnit", members[0].name, "fsm:failed",
                            {"controller": self.name, "reason": reason})
        stamp = str(self.now())
        log.error("upgrade unit [%s] failed: %s",
                  ",".join(m.name for m in members), reason)
        TRACER.tag("upgrade_failed_unit", members[0].name)
        TRACER.tag("upgrade_failed_reason", reason)
        for m in members:
            self._annotate(m.node, **{L.UPGRADE_FAILED_AT: stamp,
                                      L.UPGRADE_FAILED_REASON: reason,
                                      L.UPGRADE_STAGE_STARTED: None})
            self.recorder.event(m.node, "Warning", "DriverUpgradeFailed",
                                reason)
        self._set_unit_state(members, STATE_FAILED)
        OPERATOR_METRICS.driver_upgrades_failed.inc()

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, request: Request) -> Result:
        import time as _time

        from ..runtime.tracing import TRACER

        started = _time.perf_counter()
        try:
            # trace root for direct-driven runs (rollout bench, chaos
            # runner); passthrough under a Controller worker
            with TRACER.trace(self.name, str(request)):
                return self._reconcile(request)
        finally:
            OPERATOR_METRICS.reconcile_duration_by_controller.labels(
                controller=self.name).observe(_time.perf_counter() - started)

    def _reconcile(self, request: Request) -> Result:
        cr = self.client.get_or_none(V1, KIND_CLUSTER_POLICY, request.name)
        if cr is None:
            return Result()
        spec = TPUClusterPolicySpec.from_obj(cr)
        policy = spec.upgrade_policy
        # CR-level pause without spec surgery: annotating the policy CR
        # with tpu.graft.dev/driver-upgrade-enabled != "true" halts the
        # rollout exactly like autoUpgrade: false
        cr_gate = (get_nested(cr, "metadata", "annotations",
                              default={}) or {}).get(L.DRIVER_UPGRADE_ENABLED)
        if (not policy.auto_upgrade
                or spec.sandbox_workloads.is_enabled()  # sandbox gate,
                # upgrade_controller.go:103-121: rollouts are container-
                # plane only; isolated/virtual nodes must not be drained
                or (cr_gate is not None and cr_gate != "true")):
            self.remove_upgrade_state_labels()
            return Result()

        daemonsets = self._driver_daemonsets()
        if not daemonsets:
            return Result(requeue_after=REQUEUE_PERIODIC_S)

        nodes = {name_of(n): n for n in self.client.list("v1", "Node")}
        revisions = {name_of(ds): desired_revision(self.client, ds)
                     for ds in daemonsets}
        driver_pods = self._driver_pods_by_node()
        validator_pods = self._validator_pods_by_node()
        validator_gate_deployed = self._validator_ds_exists()

        drain_timeout = (policy.drain_timeout_seconds
                         if policy.drain_timeout_seconds is not None else 300)
        validation_timeout = (policy.validation_timeout_seconds
                              if policy.validation_timeout_seconds is not None
                              else 300)
        retry_backoff = (policy.failed_retry_backoff_seconds
                         if policy.failed_retry_backoff_seconds is not None
                         else 60)
        migration_timeout = (policy.migration_timeout_seconds
                             if policy.migration_timeout_seconds is not None
                             else 120)

        # eligible = opted-in nodes (per-node pause: the policy reconciler
        # stamps this annotation "true" on TPU nodes while autoUpgrade is
        # on; any other explicit value excludes the node without touching
        # the CR — driverAutoUpgradeAnnotationKey contract,
        # state_manager.go:423-477. Absent = eligible, so the controller
        # also works driven standalone.)
        opted_out = set()
        for node_name, node in nodes.items():
            anns = get_nested(node, "metadata", "annotations",
                              default={}) or {}
            optin = anns.get(L.DRIVER_UPGRADE_ENABLED)
            if optin is not None and optin != "true":
                opted_out.add(node_name)
                if labels_of(node).get(L.UPGRADE_STATE):
                    self._release_node(node)

        def member_of(node_name: str) -> _Member:
            node = nodes[node_name]
            pod = driver_pods.get(node_name)
            want = have = None
            pod_ready = False
            if pod is not None:
                ds_name = next((o.get("name") for o in
                                get_nested(pod, "metadata", "ownerReferences",
                                           default=[]) or []
                                if o.get("kind") == "DaemonSet"), None)
                want = revisions.get(ds_name)
                have = labels_of(pod).get("controller-revision-hash")
                pod_ready = self._pod_ready(pod)
                if want is None:
                    pod = None  # not one of ours; nothing to upgrade
            return _Member(node=node, pod=pod, want=want, have=have,
                           pod_ready=pod_ready)

        # units are partitioned over ALL nodes first: an opted-out host
        # must take its whole multi-host slice out of the rollout, not
        # shrink the unit — half a slice upgrading alone is exactly the
        # mixed-libtpu-versions state the unit mechanism prevents
        units = []
        for unit_names in self._upgrade_units(nodes):
            if any(n in opted_out for n in unit_names):
                for n in unit_names:
                    if n not in opted_out and labels_of(
                            nodes[n]).get(L.UPGRADE_STATE):
                        self._release_node(nodes[n])
                continue
            units.append([member_of(n) for n in unit_names])
        # drop units with nothing to upgrade-manage at all
        units = [u for u in units
                 if any(m.pod is not None for m in u)
                 or any(m.state for m in u)]

        # at most one cluster-wide pod LIST per reconcile, and only when
        # something is actually draining; with a CachedClient the by-node
        # pod index answers per node in O(pods-on-node) instead
        workload_pods: Optional[Dict[str, List[dict]]] = None

        # the configured plugin resource names: renamed shared/
        # isolated/vTPU resources must still land in the drain set
        dp = spec.device_plugin
        iso = spec.isolated_device_plugin
        drain_resource_names = tuple(n for n in (
            dp.resource_name if dp else None,
            iso.resource_name if iso else None,
            iso.vtpu_resource_name if iso else None) if n)

        def drain_pods_on(node_name: str) -> List[dict]:
            nonlocal workload_pods
            indexed = self._tpu_workload_pods_on(
                node_name, resource_names=drain_resource_names)
            if indexed is not None:
                return indexed
            if workload_pods is None:
                workload_pods = self._tpu_workload_pods_by_node(
                    resource_names=drain_resource_names)
            return workload_pods.get(node_name, [])

        budget = max(1, policy.max_parallel_upgrades or 1)
        in_progress_units = sum(
            1 for u in units if self._unit_state(u) in IN_PROGRESS_STATES)

        node_states: Dict[str, str] = {}

        def record(members: List[_Member], state: str) -> None:
            for m in members:
                node_states[m.name] = state

        for members in units:
            state = self._unit_state(members)
            needs = any(not m.at_new_revision for m in members)

            if state in IN_PROGRESS_STATES or state == STATE_UPGRADE_REQUIRED:
                # divergence heal on EVERY pass, not only on the next
                # transition: a member whose stage label was wiped (or
                # that crashed ahead of its siblings) re-syncs to the
                # unit's aggregate earliest stage even while the unit is
                # just waiting (e.g. parked in validation). No-op — and
                # zero writes — when the members already agree.
                self._set_unit_state(members, state)

            if state == STATE_FAILED:
                # retry with backoff: failed -> upgrade-required
                failed_ats = []
                for m in members:
                    v = (get_nested(m.node, "metadata", "annotations",
                                    default={}) or {}).get(L.UPGRADE_FAILED_AT)
                    try:
                        failed_ats.append(float(v))
                    except (TypeError, ValueError):
                        pass
                failed_at = max(failed_ats) if failed_ats else 0.0
                if self.now() - failed_at >= retry_backoff:
                    log.info("retrying failed upgrade unit [%s]",
                             ",".join(m.name for m in members))
                    for m in members:
                        self._annotate(m.node,
                                       **{L.UPGRADE_FAILED_AT: None,
                                          L.UPGRADE_FAILED_REASON: None})
                    state = STATE_UPGRADE_REQUIRED
                    self._set_unit_state(members, state)
                else:
                    record(members, STATE_FAILED)
                    continue

            if not needs and state in (None, STATE_DONE):
                for m in members:
                    if m.state is not None and m.state != STATE_DONE:
                        self._set_node_state(m.node, STATE_DONE)
                record(members, STATE_DONE)
                continue

            # FSM advance (multiple safe steps per pass), unit-atomic
            if state in (None, STATE_DONE) and needs:
                state = STATE_UPGRADE_REQUIRED
                self._set_unit_state(members, state)
            if state == STATE_UPGRADE_REQUIRED:
                if in_progress_units >= budget:
                    record(members, state)
                    continue
                in_progress_units += 1
                state = STATE_CORDON
                self._set_unit_state(members, state)
            if state == STATE_CORDON:
                for m in members:
                    self._cordon(m.node, True)
                    self.recorder.event(
                        m.node, "Normal", "DriverUpgradeStarted",
                        "Node cordoned; scheduling drain of the node")
                self._stamp_stage(members)
                state = STATE_MIGRATE
                self._set_unit_state(members, state)
            if state == STATE_MIGRATE:
                proceed = True
                if migration_timeout > 0:
                    started = self._stage_started(members)
                    if started is None:
                        self._stamp_stage(members)
                        started = self.now()
                    from .slices import SliceMigrator

                    migrator = SliceMigrator(self.client, now=self.now)
                    proceed = migrator.ready_to_drain(
                        [m.name for m in members],
                        started + migration_timeout)
                if proceed:
                    # fresh stamp: the drain deadline must not be
                    # pre-consumed by however long the handshake took
                    self._stamp_stage(members)
                    state = STATE_DRAIN
                    self._set_unit_state(members, state)
                else:
                    record(members, state)
                    continue
            if state == STATE_DRAIN:
                remaining = 0
                blocked: List[str] = []
                if policy.drain_enable in (None, True):
                    for m in members:
                        for victim in drain_pods_on(m.name):
                            try:
                                self.client.evict(name_of(victim),
                                                  namespace_of(victim) or None)
                                log.info("evicted pod %s/%s from %s",
                                         namespace_of(victim),
                                         name_of(victim), m.name)
                            except EvictionBlockedError as e:
                                remaining += 1
                                blocked.append(str(e))
                            except NotFoundError:
                                pass
                if remaining == 0:
                    state = STATE_POD_RESTART
                    self._set_unit_state(members, state)
                else:
                    started = self._stage_started(members)
                    if started is None:
                        # no stamp (pre-existing label from an older
                        # operator, or a recreated Node object): persist
                        # one so the deadline actually elapses
                        self._stamp_stage(members)
                        started = self.now()
                    if self.now() - started > drain_timeout:
                        if policy.drain_force:
                            # deadline passed and the policy says go:
                            # bypass the budget via direct deletion
                            for m in members:
                                for victim in drain_pods_on(m.name):
                                    try:
                                        self.client.delete(
                                            "v1", "Pod", name_of(victim),
                                            namespace_of(victim) or None)
                                    except NotFoundError:
                                        pass
                            log.warning(
                                "drain deadline passed on unit [%s]; "
                                "force-deleted remaining TPU pods",
                                ",".join(m.name for m in members))
                            for m in members:
                                self.recorder.event(
                                    m.node, "Warning", "DrainForced",
                                    f"Drain deadline ({drain_timeout}s) "
                                    f"passed; remaining TPU pods deleted")
                            state = STATE_POD_RESTART
                            self._set_unit_state(members, state)
                        else:
                            self._fail_unit(
                                members,
                                f"drain timed out after {drain_timeout}s: "
                                + "; ".join(blocked[:3]))
                            record(members, STATE_FAILED)
                            continue
                    else:
                        record(members, state)
                        continue
            if state == STATE_POD_RESTART:
                # the validator pods restart WITH the driver: their
                # initContainers re-prove the node against the new libtpu
                # (the driver-manager preflight closed every gate), which
                # is what STATE_VALIDATION then waits on
                for m in members:
                    victims = ([m.pod] if m.pod is not None else []) \
                        + validator_pods.get(m.name, [])
                    for v in victims:
                        try:
                            self.client.delete("v1", "Pod", name_of(v),
                                               namespace_of(v) or None)
                        except NotFoundError:
                            pass
                log.info("restarting driver + validator pods on unit [%s]",
                         ",".join(m.name for m in members))
                self._stamp_stage(members)
                state = STATE_VALIDATION
                self._set_unit_state(members, state)
                record(members, state)
                continue  # must wait for kubelet to recreate
            if state == STATE_VALIDATION:
                def validated(m: _Member) -> bool:
                    # mid-restart a member has NO driver pod; that is not
                    # "nothing to upgrade", it is "new revision unproven"
                    # — without this the unit could uncordon before the
                    # kubelet ever recreates the driver
                    if m.pod is None:
                        return False
                    validators = validator_pods.get(m.name, [])
                    validators_ok = all(self._pod_ready(p)
                                        for p in validators) \
                        and (bool(validators) or not validator_gate_deployed)
                    return m.have == m.want and m.pod_ready and validators_ok

                if all(validated(m) for m in members):
                    state = STATE_UNCORDON
                    self._set_unit_state(members, state)
                else:
                    started = self._stage_started(members)
                    if started is None:
                        self._stamp_stage(members)
                        started = self.now()
                    if self.now() - started > validation_timeout:
                        unproven = [m.name for m in members
                                    if not validated(m)]
                        self._fail_unit(
                            members,
                            f"validation timed out after "
                            f"{validation_timeout}s on: "
                            + ",".join(unproven))
                        record(members, STATE_FAILED)
                    else:
                        record(members, state)
                    continue
            if state == STATE_UNCORDON:
                for m in members:
                    self._cordon(m.node, False)
                    self._annotate(m.node,
                                   **{L.UPGRADE_STAGE_STARTED: None})
                    self._set_node_state(m.node, STATE_DONE)
                    self.recorder.event(
                        m.node, "Normal", "DriverUpgradeComplete",
                        "New libtpu revision validated; node uncordoned")
                    OPERATOR_METRICS.driver_upgrades_done.inc()
                log.info("upgrade unit [%s] complete",
                         ",".join(m.name for m in members))
                record(members, STATE_DONE)
                continue
            record(members, state or STATE_DONE)

        pending = [n for n, s in node_states.items()
                   if s not in (STATE_DONE,)]
        # unit state after this pass = the recorded state of any member
        # (the unit loop keeps them in lockstep); the member dicts
        # themselves are pre-pass snapshots
        OPERATOR_METRICS.upgrade_units_in_progress.set(
            sum(1 for u in units
                if node_states.get(u[0].name) in IN_PROGRESS_STATES))
        OPERATOR_METRICS.driver_upgrades_in_progress.set(
            sum(1 for s in node_states.values() if s in IN_PROGRESS_STATES))
        OPERATOR_METRICS.driver_upgrades_pending.set(
            sum(1 for s in node_states.values()
                if s == STATE_UPGRADE_REQUIRED))
        for fsm_state in (STATE_DONE, STATE_UPGRADE_REQUIRED, STATE_CORDON,
                          STATE_DRAIN, STATE_POD_RESTART, STATE_VALIDATION,
                          STATE_UNCORDON, STATE_FAILED):
            OPERATOR_METRICS.upgrade_state_nodes.labels(state=fsm_state).set(
                sum(1 for s in node_states.values() if s == fsm_state))
        if pending:
            return Result(requeue_after=REQUEUE_ACTIVE_S)
        return Result(requeue_after=REQUEUE_PERIODIC_S)
