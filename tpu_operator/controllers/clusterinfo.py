"""Cluster facts provider (controllers/clusterinfo/clusterinfo.go:42-454
analog). The OpenShift-specific getters (RHCOS versions, DTK images, proxy)
have no TPU/GKE analog and are dropped per SURVEY.md section 7; the TPU
additions are topology/generation summaries used by the topology manager
and the bench harness.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from ..api import labels as L
from ..runtime.client import Client
from ..runtime.objects import get_nested, labels_of


@dataclass
class ClusterInfo:
    client: Client

    def get_kubernetes_version(self) -> str:
        for node in self.client.list("v1", "Node"):
            v = get_nested(node, "status", "nodeInfo", "kubeletVersion",
                           default="")
            if v:
                return v
        return "unknown"

    def get_container_runtime(self) -> str:
        for node in self.client.list("v1", "Node"):
            rt = get_nested(node, "status", "nodeInfo",
                            "containerRuntimeVersion", default="")
            if rt:
                return rt.split(":")[0]
        return "containerd"

    def get_kernel_versions(self) -> List[str]:
        out = set()
        for node in self.client.list("v1", "Node"):
            kv = get_nested(node, "status", "nodeInfo", "kernelVersion",
                            default="")
            if kv:
                out.add(kv)
        return sorted(out)

    def get_tpu_topologies(self) -> Dict[str, int]:
        """topology string -> node count, across TPU nodes."""
        out: Dict[str, int] = {}
        for node in self.client.list("v1", "Node"):
            nl = labels_of(node)
            if L.GKE_TPU_ACCELERATOR not in nl:
                continue
            topo = nl.get(L.GKE_TPU_TOPOLOGY, "unknown")
            out[topo] = out.get(topo, 0) + 1
        return out

    def get_tpu_generations(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for node in self.client.list("v1", "Node"):
            nl = labels_of(node)
            accel = nl.get(L.GKE_TPU_ACCELERATOR)
            if not accel:
                continue
            gen = L.accelerator_generation(accel)
            out[gen] = out.get(gen, 0) + 1
        return out
