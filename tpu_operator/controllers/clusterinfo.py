"""Cluster facts provider (controllers/clusterinfo/clusterinfo.go:42-454
analog). The OpenShift-specific getters (RHCOS versions, DTK images, proxy)
have no TPU/GKE analog and are dropped per SURVEY.md section 7; the TPU
additions are topology/generation summaries.

``facts()`` computes everything in ONE node list (a 200-node cluster must
not pay one list per fact, per reconcile); the per-getter API is the
parity surface, each expressed over that single pass so the two can
never drift. The reconcile loop publishes the dict on the CR's
``status.clusterInfo`` and passes it to states via SyncContext.cluster.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from ..runtime.client import Client
from ..runtime.objects import get_nested
from .nodeinfo import attributes_of

import logging

log = logging.getLogger("tpu_operator.clusterinfo")


@dataclass
class ClusterInfo:
    client: Client

    def facts(self) -> Dict:
        """One pass over the node list. ``containerRuntime`` follows the
        reference's getRuntime discipline (state_manager.go:714-751):
        TPU nodes decide by majority (mixed fleets are warned about);
        non-TPU nodes only serve as a fallback."""
        k8s = ""
        kernels = set()
        topologies: Dict[str, int] = {}
        generations: Dict[str, int] = {}
        rt_counts: Dict[str, int] = {}
        rt_fallback = ""
        for node in self.client.list("v1", "Node"):
            info = get_nested(node, "status", "nodeInfo", default={}) or {}
            k8s = k8s or info.get("kubeletVersion", "")
            if info.get("kernelVersion"):
                kernels.add(info["kernelVersion"])
            attrs = attributes_of(node)
            rt = (info.get("containerRuntimeVersion") or "").split(":")[0]
            if rt:
                if attrs.is_tpu:
                    rt_counts[rt] = rt_counts.get(rt, 0) + 1
                elif not rt_fallback:
                    rt_fallback = rt
            if not attrs.is_tpu:
                continue
            topo = attrs.topology or "unknown"
            topologies[topo] = topologies.get(topo, 0) + 1
            if attrs.generation:
                generations[attrs.generation] = \
                    generations.get(attrs.generation, 0) + 1
        if rt_counts:
            if len(rt_counts) > 1:
                log.warning("mixed container runtimes across TPU nodes: "
                            "%s; using the majority runtime", rt_counts)
            # majority wins; name breaks ties deterministically
            runtime = max(rt_counts.items(),
                          key=lambda kv: (kv[1], kv[0]))[0]
        else:
            runtime = rt_fallback or "containerd"
        return {
            "kubernetesVersion": k8s or "unknown",
            "containerRuntime": runtime,
            "kernelVersions": sorted(kernels),
            "tpuTopologies": topologies,
            "tpuGenerations": generations,
        }

    # -- per-getter parity surface (clusterinfo.go getters) ---------------

    def get_kubernetes_version(self) -> str:
        return self.facts()["kubernetesVersion"]

    def get_container_runtime(self) -> str:
        return self.facts()["containerRuntime"]

    def get_kernel_versions(self) -> List[str]:
        return self.facts()["kernelVersions"]

    def get_tpu_topologies(self) -> Dict[str, int]:
        """topology string -> node count, across TPU nodes."""
        return self.facts()["tpuTopologies"]

    def get_tpu_generations(self) -> Dict[str, int]:
        return self.facts()["tpuGenerations"]
