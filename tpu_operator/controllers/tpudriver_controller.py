"""TPUDriver reconciler — per-node-pool libtpu rollout (engine B path).

Mirrors NVIDIADriverReconciler (controllers/nvidiadriver_controller.go:
75-408 + internal/state/driver.go:106-692): validates the CR against
sibling CRs, partitions the CR's nodes into (generation x topology) pools,
renders one driver DaemonSet per pool from the same manifest dir the
ClusterPolicy state uses, cleans up stale pool DaemonSets, and reports
aggregate readiness through status + conditions.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api import conditions
from ..api import labels as L
from ..api.clusterpolicy import (
    KIND_CLUSTER_POLICY,
    STATE_NOT_READY,
    STATE_READY,
    V1,
    TPUClusterPolicySpec,
)
from ..api.tpudriver import KIND_TPU_DRIVER, V1ALPHA1, TPUDriverSpec
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..render import Renderer
from ..runtime import (
    Controller,
    Manager,
    Reconciler,
    Request,
    Result,
    enqueue_object,
    enqueue_owner,
    generation_changed,
)
from ..runtime.objects import name_of, set_nested, thaw_obj
from ..state.nodepool import get_node_pools
from ..state.operands import (
    MANIFESTS_ROOT,
    apply_common_config,
    common_data,
    operator_init_image,
    resolve_image,
)
from ..state.skel import apply_objects, objects_ready
from ..state.state import SyncContext
from .validation import ValidationError, validate_node_selectors

log = logging.getLogger("tpu_operator.tpudriver")

REQUEUE_NOT_READY_S = 5.0  # nvidiadriver_controller.go:175-206 analog


class TPUDriverReconciler(Reconciler):
    name = "tpudriver"

    def __init__(self, client, namespace: str = "tpu-operator",
                 manifests_root=None):
        self.client = client
        self.namespace = namespace
        self.manifests_root = manifests_root or MANIFESTS_ROOT

    def setup_controller(self, controller: Controller, manager: Manager):
        controller.watch(V1ALPHA1, KIND_TPU_DRIVER,
                         predicate=generation_changed)
        controller.watch("apps/v1", "DaemonSet",
                         mapper=enqueue_owner(V1ALPHA1, KIND_TPU_DRIVER))
        # driver-pod phase flips decide per-pool readiness; edge-trigger
        # them instead of waiting for the 5s not-ready requeue
        controller.watch("v1", "Pod", mapper=self._enqueue_all_drivers)

    def _enqueue_all_drivers(self, event):
        # the informer-backed cache serves this LIST in-process, so a
        # pod churn storm costs no apiserver traffic
        for cr in self.client.list(V1ALPHA1, KIND_TPU_DRIVER):
            yield Request(name=name_of(cr))

    def _state_label(self, cr_name: str) -> str:
        return f"tpu-driver-{cr_name}"

    def reconcile(self, request: Request) -> Result:
        import time as _time

        from ..runtime.tracing import TRACER

        started = _time.perf_counter()
        try:
            # trace root for direct-driven runs; passthrough when the
            # Controller worker already opened the trace at dequeue
            with TRACER.trace(self.name, str(request)):
                return self._reconcile(request)
        finally:
            # sole observation point of the per-controller duration
            # histogram (one sample per reconcile, every drive path)
            OPERATOR_METRICS.reconcile_duration_by_controller.labels(
                controller=self.name).observe(_time.perf_counter() - started)

    def _reconcile(self, request: Request) -> Result:
        live = self.client.get_or_none(V1ALPHA1, KIND_TPU_DRIVER, request.name)
        if live is None:
            # deleted: owned DaemonSets go with it via ownerRef GC
            return Result()
        # cached reads are shared frozen snapshots; status is written in
        # place below, so reconcile a private thawed copy and keep
        # ``live`` for the conditions status-write skip
        cr = thaw_obj(live)

        # a ClusterPolicy must exist to supply stack-wide defaults
        # (nvidiadriver_controller.go:80-125)
        policies = self.client.list(V1, KIND_CLUSTER_POLICY)
        if not policies:
            # state first, conditions second: set_* writes status once —
            # a trailing second write would 409 by construction (the
            # server bumped rv on the first)
            set_nested(cr, STATE_NOT_READY, "status", "state")
            conditions.set_error(self.client, cr, "MissingClusterPolicy",
                                 "no TPUClusterPolicy found; create one first",
                                 live=live)
            return Result(requeue_after=REQUEUE_NOT_READY_S)
        policy_spec = TPUClusterPolicySpec.from_obj(policies[0])

        try:
            validate_node_selectors(self.client, cr)
        except ValidationError as e:
            set_nested(cr, STATE_NOT_READY, "status", "state")
            conditions.set_error(self.client, cr, "Conflict", str(e),
                                 live=live)
            return Result()  # user must fix the CR; no requeue loop

        spec = TPUDriverSpec.from_obj(cr)
        # full-cluster node LIST every reconcile: served from the informer
        # store when the manager runs a CachedClient, so pool partitioning
        # stays O(nodes) in-process instead of an apiserver round trip
        nodes = self.client.list("v1", "Node")
        pools = get_node_pools(nodes, restrict=spec.node_selector)

        ctx = SyncContext(client=self.client, policy=cr, spec=policy_spec,
                          namespace=self.namespace)
        renderer = Renderer(self.manifests_root / "state-libtpu-driver")
        desired = []
        for pool in pools:
            data = common_data(ctx, policy_spec.libtpu, "libtpu-driver",
                               "libtpu-installer")
            data["Image"] = resolve_image("libtpu-driver", spec,
                                          "libtpu-installer")
            data["InitContainerImage"] = (
                operator_init_image(ctx, data["Image"]) or data["Image"])
            data["UpdateStrategy"] = "OnDelete"
            data["InstallDir"] = spec.install_dir or "/home/kubernetes/bin"
            data["Channel"] = spec.channel or "stable"
            data["Name"] = f"tpu-libtpu-driver-{pool.name}"
            data["NodeSelector"] = {**data["NodeSelector"],
                                    data["DeployLabel"]: "true",
                                    **pool.selector}
            desired.extend(apply_common_config(
                renderer.render_objects(data), data))

        state_label = self._state_label(request.name)
        from ..state.operands import template_kinds

        applied = apply_objects(
            self.client, cr, state_label, desired, self.namespace,
            sweep_kinds=template_kinds(
                str(self.manifests_root / "state-libtpu-driver")))
        if not pools:
            set_nested(cr, STATE_NOT_READY, "status", "state")
            conditions.set_not_ready(self.client, cr, "NoMatchingNodes",
                                     "nodeSelector matches no TPU nodes",
                                     live=live)
            return Result(requeue_after=REQUEUE_NOT_READY_S)

        ok, msg = objects_ready(self.client, applied)
        if not ok:
            set_nested(cr, STATE_NOT_READY, "status", "state")
            conditions.set_not_ready(
                self.client, cr,
                conditions.REASON_OPERANDS_NOT_READY, msg, live=live)
            return Result(requeue_after=REQUEUE_NOT_READY_S)

        set_nested(cr, STATE_READY, "status", "state")
        conditions.set_ready(
            self.client, cr,
            f"libtpu ready on {len(pools)} pool(s): "
            + ", ".join(p.name for p in pools), live=live)
        log.info("TPUDriver %s ready across pools %s", request.name,
                 [p.name for p in pools])
        return Result()
