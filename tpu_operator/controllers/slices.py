"""Per-slice readiness rows for ``status.slices[]`` (VERDICT r4 #4).

Multi-host grouping already drives node pools, slice-config agreement
(topology/manager.py:145-156) and slice-unit upgrades
(upgrade_controller._upgrade_units), but the CR status only aggregated
per-state — a v5p-64 slice had no readable row. This module computes
one row per multi-host slice (slice identity via nodepool.slices_of,
the same key the upgrade controller groups by):

    {id, accelerator, topology, hosts, hostsValidated, validated,
     upgradeState}

A slice is ``validated`` only when EVERY host's validation-gate pod is
Ready — grouped readiness, the genuinely-new design SURVEY.md section 7
calls out (the reference never needed it; its per-node proofs are
independent). Host validation is read the same way the reference's
upgrade path reads it: from the validator pods
(validator/main.go:151 "app=nvidia-operator-validator" analog) — both
gate apps, since isolated/virtual nodes run tpu-isolated-validator.
Terminating pods don't count: a dying validator's Ready=True is the OLD
proof, not a re-validation (same rule as the upgrade controller's
validation gate).

Single-host pools are deliberately NOT listed: their readiness is
already the per-state status, and one row per node would bloat
``status`` on large clusters. Rows are capped for the same reason.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Iterable, List, Optional

from ..api import labels as L
from ..api.conditions import update_status_with_retry
from ..api.slicerequest import (
    INTENT_MIGRATE,
    KIND_SLICE_REQUEST,
    MIG_ABORTED,
    MIG_CHECKPOINTED,
    MIG_MIGRATING,
    MIG_REBOUND,
    MIG_RESHARDING,
    MIG_RESUMED,
    MIG_TERMINAL,
    PHASE_PLACED,
    V1ALPHA1,
    SliceRequestSpec,
)
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime.client import Client, ListOptions
from ..runtime.timeline import TIMELINE
from ..runtime.objects import (
    annotations_of,
    get_nested,
    labels_of,
    name_of,
    namespace_of,
    pod_ready,
    set_nested,
    thaw_obj,
)
from ..state.nodepool import get_node_pools, slices_of

log = logging.getLogger("tpu_operator.slices")

MAX_ROWS = 100  # status-size bound; rows are sorted, so truncation is stable

# upgrade-state severity for the per-slice aggregate: the row shows the
# most in-need-of-attention member state (failed dominates; done only
# when every labeled member is done)
_SEVERITY = ("failed", "drain-required", "migrate-required",
             "cordon-required", "pod-restart-required",
             "validation-required", "uncordon-required",
             "upgrade-required", "done")


def _aggregate_upgrade_state(states: List[str]) -> str:
    present = [s for s in states if s]
    if not present:
        return ""
    for sev in _SEVERITY:
        if sev in present:
            return sev
    return present[0]  # unknown label value: surface it verbatim


def _validated_hosts(client: Client, namespace: str) -> set:
    from .upgrade_controller import UpgradeReconciler

    out = set()
    for app in UpgradeReconciler.VALIDATOR_APPS:
        for pod in client.list("v1", "Pod",
                               ListOptions(namespace=namespace,
                                           label_selector={"app": app})):
            if get_nested(pod, "metadata", "deletionTimestamp"):
                continue
            if pod_ready(pod):
                node = get_nested(pod, "spec", "nodeName")
                if node:
                    out.add(node)
    return out


def slice_status(client: Client, namespace: str,
                 nodes: Optional[List[dict]] = None) -> List[dict]:
    """Rows for ``status.slices[]``; empty when no multi-host pool
    exists. Pass ``nodes`` when the caller already holds the node list —
    the reconcile loop must not re-list the cluster for each consumer.
    Returns the FULL sorted row list; the CR writer applies the
    MAX_ROWS status-size cap, so gauge/alert consumers still see every
    slice (a truncated count would hide an unvalidated slice whose id
    sorts past the cap)."""
    if nodes is None:
        nodes = client.list("v1", "Node")
    by_name = {name_of(n): n for n in nodes}
    pools = [p for p in get_node_pools(nodes) if p.multi_host]
    if not pools:
        return []
    validated = _validated_hosts(client, namespace)
    rows: List[dict] = []
    for pool in pools:
        for slice_id, members in slices_of(pool, by_name).items():
            n_ok = sum(1 for m in members if m in validated)
            rows.append({
                "id": slice_id,
                "accelerator": pool.accelerator,
                "topology": pool.topology,
                "hosts": len(members),
                "hostsValidated": n_ok,
                "validated": n_ok == len(members),
                "upgradeState": _aggregate_upgrade_state(
                    [labels_of(by_name[m]).get(L.UPGRADE_STATE, "")
                     for m in members]),
            })
    rows.sort(key=lambda r: r["id"])
    return rows


# --- elastic-slice protocol (slice-intent contract) ------------------------
#
# The handshake, driven from both ends:
#
#   operator                        workload shim (workloads/elastic.py)
#   --------                        ------------------------------------
#   post intent annotation +        sees intent, checkpoints at the next
#   status.migration Migrating ->   step boundary, acks the durable step
#                                   (annotation + status Checkpointed) ->
#   leases replacement window,
#   rewrites the binding
#   (status Rebound) ->             restores the acked step on the new
#                                   topology (status Resumed)
#
# Past the deadline the operator aborts the attempt (status Aborted,
# outcome=timeout) and degrades to the pre-elastic hard drain — the
# workload loses only un-ACKED steps, never acknowledged ones. All
# timestamps flow through an injectable clock so the chaos plane drives
# the whole protocol off its virtual clock and verdicts stay
# byte-identical per seed.


def request_key(cr: dict) -> str:
    return f"{namespace_of(cr) or 'default'}/{name_of(cr)}"


def migration_of(cr: dict) -> dict:
    return dict(get_nested(cr, "status", "migration", default={}) or {})


def _fmt_ts(ts: float) -> str:
    return f"{float(ts):.3f}"


def placed_requests_on(client: Client, node_names: Iterable[str]) -> List[dict]:
    """Placed SliceRequests whose binding intersects ``node_names``,
    sorted by key for deterministic processing order."""
    wanted = set(node_names)
    out = []
    for cr in client.list(V1ALPHA1, KIND_SLICE_REQUEST):
        if get_nested(cr, "status", "phase") != PHASE_PLACED:
            continue
        bound = get_nested(cr, "status", "nodes", default=[]) or []
        if wanted.intersection(bound):
            out.append(cr)
    out.sort(key=request_key)
    return out


def clear_intent(client: Client, cr: dict) -> None:
    client.patch(
        V1ALPHA1, KIND_SLICE_REQUEST, name_of(cr),
        {"metadata": {"annotations": {L.SLICE_INTENT: None,
                                      L.SLICE_INTENT_DEADLINE: None,
                                      L.SLICE_INTENT_ACK: None}}},
        namespace=namespace_of(cr))


def post_intent(client: Client, cr: dict, live: dict, intent: str,
                deadline: float, now: float,
                extra: Optional[dict] = None) -> None:
    """Open a migration attempt: intent annotations first (the workload's
    trigger), then status.migration (the observable phase)."""
    key = request_key(cr)
    client.patch(
        V1ALPHA1, KIND_SLICE_REQUEST, name_of(cr),
        {"metadata": {"annotations": {
            L.SLICE_INTENT: intent,
            L.SLICE_INTENT_DEADLINE: _fmt_ts(deadline),
            L.SLICE_INTENT_ACK: None}}},
        namespace=namespace_of(cr))
    mig = {
        "phase": MIG_MIGRATING,
        "intent": intent,
        "deadline": _fmt_ts(deadline),
        "startedAt": _fmt_ts(now),
        "from": sorted(get_nested(cr, "status", "nodes", default=[]) or []),
    }
    mig.update(extra or {})
    set_nested(cr, mig, "status", "migration")
    update_status_with_retry(client, cr, live=live)
    if TIMELINE.enabled:
        TIMELINE.record("SliceRequest", key, "migration:" + MIG_MIGRATING,
                        {"intent": intent, "deadline": _fmt_ts(deadline),
                         "from": mig["from"]})
    log.info("posted %s intent on %s (deadline %s)", intent, key,
             _fmt_ts(deadline))


def abort_migration(client: Client, cr: dict, live: dict, reason: str,
                    outcome: str, extra: Optional[dict] = None) -> None:
    """Retire the current attempt; the hard-drain (or the unchanged
    binding, for a resize) is the degradation the caller falls back to.
    Intent annotations are kept so the attempt stays idempotent within
    its deadline window — a fresh attempt posts a fresh deadline."""
    mig = migration_of(cr)
    mig["phase"] = MIG_ABORTED
    mig["reason"] = reason
    mig.update(extra or {})
    mig.pop("to", None)
    set_nested(cr, mig, "status", "migration")
    update_status_with_retry(client, cr, live=live)
    OPERATOR_METRICS.slice_migrations.labels(outcome=outcome).inc()
    if TIMELINE.enabled:
        TIMELINE.record("SliceRequest", request_key(cr),
                        "migration:" + MIG_ABORTED,
                        {"outcome": outcome, "reason": reason})
    log.warning("migration of %s aborted (%s): %s",
                request_key(cr), outcome, reason)


def _move_binding(client: Client, cr: dict, live: dict,
                  spec: SliceRequestSpec, candidate, now: float,
                  outcome: str, phase: str,
                  mig_extra: Optional[dict] = None) -> None:
    """Move a Placed binding onto ``candidate``'s window: lease the new
    nodes BEFORE publishing status (placement-sound, same order as the
    initial bind), then release the leases left behind. A crash between
    status and release leaves orphan self-leases, which the placement
    controller's Placed-sound sweep reclaims. ``phase`` is Rebound for
    the full-checkpoint path, Resharding for the direct shard handoff —
    the workload's restore strategy keys off it."""
    key = request_key(cr)
    old = set(get_nested(cr, "status", "nodes", default=[]) or [])
    new = set(candidate.nodes)
    for n in sorted(new):
        client.patch("v1", "Node", n,
                     {"metadata": {"annotations": {L.PLACED_BY: key}}})
    mig = migration_of(cr)
    mig["phase"] = phase
    mig["to"] = sorted(new)
    mig.pop("reason", None)
    mig.update(mig_extra or {})
    set_nested(cr, mig, "status", "migration")
    set_nested(cr, sorted(new), "status", "nodes")
    set_nested(cr, candidate.pool, "status", "pool")
    set_nested(cr, candidate.slice_id, "status", "sliceId")
    set_nested(cr, f"{candidate.score:.6f}", "status", "score")
    set_nested(cr, spec.chips_needed(), "status", "chips")
    set_nested(cr, int(get_nested(cr, "status", "migrations",
                                  default=0) or 0) + 1,
               "status", "migrations")
    update_status_with_retry(client, cr, live=live)
    for n in sorted(old - new):
        node = client.get_or_none("v1", "Node", n)
        if node is not None and annotations_of(node).get(L.PLACED_BY) == key:
            client.patch("v1", "Node", n,
                         {"metadata": {"annotations": {L.PLACED_BY: None}}})
    clear_intent(client, cr)
    OPERATOR_METRICS.slice_migrations.labels(outcome=outcome).inc()
    if TIMELINE.enabled:
        TIMELINE.record("SliceRequest", key, "migration:" + phase,
                        {"outcome": outcome, "pool": candidate.pool,
                         "score": f"{candidate.score:.6f}",
                         "from": sorted(old), "to": sorted(new)})
    started = mig.get("startedAt")
    if started:
        OPERATOR_METRICS.slice_migration_duration.observe(
            max(0.0, now - float(started)))
    log.info("request %s rebound %s -> %s (%s)", key,
             sorted(old), sorted(new), outcome)


def rebind_request(client: Client, cr: dict, live: dict,
                   spec: SliceRequestSpec, candidate, now: float,
                   outcome: str) -> None:
    """The full-checkpoint rebind: every byte of the acked checkpoint is
    restored on the new binding. Stamps path=full-checkpoint so the CLI
    can show which road a completed move took."""
    _move_binding(client, cr, live, spec, candidate, now, outcome,
                  phase=MIG_REBOUND,
                  mig_extra={"path": "full-checkpoint"})


def _handoff_ineligible(cr: dict, candidate) -> Optional[str]:
    """None when a direct shard handoff onto ``candidate`` is sound,
    else the fallback reason. Pure (no metrics, no I/O) so the resize
    path can also use it to PREFER a same-domain candidate: the exact-
    fit scorer routinely out-ranks a job's own window with a window in
    another pool, and for a resize the byte bill dominates the score
    margin. Sound means:

    - the sharded layout is enabled and the workload's ack published
      its shard map at the operator's layout version,
    - the candidate stays in the SAME ICI domain (same pool, at least
      one surviving host — a cross-domain or cross-cell move shares no
      interconnect, every shard travels anyway)."""
    from ..workloads.elastic import LAYOUT_VERSION, SHARDED_CKPT_GATE

    if not SHARDED_CKPT_GATE.enabled:
        return "disabled"
    layout = migration_of(cr).get("layout")
    if not layout or not layout.get("shards"):
        return "no-layout"
    if int(layout.get("version", -1)) != LAYOUT_VERSION:
        return "layout-version"
    old_nodes = set(get_nested(cr, "status", "nodes", default=[]) or [])
    if candidate.pool != get_nested(cr, "status", "pool") \
            or not old_nodes & set(candidate.nodes):
        return "cross-domain"
    return None


def handoff_eligible(cr: dict, candidate) -> bool:
    return _handoff_ineligible(cr, candidate) is None


def plan_handoff(cr: dict, candidate) -> Optional[dict]:
    """Fast-path eligibility + shard-movement plan for a resize onto
    ``candidate``. Returns the plan (bytes/shards accounted) only when
    the direct handoff is sound (see :func:`_handoff_ineligible`) and
    the planner can diff the layouts (no version skew, same shard set).
    Any mismatch returns None (counted by reason) and the caller rides
    the existing atomic full-checkpoint path — the fast path is an
    optimization, never a new failure mode."""
    import time as _time

    from ..workloads.elastic import plan_reshard, rebalance_layout

    def fallback(reason: str) -> None:
        OPERATOR_METRICS.reshard_fallbacks.labels(reason=reason).inc()

    reason = _handoff_ineligible(cr, candidate)
    if reason is not None:
        fallback(reason)
        return None
    layout = migration_of(cr).get("layout")
    t0 = _time.perf_counter()
    plan = plan_reshard(layout, rebalance_layout(layout, candidate.nodes))
    OPERATOR_METRICS.reshard_plan_seconds.observe(
        _time.perf_counter() - t0)
    if not plan["compatible"]:
        fallback("incompatible")
        return None
    return plan


def reshard_request(client: Client, cr: dict, live: dict,
                    spec: SliceRequestSpec, candidate, now: float,
                    plan: dict) -> None:
    """The same-domain direct shard handoff: surviving hosts keep their
    shards in place, only the planned moves travel. The binding move is
    the SAME placement-sound lease dance as a full rebind — only the
    phase (Resharding) and the byte bill differ; the workload's restore
    fetches exactly the planned shards and falls back to the full
    restore on any torn manifest."""
    _move_binding(client, cr, live, spec, candidate, now,
                  outcome="resharded", phase=MIG_RESHARDING,
                  mig_extra={"path": "sharded-handoff",
                             "bytesMoved": int(plan["bytesMoved"]),
                             "shardsMoved": int(plan["shardsMoved"])})
    OPERATOR_METRICS.reshard_bytes_moved.inc(int(plan["bytesMoved"]))
    OPERATOR_METRICS.reshard_shard_handoffs.inc(int(plan["shardsMoved"]))


class SliceMigrator:
    """Drives the migrate half of the protocol for the upgrade FSM.

    Stateless across passes — every decision is recomputed from the
    cluster, so a controller restart mid-handshake resumes where the
    annotations/status say it left off. ``ready_to_drain`` returns True
    only when every placed request on the unit has either rebound onto
    replacement capacity or exhausted its deadline (hard-drain
    degradation)."""

    def __init__(self, client: Client, now: Callable[[], float] = time.time):
        self.client = client
        self.now = now

    def ready_to_drain(self, unit_nodes: List[str], deadline: float) -> bool:
        ready = True
        for live in placed_requests_on(self.client, unit_nodes):
            if not self._advance_one(live, unit_nodes, deadline):
                ready = False
        return ready

    def _advance_one(self, live: dict, unit_nodes: List[str],
                     deadline: float) -> bool:
        cr = thaw_obj(live)
        key = request_key(cr)
        anns = annotations_of(cr)
        phase = migration_of(cr).get("phase", "")
        intent = anns.get(L.SLICE_INTENT)
        try:
            raw = anns.get(L.SLICE_INTENT_DEADLINE)
            ann_deadline = float(raw) if raw is not None else None
        except (TypeError, ValueError):
            ann_deadline = None
        live_attempt = (intent is not None and ann_deadline is not None
                        and self.now() <= ann_deadline)
        if not live_attempt:
            # an expired attempt still mid-phase degrades to the hard
            # drain right now; otherwise open a fresh attempt for THIS
            # drain (unless the workload opted out of the handshake, or
            # our own window is already gone)
            if intent is not None and phase not in MIG_TERMINAL:
                abort_migration(self.client, cr, live,
                                "migration deadline exceeded; hard drain",
                                outcome="timeout")
                return True
            if anns.get(L.SLICE_ELASTIC) == "false":
                return True
            if self.now() > deadline:
                return True
            post_intent(self.client, cr, live, INTENT_MIGRATE,
                        deadline, self.now())
            return False
        # an attempt is live — ours, a sibling upgrade unit's (a request
        # spanning two draining units), or a concurrent resize. The SAME
        # phase machine drives all of them off the ANNOTATION's deadline,
        # so two units sharing a request never ping-pong reposts
        if phase in (MIG_REBOUND, MIG_RESHARDING, MIG_RESUMED, MIG_ABORTED):
            return True
        if phase == MIG_CHECKPOINTED:
            from .placement_controller import find_replacement

            spec = SliceRequestSpec.from_obj(cr)
            cand = find_replacement(self.client, spec, key,
                                    exclude=unit_nodes)
            if cand is not None:
                rebind_request(self.client, cr, live, spec, cand,
                               self.now(), outcome="migrated")
                return True
        return False
