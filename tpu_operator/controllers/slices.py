"""Per-slice readiness rows for ``status.slices[]`` (VERDICT r4 #4).

Multi-host grouping already drives node pools, slice-config agreement
(topology/manager.py:145-156) and slice-unit upgrades
(upgrade_controller._upgrade_units), but the CR status only aggregated
per-state — a v5p-64 slice had no readable row. This module computes
one row per multi-host slice (slice identity via nodepool.slices_of,
the same key the upgrade controller groups by):

    {id, accelerator, topology, hosts, hostsValidated, validated,
     upgradeState}

A slice is ``validated`` only when EVERY host's validation-gate pod is
Ready — grouped readiness, the genuinely-new design SURVEY.md section 7
calls out (the reference never needed it; its per-node proofs are
independent). Host validation is read the same way the reference's
upgrade path reads it: from the validator pods
(validator/main.go:151 "app=nvidia-operator-validator" analog) — both
gate apps, since isolated/virtual nodes run tpu-isolated-validator.
Terminating pods don't count: a dying validator's Ready=True is the OLD
proof, not a re-validation (same rule as the upgrade controller's
validation gate).

Single-host pools are deliberately NOT listed: their readiness is
already the per-state status, and one row per node would bloat
``status`` on large clusters. Rows are capped for the same reason.
"""

from __future__ import annotations

from typing import List, Optional

from ..api import labels as L
from ..runtime.client import Client, ListOptions
from ..runtime.objects import get_nested, labels_of, name_of, pod_ready
from ..state.nodepool import get_node_pools, slices_of

MAX_ROWS = 100  # status-size bound; rows are sorted, so truncation is stable

# upgrade-state severity for the per-slice aggregate: the row shows the
# most in-need-of-attention member state (failed dominates; done only
# when every labeled member is done)
_SEVERITY = ("failed", "drain-required", "cordon-required",
             "pod-restart-required", "validation-required",
             "uncordon-required", "upgrade-required", "done")


def _aggregate_upgrade_state(states: List[str]) -> str:
    present = [s for s in states if s]
    if not present:
        return ""
    for sev in _SEVERITY:
        if sev in present:
            return sev
    return present[0]  # unknown label value: surface it verbatim


def _validated_hosts(client: Client, namespace: str) -> set:
    from .upgrade_controller import UpgradeReconciler

    out = set()
    for app in UpgradeReconciler.VALIDATOR_APPS:
        for pod in client.list("v1", "Pod",
                               ListOptions(namespace=namespace,
                                           label_selector={"app": app})):
            if get_nested(pod, "metadata", "deletionTimestamp"):
                continue
            if pod_ready(pod):
                node = get_nested(pod, "spec", "nodeName")
                if node:
                    out.add(node)
    return out


def slice_status(client: Client, namespace: str,
                 nodes: Optional[List[dict]] = None) -> List[dict]:
    """Rows for ``status.slices[]``; empty when no multi-host pool
    exists. Pass ``nodes`` when the caller already holds the node list —
    the reconcile loop must not re-list the cluster for each consumer.
    Returns the FULL sorted row list; the CR writer applies the
    MAX_ROWS status-size cap, so gauge/alert consumers still see every
    slice (a truncated count would hide an unvalidated slice whose id
    sorts past the cap)."""
    if nodes is None:
        nodes = client.list("v1", "Node")
    by_name = {name_of(n): n for n in nodes}
    pools = [p for p in get_node_pools(nodes) if p.multi_host]
    if not pools:
        return []
    validated = _validated_hosts(client, namespace)
    rows: List[dict] = []
    for pool in pools:
        for slice_id, members in slices_of(pool, by_name).items():
            n_ok = sum(1 for m in members if m in validated)
            rows.append({
                "id": slice_id,
                "accelerator": pool.accelerator,
                "topology": pool.topology,
                "hosts": len(members),
                "hostsValidated": n_ok,
                "validated": n_ok == len(members),
                "upgradeState": _aggregate_upgrade_state(
                    [labels_of(by_name[m]).get(L.UPGRADE_STATE, "")
                     for m in members]),
            })
    rows.sort(key=lambda r: r["id"])
    return rows
