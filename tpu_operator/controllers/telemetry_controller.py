"""Node telemetry condition reconciler — the scorer's publishing arm.

metrics/fleet.py condemns and absolves nodes in memory from their
health-digest streams; this reconciler is the only writer of that
verdict into the cluster, as the ``TPUTelemetryHealthy`` node condition
(status "False" = condemned). Everything downstream — FleetState and
FleetIndex eligibility, the placement controller's ``_binding_broken``
drain — reads the condition, never the in-memory ledger, so a restarted
operator re-earns each condemnation from fresh streaks instead of
trusting stale state.

Rides the health lane: a digest edge must not pool behind bulk churn.
Writes follow the zero-write steady state — a node whose condition
already matches the scorer costs the apiserver nothing, and a node that
was never condemned never gains the condition at all (the fleet's
steady state is condition-free, not fleet-wide "True" stamps).
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as L
from ..api.conditions import update_status_with_retry
from ..metrics.fleet import FLEET_TELEMETRY, FleetTelemetry
from ..runtime import (
    LANE_HEALTH,
    Controller,
    Manager,
    Reconciler,
    Request,
    Result,
    WatchEvent,
)
from ..runtime.objects import (
    annotations_of,
    get_nested,
    labels_of,
    name_of,
    set_nested,
    thaw_obj,
)


def _condition_of(node: dict) -> Optional[dict]:
    for c in get_nested(node, "status", "conditions", default=[]) or []:
        if c.get("type") == L.TELEMETRY_CONDITION:
            return c
    return None


def _node_telemetry_changed(event: WatchEvent,
                            old: Optional[dict]) -> bool:
    """React to digest publishes and condition flips only — lease
    echoes and label churn never wake this reconciler."""
    if event.type in ("ADDED", "DELETED") or old is None:
        return True

    def facet(n):
        cond = _condition_of(n) or {}
        return (annotations_of(n).get(L.HEALTH_DIGEST),
                cond.get("status"), cond.get("message"))

    return facet(event.obj) != facet(old)


class TelemetryReconciler(Reconciler):
    name = "telemetry"
    primary_kind = "Node"

    def __init__(self, client, telemetry: Optional[FleetTelemetry] = None):
        self.client = client
        self.telemetry = FLEET_TELEMETRY if telemetry is None else telemetry

    def setup_controller(self, controller: Controller, manager: Manager):
        controller.watch("v1", "Node",
                         predicate=_node_telemetry_changed,
                         lane=LANE_HEALTH)

    def reconcile(self, request: Request) -> Result:
        live = self.client.get_or_none("v1", "Node", request.name)
        if live is None:
            return Result()
        if L.GKE_TPU_ACCELERATOR not in labels_of(live):
            return Result()
        name = name_of(live)
        condemned = self.telemetry.is_condemned(name)
        current = _condition_of(live)
        if condemned:
            want = {"type": L.TELEMETRY_CONDITION, "status": "False",
                    "reason": "TelemetryCondemned",
                    "message": (f"condemned after "
                                f"{self.telemetry.condemn_after} "
                                "consecutive FAIL digests")}
        elif current is not None:
            # absolved (or scorer state lost to a restart and not yet
            # re-earned): flip to True rather than delete, so the
            # recovery is visible in the condition history
            want = {"type": L.TELEMETRY_CONDITION, "status": "True",
                    "reason": "TelemetryHealthy",
                    "message": "digest stream healthy"}
        else:
            return Result()
        if current == want:
            return Result()
        node = thaw_obj(live)
        conds = [c for c in get_nested(node, "status", "conditions",
                                       default=[]) or []
                 if c.get("type") != L.TELEMETRY_CONDITION]
        conds.append(want)
        set_nested(node, conds, "status", "conditions")
        update_status_with_retry(self.client, node, live=live)
        return Result()
