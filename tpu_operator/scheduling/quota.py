"""Fair-share admission: hierarchical quota tree + starvation accounting.

The PR 10 batched gang pass drains Pending in strict priority/age order,
which is correct when capacity is ample and starvation-prone at the
oversubscribed steady state. Gavel (PAPERS.md) shows fairness has to be
an allocation *policy*, not a queue ordering tweak — this module is that
policy layer, sitting between the workqueue and the gang pass:

- ``QuotaTree``: a hierarchical quota config (TPUQuota CRD or the
  ``tpu-operator-quota`` ConfigMap) mapping every SliceRequest to a leaf
  class with weight, min-guarantee and max-cap. Shares are computed by
  iterative weighted water-filling per tree level, so a capped or
  demand-light class's leftover is *borrowed* by its siblings.
- ``order_batch``: pluggable batch-ordering strategies over one gang
  pass — ``priority`` (the legacy priority/age baseline, the kill
  switch), ``finish-time`` (least attained chips per unit weight first)
  and ``throughput`` (least attained chips x generation-peak-TFLOPs per
  unit weight first), selected by ``OPERATOR_ADMISSION_POLICY``.
- ``AdmissionState``: per-class deficit clocks (time a class has sat
  below its min-guarantee floor with work queued) and preemption-budget
  token buckets (how many preemptions a class may *suffer* per window).
  Both persist in the durable snapshot so an operator crash never resets
  starvation accounting.

Everything is a pure function of (config, cluster state, injected
clock): the chaos plane drives it off the virtual clock and verdicts
stay byte-identical per seed.
"""

from __future__ import annotations

import calendar
import json
import logging
import math
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import labels as L
from ..runtime.objects import annotations_of, get_nested, name_of, namespace_of
from ..workloads.hardware import CHIPS

log = logging.getLogger("tpu_operator.quota")

DEFAULT_CLASS = "default"
QUOTA_CONFIGMAP = "tpu-operator-quota"
QUOTA_CONFIG_KEY = "quota.json"
KIND_TPU_QUOTA = "TPUQuota"
V1ALPHA1 = "tpu.graft.dev/v1alpha1"

POLICY_BASELINE = "priority"
POLICY_FINISH_TIME = "finish-time"
POLICY_THROUGHPUT = "throughput"
POLICIES = (POLICY_BASELINE, POLICY_FINISH_TIME, POLICY_THROUGHPUT)

# generation-peak TFLOPs for throughput-normalized allocation; unknown
# generations rate as 1.0 chip-equivalent so they still count as service
_GEN_TFLOPS = {gen: spec.peak_bf16_tflops for gen, spec in CHIPS.items()}


def env_admission_policy(env: Optional[dict] = None) -> str:
    """``OPERATOR_ADMISSION_POLICY``: priority (default, the kill
    switch) | finish-time | throughput. Unknown values fall back to the
    baseline rather than failing the controller."""
    src = os.environ if env is None else env
    v = (src.get("OPERATOR_ADMISSION_POLICY") or POLICY_BASELINE).strip()
    return v if v in POLICIES else POLICY_BASELINE


class AdmissionGate:
    """Read once at import (same pattern as PlacementIndexGate) so a
    single reconcile pass never straddles two policies; tests override
    the attribute directly."""

    def __init__(self):
        self.policy = env_admission_policy()


ADMISSION_GATE = AdmissionGate()


# --- deterministic priority/age baseline ------------------------------------

def created_epoch(cr: dict) -> float:
    """``metadata.creationTimestamp`` as epoch seconds. The legacy sort
    compared the RAW strings, which breaks total order as soon as two
    API clients serialize differently (fractional seconds, ``+00:00``
    offsets) — clock skew in disguise. Unparseable stamps sort last
    (+inf) and fall through to the (namespace, name) tie-break."""
    raw = str(get_nested(cr, "metadata", "creationTimestamp",
                         default="") or "")
    if not raw:
        return math.inf
    s = raw.strip()
    if s.endswith("Z"):
        s = s[:-1]
    elif s.endswith("+00:00"):
        s = s[:-6]
    frac = 0.0
    if "." in s:
        s, _, fpart = s.partition(".")
        try:
            frac = float("0." + fpart)
        except ValueError:
            frac = 0.0
    try:
        return calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%S")) + frac
    except (ValueError, OverflowError):
        return math.inf


def baseline_key(key: str, cr: dict, spec) -> Tuple:
    """The priority/age gang-pass order: higher priority first, then
    older first, then (namespace, name) so equal-priority same-second
    requests drain in one total deterministic order under clock skew."""
    ns, _, name = key.partition("/")
    return (-int(spec.priority or 0), created_epoch(cr), ns, name)


# --- quota tree -------------------------------------------------------------

@dataclass(frozen=True)
class QuotaClass:
    """One node of the quota tree. ``parent`` "" means a child of the
    implicit root. ``preempt_tokens`` bounds how many preemptions this
    class may *suffer* per ``preempt_window_s`` — 0 (the default) makes
    the class preemption-exempt."""

    name: str
    parent: str = ""
    weight: float = 1.0
    min_chips: int = 0
    max_chips: Optional[int] = None
    starvation_bound_s: float = math.inf
    preempt_tokens: int = 0
    preempt_window_s: float = 600.0

    @classmethod
    def from_doc(cls, doc: dict) -> "QuotaClass":
        bound = doc.get("starvationBoundSeconds")
        maxc = doc.get("maxChips")
        return cls(
            name=str(doc["name"]),
            parent=str(doc.get("parent") or ""),
            weight=max(0.0, float(doc.get("weight", 1.0))),
            min_chips=max(0, int(doc.get("minChips", 0))),
            max_chips=None if maxc is None else max(0, int(maxc)),
            starvation_bound_s=(math.inf if bound is None
                                else max(0.0, float(bound))),
            preempt_tokens=max(0, int(doc.get("preemptTokens", 0))),
            preempt_window_s=max(1.0, float(doc.get("preemptWindowSeconds",
                                                    600.0))),
        )


class QuotaTree:
    """The parsed quota hierarchy. A ``default`` leaf always exists
    (synthesized, unbounded, weight 1.0, no guarantees) so unclassified
    requests are never rejected by the admission layer."""

    def __init__(self, classes: List[QuotaClass]):
        by_name: Dict[str, QuotaClass] = {}
        for qc in classes:
            if qc.name in by_name:
                raise ValueError(f"duplicate quota class {qc.name!r}")
            by_name[qc.name] = qc
        for qc in classes:
            if qc.parent and qc.parent not in by_name:
                raise ValueError(
                    f"quota class {qc.name!r} parents unknown "
                    f"{qc.parent!r}")
        children: Dict[str, List[str]] = {"": []}
        for qc in classes:
            children.setdefault(qc.name, [])
            children.setdefault(qc.parent, []).append(qc.name)
        # cycle guard: every class must reach the root
        for qc in classes:
            seen, cur = set(), qc
            while cur.parent:
                if cur.parent in seen:
                    raise ValueError(f"quota tree cycle at {qc.name!r}")
                seen.add(cur.parent)
                cur = by_name[cur.parent]
        if DEFAULT_CLASS not in by_name:
            dq = QuotaClass(name=DEFAULT_CLASS)
            by_name[DEFAULT_CLASS] = dq
            children[""].append(DEFAULT_CLASS)
            children[DEFAULT_CLASS] = []
        self.by_name = by_name
        self.children = {k: sorted(v) for k, v in children.items()}

    def get(self, name: str) -> QuotaClass:
        return self.by_name.get(name) or self.by_name[DEFAULT_CLASS]

    def leaf_names(self) -> List[str]:
        return sorted(n for n, kids in self.children.items()
                      if n and not kids)

    def class_of(self, cr: dict) -> str:
        """Leaf class of one SliceRequest: the explicit
        ``tpu.graft.dev/quota-class`` annotation wins, then a leaf named
        after the request's namespace, then ``default``."""
        leaves = set(self.leaf_names())
        ann = annotations_of(cr).get(L.QUOTA_CLASS)
        if ann and ann in leaves:
            return ann
        ns = namespace_of(cr) or ""
        if ns in leaves:
            return ns
        return DEFAULT_CLASS

    # -- share math ---------------------------------------------------------

    def shares(self, capacity: int,
               demand: Dict[str, int]) -> Dict[str, int]:
        """Fair share per LEAF class for ``capacity`` chips given
        per-leaf ``demand``: weighted water-fill per tree level with
        min-guarantee and max-cap clamping; leftover from capped or
        demand-light classes is borrowed by unsatisfied siblings."""
        eff: Dict[str, int] = {}

        def subtree_demand(name: str) -> int:
            kids = self.children.get(name, [])
            if not kids:
                d = max(0, int(demand.get(name, 0)))
            else:
                d = sum(subtree_demand(k) for k in kids)
            qc = self.by_name.get(name)
            if qc is not None and qc.max_chips is not None:
                d = min(d, qc.max_chips)
            eff[name] = d
            return d

        for top in self.children.get("", []):
            subtree_demand(top)
        out: Dict[str, int] = {}

        def distribute(avail: int, names: List[str]) -> None:
            alloc = {n: 0 for n in names}
            # min guarantees first (never above effective demand); when
            # mins oversubscribe capacity, grant in sorted-name order so
            # the outcome is total and deterministic
            for n in sorted(names):
                want = min(self.by_name[n].min_chips, eff[n])
                give = min(want, avail)
                alloc[n] += give
                avail -= give
            # weighted fill with borrow: classes at cap/demand drop out,
            # the rest absorb the remainder; sub-chip remainders hand
            # out one chip at a time in sorted-name order
            guard = 0
            while avail > 0 and guard < 10_000:
                guard += 1
                open_ = [n for n in sorted(names) if alloc[n] < eff[n]
                         and self.by_name[n].weight > 0]
                if not open_:
                    break
                total_w = sum(self.by_name[n].weight for n in open_)
                gave = 0
                for n in open_:
                    fair = int(avail * self.by_name[n].weight / total_w)
                    give = min(max(fair, 0), eff[n] - alloc[n])
                    alloc[n] += give
                    gave += give
                if gave == 0:
                    for n in open_:
                        if avail <= 0:
                            break
                        alloc[n] += 1
                        avail -= 1
                    break
                avail -= gave
            for n in names:
                kids = self.children.get(n, [])
                if kids:
                    distribute(alloc[n], kids)
                else:
                    out[n] = alloc[n]

        distribute(max(0, int(capacity)), self.children.get("", []))
        for leaf in self.leaf_names():
            out.setdefault(leaf, 0)
        return out

    # -- config loading -----------------------------------------------------

    @classmethod
    def from_config(cls, doc: dict) -> "QuotaTree":
        rows = doc.get("classes")
        if not isinstance(rows, list) or not rows:
            raise ValueError("quota config needs a non-empty 'classes' list")
        return cls([QuotaClass.from_doc(r) for r in rows])

    @classmethod
    def load(cls, client, namespace: str) -> Optional["QuotaTree"]:
        """The TPUQuota CRD wins over the ConfigMap; neither present (or
        unparseable — a bad config must not take placement down) means
        no quota: the admission layer is a strict no-op."""
        try:
            for obj in client.list(V1ALPHA1, KIND_TPU_QUOTA):
                spec = get_nested(obj, "spec", default={}) or {}
                if spec.get("classes"):
                    return cls.from_config(dict(spec))
        except Exception:
            pass
        try:
            cm = client.get_or_none("v1", "ConfigMap", QUOTA_CONFIGMAP,
                                    namespace)
        except Exception:
            cm = None
        if cm is None:
            return None
        raw = (get_nested(cm, "data", default={}) or {}).get(
            QUOTA_CONFIG_KEY)
        if not raw:
            return None
        try:
            return cls.from_config(json.loads(raw))
        except (ValueError, TypeError) as e:
            log.warning("ignoring unparseable quota config: %s", e)
            return None


# --- per-class deficit clocks and preemption budgets ------------------------

@dataclass
class AdmissionState:
    """The only mutable admission state. ``deficit_since`` anchors the
    per-class starvation clock at the moment the class dropped below its
    min-guarantee floor with work queued; ``tokens``/``window_start``
    are the preemption budget buckets. All plain JSON scalars so the
    snapshot plane persists it verbatim (schema v3)."""

    deficit_since: Dict[str, float] = field(default_factory=dict)
    tokens: Dict[str, float] = field(default_factory=dict)
    window_start: Dict[str, float] = field(default_factory=dict)

    def observe(self, tree: QuotaTree, usage: Dict[str, int],
                queued: Dict[str, int], now: float) -> Dict[str, float]:
        """Advance every leaf's deficit clock; returns class -> current
        deficit seconds. A class is starving while it has queued demand
        AND sits below ``min(min_chips, usage + queued)`` — the floor a
        min-guarantee entitles it to given what it actually wants."""
        deficits: Dict[str, float] = {}
        for name in tree.leaf_names():
            qc = tree.get(name)
            use = max(0, int(usage.get(name, 0)))
            q = max(0, int(queued.get(name, 0)))
            floor = min(qc.min_chips, use + q)
            if q > 0 and use < floor:
                since = self.deficit_since.setdefault(name, float(now))
                deficits[name] = max(0.0, float(now) - since)
            else:
                self.deficit_since.pop(name, None)
                deficits[name] = 0.0
        return deficits

    def _roll(self, qc: QuotaClass, now: float) -> None:
        start = self.window_start.get(qc.name)
        if start is None or float(now) - start >= qc.preempt_window_s:
            self.window_start[qc.name] = float(now)
            self.tokens[qc.name] = float(qc.preempt_tokens)

    def remaining(self, qc: QuotaClass, now: float) -> float:
        self._roll(qc, now)
        return max(0.0, self.tokens.get(qc.name, 0.0))

    def take_token(self, qc: QuotaClass, now: float) -> bool:
        """Consume one preemption token from ``qc``'s bucket (the class
        about to SUFFER the preemption); False when the window budget is
        exhausted — the caller must not preempt."""
        if self.remaining(qc, now) < 1.0:
            return False
        self.tokens[qc.name] -= 1.0
        return True

    def to_dict(self) -> dict:
        return {
            "deficit_since": {k: float(v)
                              for k, v in sorted(self.deficit_since.items())},
            "tokens": {k: float(v) for k, v in sorted(self.tokens.items())},
            "window_start": {k: float(v)
                             for k, v in sorted(self.window_start.items())},
        }

    @classmethod
    def from_dict(cls, doc: Optional[dict]) -> "AdmissionState":
        doc = doc or {}

        def _m(key):
            out = {}
            for k, v in (doc.get(key) or {}).items():
                try:
                    out[str(k)] = float(v)
                except (TypeError, ValueError):
                    continue
            return out

        return cls(deficit_since=_m("deficit_since"), tokens=_m("tokens"),
                   window_start=_m("window_start"))


# --- batch ordering policies ------------------------------------------------

def _item_cost(spec, policy: str, dominant_tflops: float) -> float:
    chips = max(1, int(spec.chips_needed() or 1))
    if policy == POLICY_THROUGHPUT:
        return chips * max(1.0, dominant_tflops)
    return float(chips)


def order_batch(items: List[tuple], policy: str,
                tree: Optional[QuotaTree],
                usage: Optional[Dict[str, int]] = None,
                usage_tflops: Optional[Dict[str, float]] = None,
                dominant_tflops: float = 1.0) -> List[tuple]:
    """Order one gang-pass batch of ``(key, cr, live, spec)`` items.

    ``priority`` (or no quota tree) returns the batch UNCHANGED — the
    caller already drains in baseline order, which keeps the kill switch
    byte-identical to the legacy gang pass. The fair policies interleave
    classes least-attained-first: pick the class with the smallest
    attained-service-per-weight, admit its best item, charge the class
    for it, repeat — finish-time fairness measured in chips, throughput
    fairness in chips x generation-peak-TFLOPs."""
    if policy == POLICY_BASELINE or tree is None or len(items) <= 1:
        return list(items)
    attained: Dict[str, float] = {}
    base = usage_tflops if policy == POLICY_THROUGHPUT else usage
    for name in tree.leaf_names():
        qc = tree.get(name)
        w = qc.weight if qc.weight > 0 else 1e-9
        attained[name] = float((base or {}).get(name, 0.0)) / w
    queues: Dict[str, List[tuple]] = {}
    for item in items:
        key, cr, _live, _spec = item
        queues.setdefault(tree.class_of(cr), []).append(item)
    for name, q in queues.items():
        q.sort(key=lambda it: baseline_key(it[0], it[1], it[3]))
        attained.setdefault(name, 0.0)
    out: List[tuple] = []
    while any(queues.values()):
        name = min((n for n in sorted(queues) if queues[n]),
                   key=lambda n: (attained[n], n))
        item = queues[name].pop(0)
        out.append(item)
        qc = tree.get(name)
        w = qc.weight if qc.weight > 0 else 1e-9
        attained[name] += _item_cost(item[3], policy, dominant_tflops) / w
    return out


# --- shared quota report (CLI `tpuop-cfg quota`, /debug/quota) --------------

def _capacity_chips(nodes) -> int:
    """TPU chips the placement engine could ever offer, using the SAME
    per-node chip extraction the scorer uses (lazy import — topology
    pulls in the scoring stack)."""
    from ..topology.placement import _node_chips

    return sum(max(0, int(_node_chips(n) or 0)) for n in nodes)


def quota_report(client, namespace: str,
                 tree: Optional[QuotaTree] = None,
                 state: Optional[AdmissionState] = None,
                 policy: Optional[str] = None,
                 now: Optional[Callable[[], float]] = None) -> dict:
    """The quota explainer document: per-leaf usage/queued/share/deficit
    /budget plus the breached list. Pure function of the cluster (tree
    and live admission state optional — a must-gather has neither, so
    deficits render as unknown there, never as fabricated zeros)."""
    from ..api.slicerequest import (KIND_SLICE_REQUEST, PHASE_PLACED,
                                    V1ALPHA1 as SR_API)

    if tree is None:
        tree = QuotaTree.load(client, namespace)
    if tree is None:
        return {"configured": False, "classes": [], "breached": [],
                "policy": policy or ADMISSION_GATE.policy}
    clock = now or time.time
    t = float(clock())
    usage: Dict[str, int] = {}
    queued: Dict[str, int] = {}
    queued_requests: Dict[str, int] = {}
    for cr in client.list(SR_API, KIND_SLICE_REQUEST):
        cls_name = tree.class_of(cr)
        phase = get_nested(cr, "status", "phase", default="") or ""
        if phase == PHASE_PLACED:
            usage[cls_name] = usage.get(cls_name, 0) + int(
                get_nested(cr, "status", "chips", default=0) or 0)
        else:
            from ..api.slicerequest import SliceRequestSpec

            spec = SliceRequestSpec.from_obj(cr)
            queued[cls_name] = (queued.get(cls_name, 0)
                                + int(spec.chips_needed() or 0))
            queued_requests[cls_name] = queued_requests.get(cls_name, 0) + 1
    capacity = _capacity_chips(client.list("v1", "Node"))
    demand = {n: usage.get(n, 0) + queued.get(n, 0)
              for n in tree.leaf_names()}
    shares = tree.shares(capacity, demand)
    deficits = (state.observe(tree, usage, queued, t)
                if state is not None else None)
    rows, breached = [], []
    for name in tree.leaf_names():
        qc = tree.get(name)
        row = {
            "class": name,
            "weight": qc.weight,
            "minChips": qc.min_chips,
            "maxChips": qc.max_chips,
            "usageChips": usage.get(name, 0),
            "queuedChips": queued.get(name, 0),
            "queuedRequests": queued_requests.get(name, 0),
            "shareChips": shares.get(name, 0),
            "starvationBoundSeconds": (
                None if math.isinf(qc.starvation_bound_s)
                else qc.starvation_bound_s),
            "preemptTokens": qc.preempt_tokens,
            "preemptWindowSeconds": qc.preempt_window_s,
        }
        if deficits is not None:
            row["deficitSeconds"] = round(deficits.get(name, 0.0), 3)
            row["tokensRemaining"] = state.remaining(qc, t)
            if deficits.get(name, 0.0) > qc.starvation_bound_s:
                row["starving"] = True
                breached.append(name)
        rows.append(row)
    return {"configured": True,
            "policy": policy or ADMISSION_GATE.policy,
            "capacityChips": capacity,
            "classes": rows,
            "breached": sorted(breached)}
