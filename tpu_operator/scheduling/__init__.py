"""Fair-share admission over the placement plane (Gavel-style policies)."""

from .quota import (  # noqa: F401
    ADMISSION_GATE,
    DEFAULT_CLASS,
    POLICIES,
    POLICY_BASELINE,
    AdmissionState,
    QuotaClass,
    QuotaTree,
    baseline_key,
    env_admission_policy,
    order_batch,
    quota_report,
)
