"""Workload-pod spawning proofs.

The plugin/jax validations that go through the scheduler: create a real
pod (optionally requesting google.com/tpu) and wait for it to succeed —
proving admission, scheduling, device allocation, and the runtime end to
end (validator/main.go:1086-1170 plugin pod, :1350-1425 cuda pod analog).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from ..api import labels as L
from ..runtime.client import Client, NotFoundError
from ..runtime.objects import get_nested
from . import barrier
from .components import ValidationFailed

log = logging.getLogger("tpu_validator")

POD_WAIT_ATTEMPTS = 60     # validator/main.go pod-wait 60x5s
POD_WAIT_INTERVAL_S = 5.0
RESOURCE_WAIT_ATTEMPTS = 30  # TPU-discovery 30x5s analog


def jax_workload_pod(namespace: str, node_name: str, image: str,
                     matmul_size: int = 4096,
                     request_tpu: bool = True) -> dict:
    """The JAX matmul proof pod (cuda-workload-validation.yaml analog)."""
    resources = ({"limits": {L.TPU_RESOURCE: "1"}} if request_tpu else {})
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": "tpu-jax-validator" + ("" if request_tpu else "-nores"),
            "namespace": namespace,
            "labels": {"app": "tpu-jax-validator"},
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeName": node_name,
            "tolerations": [{"key": L.TPU_RESOURCE, "operator": "Exists",
                             "effect": "NoSchedule"}],
            "containers": [{
                "name": "jax-matmul",
                "image": image,
                "command": ["python", "-m", "tpu_operator.workloads.matmul"],
                "env": [{"name": "MATMUL_SIZE", "value": str(matmul_size)}],
                "resources": resources,
            }],
        },
    }


def wait_for_pod_phase(client: Client, name: str, namespace: str,
                       want=("Succeeded",),
                       attempts: int = POD_WAIT_ATTEMPTS,
                       interval: float = POD_WAIT_INTERVAL_S) -> str:
    for _ in range(attempts):
        pod = client.get_or_none("v1", "Pod", name, namespace)
        phase = get_nested(pod or {}, "status", "phase", default="")
        if phase in want:
            return phase
        if phase == "Failed" and "Failed" not in want:
            raise ValidationFailed(f"workload pod {name} failed")
        time.sleep(interval)
    raise ValidationFailed(
        f"workload pod {name} did not reach {want} in "
        f"{attempts * interval:.0f}s")


def spawn_and_wait(client: Client, pod: dict,
                   attempts: int = POD_WAIT_ATTEMPTS,
                   interval: float = POD_WAIT_INTERVAL_S) -> str:
    name = pod["metadata"]["name"]
    ns = pod["metadata"]["namespace"]
    try:
        client.delete("v1", "Pod", name, ns)  # clear previous attempt
    except NotFoundError:
        pass
    client.create(pod)
    try:
        return wait_for_pod_phase(client, name, ns, attempts=attempts,
                                  interval=interval)
    finally:
        try:
            client.delete("v1", "Pod", name, ns)
        except NotFoundError:
            pass


def validate_plugin(client: Client, node_name: str, namespace: str,
                    image: str,
                    attempts: int = RESOURCE_WAIT_ATTEMPTS,
                    interval: float = POD_WAIT_INTERVAL_S) -> Dict[str, str]:
    """google.com/tpu allocatable on the node, then a pod requesting one
    TPU runs to completion."""
    allocatable = "0"
    for _ in range(attempts):
        node = client.get_or_none("v1", "Node", node_name)
        allocatable = str(get_nested(node or {}, "status", "allocatable",
                                     L.TPU_RESOURCE, default="0"))
        if allocatable not in ("", "0"):
            break
        time.sleep(interval)
    else:
        raise ValidationFailed(
            f"node {node_name} never advertised {L.TPU_RESOURCE}")

    pod = jax_workload_pod(namespace, node_name, image, request_tpu=True)
    pod["metadata"]["name"] = "tpu-plugin-validator"
    phase = spawn_and_wait(client, pod, interval=interval)
    info = {"ALLOCATABLE": allocatable, "WORKLOAD_PHASE": phase}
    barrier.write_status("plugin-ready", info)
    return info


def validate_jax_pod(client: Client, node_name: str, namespace: str,
                     image: str, matmul_size: int = 4096) -> Dict[str, str]:
    pod = jax_workload_pod(namespace, node_name, image,
                           matmul_size=matmul_size, request_tpu=False)
    phase = spawn_and_wait(client, pod)
    info = {"WORKLOAD_PHASE": phase, "MATMUL_SIZE": str(matmul_size)}
    barrier.write_status("jax-ready", info)
    return info
