"""Validation components (validator/main.go:479-596 dispatch analog).

Each component proves one layer of the TPU stack and writes its barrier
status file. Component -> proof:

- ``driver``   TPU chips visible: /dev/accel* (TPU VM) or /dev/vfio
               device nodes, the native libtpu probe when present, or a
               JAX enumeration; writes chip inventory into driver-ready
               (validateHostDriver/validateDriverContainer analog,
               main.go:694-750)
- ``runtime``  device nodes accessible + env contract -> runtime-ready
- ``jax``      REAL compute proof: bf16 matmul on a chip, in-process or
               as a spawned workload pod (cuda component analog,
               main.go:1350-1425)
- ``ici``      psum allreduce across all local chips; asserts achieved
               fraction of ICI peak >= threshold (the BASELINE.md north
               star; nothing like it exists for NCCL in the reference,
               where fabric checks are presence-only)
- ``dcn``      multi-slice only: the megascale coordinator resolves and
               accepts a TCP connect over the data-center network (the
               fabric-enablement slot MOFED/GDS checks fill in the
               reference, main.go:1002-1084); skipped single-slice
- ``plugin``   google.com/tpu extended resource allocatable on this node,
               then a pod *requesting* one TPU schedules and runs
               (main.go:1086-1253 analog)
- ``fencing``  isolated/virtual nodes: the fence exists, is non-empty,
               and names real chips (sandbox-validation vfio proof slot,
               main.go:1431-1692)
- ``vtpu``     virtual nodes: the vTPU inventory resolves and backs onto
               fenced chips only (vgpu-devices proof slot); skipped on
               whole-chip isolated nodes
- ``metrics``  node-status exporter loop (validator/metrics.go analog)
- ``sleep``    main-container park; ``cleanup`` preStop barrier teardown
"""

from __future__ import annotations

import glob
import json
import logging
import os
import subprocess
import time
from typing import Callable, Dict, List, Optional

from . import barrier

log = logging.getLogger("tpu_validator")


class ValidationFailed(Exception):
    pass


# ---------------------------------------------------------------------------
# chip discovery
# ---------------------------------------------------------------------------


def discover_chips() -> Dict:
    """Enumerate TPU chips on this host, best source first:

    1. TPU_FAKE_CHIPS env (tests / fake clusters)
    2. the native libtpu probe binary (native/libtpu_probe)
    3. /dev/accel* + /dev/vfio/* device nodes
    4. JAX device enumeration (requires exclusive libtpu access, so only
       used when TPU_VALIDATOR_USE_JAX=true)
    """
    fake = os.environ.get("TPU_FAKE_CHIPS")
    if fake:
        n = int(fake)
        return {"count": n, "source": "fake",
                "devices": [f"/dev/accel{i}" for i in range(n)]}

    probe = os.environ.get("LIBTPU_PROBE_BIN", "libtpu-probe")
    try:
        out = subprocess.run([probe, "--json"], capture_output=True,
                             timeout=30, text=True)
        if out.returncode == 0 and out.stdout.strip():
            data = json.loads(out.stdout)
            data.setdefault("source", "libtpu-probe")
            return data
    except (OSError, subprocess.TimeoutExpired, json.JSONDecodeError):
        pass

    devices = sorted(glob.glob("/dev/accel*"))
    vfio = sorted(p for p in glob.glob("/dev/vfio/*")
                  if os.path.basename(p) != "vfio")
    if devices or vfio:
        return {"count": len(devices) or len(vfio),
                "source": "devfs", "devices": devices or vfio}

    if os.environ.get("TPU_VALIDATOR_USE_JAX", "").lower() == "true":
        import jax

        tpus = [d for d in jax.devices() if d.platform != "cpu"]
        return {"count": len(tpus), "source": "jax",
                "devices": [str(d) for d in tpus],
                "kind": tpus[0].device_kind if tpus else ""}

    return {"count": 0, "source": "none", "devices": []}


# ---------------------------------------------------------------------------
# components
# ---------------------------------------------------------------------------


def validate_driver() -> Dict[str, str]:
    chips = discover_chips()
    if chips["count"] == 0:
        raise ValidationFailed(
            "no TPU chips visible (no /dev/accel*, no vfio devices, "
            "libtpu probe found nothing)")
    info = {
        "CHIP_COUNT": str(chips["count"]),
        "SOURCE": chips["source"],
        "DEVICES": ",".join(chips.get("devices", [])),
    }
    if chips.get("kind"):
        info["DEVICE_KIND"] = chips["kind"]
    if chips.get("libtpu_version"):
        info["LIBTPU_VERSION"] = chips["libtpu_version"]
    barrier.write_status("driver-ready", info)
    return info


def device_node_error(path: str) -> Optional[str]:
    """Real device-node proof: a TPU device node must be a *character
    device* that opens O_RDWR — permission-bit checks (os.access) pass a
    present-but-broken node, e.g. a regular file left behind by a failed
    driver install or a node with the wrong type/mode. Returns None when
    healthy, else the reason."""
    import stat as _stat

    try:
        st = os.stat(path)
    except OSError as e:
        return f"{path}: stat failed ({e.strerror})"
    if not _stat.S_ISCHR(st.st_mode):
        return f"{path}: not a character device (mode {oct(st.st_mode)})"
    try:
        fd = os.open(path, os.O_RDWR)
    except OSError as e:
        import errno as _errno

        if e.errno == _errno.EBUSY:
            # exclusively held by a running workload — the device is
            # demonstrably alive; failing validation here would wedge
            # re-proofs on busy-but-healthy nodes
            return None
        return f"{path}: open(O_RDWR) failed ({e.strerror})"
    os.close(fd)
    return None


def validate_runtime() -> Dict[str, str]:
    if not barrier.is_ready("driver-ready"):
        if os.environ.get("WITH_WAIT", "").lower() == "true":
            if not barrier.wait_for("driver-ready"):
                raise ValidationFailed("timed out waiting for driver-ready")
        else:
            raise ValidationFailed("driver-ready gate not passed")
    chips = discover_chips()
    broken = [err for d in chips.get("devices", [])
              if d.startswith("/dev/") and (err := device_node_error(d))]
    if chips["count"] and broken and chips["source"] != "fake":
        raise ValidationFailed(f"device nodes not usable: {broken}")
    info = {"DEVICE_COUNT": str(chips["count"])}
    # control-plane belief vs node reality (clusterinfo-for-decisions):
    # the operator renders its detected runtime into the DS env; the
    # node records what it actually runs next to it, so belief/reality
    # drift is visible in the barrier file and the node-status metrics
    expected = os.environ.get("EXPECTED_CONTAINER_RUNTIME")
    if expected:
        info["EXPECTED_CONTAINER_RUNTIME"] = expected
        actual = _node_container_runtime()
        if actual:
            info["CONTAINER_RUNTIME"] = actual
            if not actual.startswith(expected):
                log.warning(
                    "container runtime drift: operator detected %r, "
                    "node reports %r", expected, actual)
    barrier.write_status("runtime-ready", info)
    return info


def _node_container_runtime() -> str:
    """The runtime actually serving this node: its socket under the
    host rootfs (the runtime-validation initContainer mounts it at
    HOST_ROOT, like driver-validation's /host) is the ground truth —
    probing the container's own filesystem would always come up empty."""
    host = os.environ.get("HOST_ROOT", "/host").rstrip("/")
    for sock, name in (("/run/containerd/containerd.sock", "containerd"),
                       ("/var/run/docker.sock", "docker"),
                       ("/var/run/crio/crio.sock", "cri-o")):
        if os.path.exists(host + sock):
            return name
    return ""


def validate_jax(matmul_size: Optional[int] = None,
                 allow_cpu: Optional[bool] = None) -> Dict[str, str]:
    """In-process single-chip matmul proof. (The pod-spawning variant lives
    in workload.py and is used when a kube client is available.)

    The proof must run on an actual TPU: JAX silently falls back to the CPU
    backend when libtpu can't initialize, and certifying a node off a CPU
    matmul would defeat the whole gate. CPU is allowed only via explicit
    opt-in (tests, fake clusters)."""
    size = matmul_size or int(os.environ.get("MATMUL_SIZE", "4096"))
    if allow_cpu is None:
        allow_cpu = os.environ.get("TPU_VALIDATOR_ALLOW_CPU",
                                   "").lower() == "true"
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu" and not allow_cpu:
        raise ValidationFailed(
            "JAX initialized on the CPU backend — libtpu is not usable "
            "from this container (set TPU_VALIDATOR_ALLOW_CPU=true only "
            "for fake/test clusters)")
    from ..workloads import matmul

    res = matmul.run(size=size, iters=8, calls=2, repeats=1)
    if not res.checksum_ok:
        raise ValidationFailed("matmul produced non-finite values")
    info = {
        "MATMUL_SIZE": str(size),
        # 4 significant digits, not fixed-point: a tiny proof matmul on a
        # slow host must not round to "0.00"
        "TFLOPS": f"{res.tflops:.4g}",
        "DEVICE_KIND": res.device_kind,
    }
    if res.utilization is not None:
        info["MXU_UTILIZATION"] = f"{res.utilization:.3f}"
    barrier.write_status("jax-ready", info)
    return info


def validate_ici(threshold: Optional[float] = None,
                 allow_cpu: Optional[bool] = None) -> Dict[str, str]:
    import jax

    if allow_cpu is None:
        allow_cpu = os.environ.get("TPU_VALIDATOR_ALLOW_CPU",
                                   "").lower() == "true"
    if jax.devices()[0].platform == "cpu" and not allow_cpu:
        raise ValidationFailed(
            "JAX initialized on the CPU backend — cannot measure ICI "
            "(set TPU_VALIDATOR_ALLOW_CPU=true only for fake/test clusters)")
    thr = threshold if threshold is not None else float(
        os.environ.get("ICI_THRESHOLD", "0.8"))
    n = jax.device_count()
    if n < 2:
        info = {"SKIPPED": "single-chip host, no ICI to validate",
                "DEVICES": str(n)}
        barrier.write_status("ici-ready", info)
        return info
    from ..workloads import collectives

    res = collectives.run(size_mb=float(os.environ.get("ICI_SIZE_MB", "256")))
    if not res.correct:
        raise ValidationFailed("allreduce produced wrong values")
    info = {
        "DEVICES": str(res.devices),
        "BUS_BW_GBPS": f"{res.bus_bw_gbps:.2f}",
        "DEVICE_KIND": res.device_kind,
    }
    if res.fraction_of_peak is not None:
        info["FRACTION_OF_PEAK"] = f"{res.fraction_of_peak:.3f}"
        if res.fraction_of_peak < thr:
            raise ValidationFailed(
                f"ICI allreduce reached {res.fraction_of_peak:.1%} of peak, "
                f"below the {thr:.0%} threshold")
    if os.environ.get("ICI_FULL_SUITE", "").lower() == "true":
        # the NCCL-tests slot: one figure per primitive in the barrier
        # file (informational — the psum number above stays the gate; a
        # primitive that moves wrong data still fails hard)
        suite = collectives.run_suite(
            size_mb=float(os.environ.get("ICI_SUITE_SIZE_MB", "64")))
        for op, r in suite.items():
            if not r.correct:
                raise ValidationFailed(f"collective {op} produced wrong "
                                       f"values")
            info[f"SUITE_{op.upper()}_BUS_GBPS"] = f"{r.bus_bw_gbps:.2f}"
    barrier.write_status("ici-ready", info)
    return info


def validate_hbm(threshold: Optional[float] = None,
                 allow_cpu: Optional[bool] = None) -> Dict[str, str]:
    """HBM bandwidth proof: the Pallas STREAM-triad kernel must sustain a
    healthy fraction of the chip's published HBM bandwidth (a slow HBM is
    a failing chip). Default bar is 0.5 — conservative across runtimes;
    the measured healthy figure on v5e is ~0.8."""
    import jax

    if allow_cpu is None:
        allow_cpu = os.environ.get("TPU_VALIDATOR_ALLOW_CPU",
                                   "").lower() == "true"
    if jax.devices()[0].platform == "cpu" and not allow_cpu:
        raise ValidationFailed(
            "JAX initialized on the CPU backend — cannot measure HBM "
            "(set TPU_VALIDATOR_ALLOW_CPU=true only for fake/test clusters)")
    thr = threshold if threshold is not None else float(
        os.environ.get("HBM_THRESHOLD", "0.5"))
    from ..workloads import pallas_probe

    res = pallas_probe.run(size_mb=float(os.environ.get("HBM_SIZE_MB", "512")))
    if not res.correct:
        raise ValidationFailed("triad kernel produced wrong values")
    info = {
        "BANDWIDTH_GBPS": f"{res.bandwidth_gbps:.2f}",
        "DEVICE_KIND": res.device_kind,
    }
    if res.fraction_of_peak is not None:
        info["FRACTION_OF_PEAK"] = f"{res.fraction_of_peak:.3f}"
        if res.fraction_of_peak < thr:
            raise ValidationFailed(
                f"HBM triad reached {res.fraction_of_peak:.1%} of peak, "
                f"below the {thr:.0%} threshold")
    barrier.write_status("hbm-ready", info)
    return info


def validate_dcn(timeout: Optional[float] = None) -> Dict[str, str]:
    """Multi-slice DCN reachability (SURVEY.md section 5: the TPU analog
    of the reference's fabric-enablement checks — MOFED/GDS presence,
    validator/main.go:1002-1084 — is proving the *data-center network*
    path between slices). Multi-slice jobs discover each other through the
    megascale coordinator; this proof resolves and TCP-connects it. On a
    single-slice node there is no DCN to validate — skipped, like the
    reference's MOFED check on nodes without the Mellanox PCI label
    (main.go:204)."""
    import socket

    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1") or 1)
    coordinator = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS", "")
    if num_slices <= 1 or not coordinator:
        info = {"SKIPPED": "single-slice node, no DCN to validate",
                "NUM_SLICES": str(num_slices)}
        barrier.write_status("dcn-ready", info)
        return info
    host, _, port_s = coordinator.partition(":")
    port = int(port_s or 8080)
    deadline = time.monotonic() + (
        timeout if timeout is not None
        else float(os.environ.get("DCN_TIMEOUT_S", "60")))
    last_err: Optional[Exception] = None
    info: Optional[Dict[str, str]] = None
    while time.monotonic() < deadline:
        start = time.perf_counter()
        try:
            with socket.create_connection((host, port), timeout=5.0):
                rtt_ms = (time.perf_counter() - start) * 1e3
            info = {
                "COORDINATOR": coordinator,
                "NUM_SLICES": str(num_slices),
                "SLICE_ID": os.environ.get("MEGASCALE_SLICE_ID", ""),
                "RTT_MS": f"{rtt_ms:.2f}",
            }
            break
        except OSError as e:
            last_err = e
            time.sleep(1.0)
    if info is None:
        raise ValidationFailed(
            f"megascale coordinator {coordinator} unreachable over DCN: "
            f"{last_err}")
    # outside the connect-retry loop: a probe error must never be
    # misread as coordinator unreachability (and never re-run per retry)
    _maybe_dcn_bandwidth_probe(info)
    barrier.write_status("dcn-ready", info)
    return info


def _maybe_dcn_bandwidth_probe(info: Dict[str, str]) -> None:
    """DCN_BANDWIDTH_PROBE=true: measure the cross-slice gradient-sync
    path (psum over the hybrid mesh's dcn axis) and add its figures to
    the barrier info — the measured-bandwidth counterpart of the TCP
    reachability check, like validate_ici is to the driver proof.
    ``DCN_PROBE_FAKE_SLICES=N`` splits the visible devices into N equal
    groups for fake/test clusters whose devices carry no slice_index.
    Wrong psum results fail the proof; a probe that cannot run (e.g.
    devices not visible from this pod) records the error and leaves the
    reachability verdict standing."""
    if os.environ.get("DCN_BANDWIDTH_PROBE", "").lower() != "true":
        return
    from ..parallel import multihost

    try:
        fake_n = int(os.environ.get("DCN_PROBE_FAKE_SLICES", "0") or 0)
        kwargs = {}
        if fake_n > 1:
            import jax

            devs = jax.devices()
            per = len(devs) // fake_n
            if per < 1:
                raise ValueError(
                    f"DCN_PROBE_FAKE_SLICES={fake_n} exceeds the "
                    f"{len(devs)} visible devices")
            kwargs = {"devices": devs[:per * fake_n],
                      "slice_getter": multihost.fake_slice_getter(
                          devs, fake_n)}
        res = multihost.dcn_allreduce_probe(
            size_mb=float(os.environ.get("DCN_PROBE_SIZE_MB", "64")),
            **kwargs)
    except Exception as e:
        # a probe that cannot RUN (no visible backend, bad config) is a
        # recorded error, not a failed proof — reachability stands; only
        # a probe that ran and moved WRONG DATA fails below
        info["DCN_PROBE_ERROR"] = f"{type(e).__name__}: {e}"
        return
    if not res.correct:
        raise ValidationFailed("DCN psum produced wrong values")
    info["DCN_SLICES"] = str(res.slices)
    info["DCN_BUS_GBPS"] = f"{res.bus_bw_gbps:.2f}"
    # DCN_THRESHOLD (Gbps bus bandwidth): ICI_THRESHOLD's DCN mirror,
    # but absolute not fraction-of-peak — DCN peak depends on the
    # inter-slice fabric, which the node cannot introspect. Off unless
    # set: reachability plus correct data is the default contract.
    thr_s = os.environ.get("DCN_THRESHOLD", "")
    if thr_s:
        thr = float(thr_s)
        if res.bus_bw_gbps < thr:
            raise ValidationFailed(
                f"DCN psum bus bandwidth {res.bus_bw_gbps:.2f} Gbps is "
                f"below the {thr:g} Gbps DCN_THRESHOLD")


def validate_fencing() -> Dict[str, str]:
    """Isolated/virtual nodes (sandbox-validation slot,
    validator/main.go:1431-1692 vfio-pci proof analog): the fence file
    exists, every fenced chip is a real chip on this host, and at least
    one chip is fenced — an isolated node with an empty fence serves
    nothing and must not pass its gate."""
    from ..isolation.fencing import fenced_chips, read_fencing_file

    cfg = read_fencing_file()
    if cfg is None:
        raise ValidationFailed(
            "no fencing config published (is chip-fencing running?)")
    fenced = fenced_chips()
    if not fenced:
        raise ValidationFailed(
            f"fence is empty (config={cfg.get('config')!r}) — an isolated "
            "node must fence at least one chip")
    chips = discover_chips()
    known = {os.path.basename(d) for d in chips.get("devices", [])}
    unknown = [c for c in fenced if known and c not in known]
    if unknown:
        raise ValidationFailed(
            f"fenced chips {unknown} are not present on this host "
            f"(have {sorted(known)})")
    info = {"FENCED_COUNT": str(len(fenced)),
            "FENCED": ",".join(fenced),
            "CONFIG": str(cfg.get("config", ""))}
    barrier.write_status("fencing-ready", info)
    return info


def _node_workload_config() -> str:
    """This node's workload config: TPU_WORKLOAD_CONFIG env (tests),
    else the node label via the apiserver (best effort — the validator
    DS has nodes/get RBAC for exactly this, the same introspection the
    reference's sandbox validator uses to pick vfio vs vgpu proofs)."""
    env = os.environ.get("TPU_WORKLOAD_CONFIG", "")
    if env:
        return env
    # a node with no label was routed by the plane's default — the
    # manifest passes it down so the proof resolves the same config the
    # operator did
    default = os.environ.get("TPU_DEFAULT_WORKLOAD_CONFIG", "")
    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        return default
    try:
        from ..api import labels as L
        from ..runtime.kubeclient import HTTPClient, KubeConfig

        node = HTTPClient(KubeConfig.load()).get("v1", "Node", node_name)
        return ((node.get("metadata") or {}).get("labels") or {}).get(
            L.WORKLOAD_CONFIG, default)
    except Exception:
        return default


def validate_vtpu() -> Dict[str, str]:
    """Virtual nodes (the vGPU-devices proof slot): the vTPU manager has
    published a resolvable inventory whose backing chips are all fenced.
    On an ``isolated`` (whole-chip) node there is no inventory to prove —
    skipped, like the reference's MOFED check on nodes without the
    Mellanox PCI label."""
    from ..isolation.fencing import fenced_chips
    from ..isolation.vtpu import read_vtpu_file

    config = _node_workload_config()
    if config == "isolated":
        # whole-chip node: never validate an inventory here — one left
        # over from a virtual->isolated flip is stale by definition (the
        # fencing agent withdraws it; this proof must not bless it)
        info = {"SKIPPED": "whole-chip isolated node, no vTPU inventory",
                "WORKLOAD_CONFIG": config}
        barrier.write_status("vtpu-ready", info)
        return info
    vtpu = read_vtpu_file()
    if not vtpu or not vtpu.get("devices"):
        if not config:
            # can't tell isolated from virtual: retry (WITH_WAIT), don't
            # demand an inventory that may by design never exist here
            raise ValidationFailed(
                "cannot determine this node's workload config (apiserver "
                "unreachable or NODE_NAME unset) and no vTPU inventory is "
                "published; retrying")
        raise ValidationFailed(
            "no vTPU inventory published (is vtpu-device-manager running "
            "and the fence applied?)")
    fenced = set(fenced_chips())
    backing = {d.get("chip") for d in vtpu["devices"]}
    stray = sorted(c for c in backing if c not in fenced)
    if stray:
        raise ValidationFailed(
            f"vTPU devices back onto unfenced chips {stray} — the shared "
            "pool would double-allocate them")
    info = {"PROFILE": str(vtpu.get("profile", "")),
            "VTPU_COUNT": str(len(vtpu["devices"])),
            "CHIP_COUNT": str(len(backing))}
    barrier.write_status("vtpu-ready", info)
    return info


def component_sleep() -> None:  # pragma: no cover - blocks forever
    log.info("node validated; sleeping (DaemonSet main container)")
    while True:
        time.sleep(3600)


def component_cleanup() -> None:
    barrier.cleanup_all()
    log.info("validation status files removed")
