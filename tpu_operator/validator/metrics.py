"""Node validation-status metrics exporter (validator/metrics.go:39-320
analog): polls the barrier status files, periodically re-proves the driver
layer, and serves tpu_operator_node_* gauges for the node-status-exporter
DaemonSet."""

from __future__ import annotations

import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from prometheus_client import CollectorRegistry, Gauge, generate_latest

from . import barrier, components

log = logging.getLogger("tpu_validator.metrics")

POLL_INTERVAL_S = 30.0        # status-file poll (metrics.go:39-46 analog)
REVALIDATE_INTERVAL_S = 60.0  # driver re-proof cadence

COMPONENT_FILES = {
    "driver": "driver-ready",
    "runtime": "runtime-ready",
    "jax": "jax-ready",
    "plugin": "plugin-ready",
    "ici": "ici-ready",
}

# isolated-plane components: emitted only on nodes where the plane is
# present (fence file published), so container nodes don't export a
# constant 0 that is indistinguishable from a real validation failure
ISOLATION_COMPONENT_FILES = {
    "fencing": "fencing-ready",
    "vtpu": "vtpu-ready",
}


class NodeMetrics:
    def __init__(self, node_name: str = ""):
        self.registry = CollectorRegistry()
        self.node_name = node_name
        self.ready = Gauge("tpu_operator_node_component_ready",
                           "1 when the component's validation is current",
                           labelnames=("component", "node"),
                           registry=self.registry)
        self.chips = Gauge("tpu_operator_node_tpu_chips",
                           "TPU chips discovered on this node",
                           labelnames=("node",), registry=self.registry)
        self.revalidations = Gauge("tpu_operator_node_revalidations_total",
                                   "Driver re-validation attempts",
                                   labelnames=("node",),
                                   registry=self.registry)
        self.revalidation_ok = Gauge(
            "tpu_operator_node_driver_revalidation_ok",
            "1 when the last periodic driver re-proof succeeded",
            labelnames=("node",), registry=self.registry)
        # performance figures measured by the proofs (barrier file INFO
        # lines) re-published as scrapeable gauges — the perf floor
        # becomes a continuously observable per-node signal, not a value
        # buried in a hostPath file
        self.mxu_utilization = Gauge(
            "tpu_operator_node_matmul_mxu_utilization",
            "Fraction of peak bf16 the jax proof sustained",
            labelnames=("node",), registry=self.registry)
        self.ici_fraction = Gauge(
            "tpu_operator_node_ici_fraction_of_peak",
            "Fraction of peak ICI bandwidth the psum proof reached",
            labelnames=("node",), registry=self.registry)
        self.hbm_fraction = Gauge(
            "tpu_operator_node_hbm_fraction_of_peak",
            "Fraction of peak HBM bandwidth the STREAM probe reached",
            labelnames=("node",), registry=self.registry)
        self.collective_bus = Gauge(
            "tpu_operator_node_collective_bus_gbps",
            "Per-primitive ICI bus bandwidth from the full suite",
            labelnames=("op", "node"), registry=self.registry)
        self._published_ops: set = set()
        self._reval_count = 0

    @staticmethod
    def _isolation_plane_present() -> bool:
        """This node runs the isolated plane iff a fence has been
        published (or its proof passed) — the signal the exporter can see
        without apiserver access."""
        from ..isolation.fencing import read_fencing_file

        return read_fencing_file() is not None or \
            barrier.is_ready("fencing-ready")

    def collect_once(self, revalidate: bool = False) -> None:
        if revalidate:
            self._reval_count += 1
            self.revalidations.labels(node=self.node_name).set(
                self._reval_count)
            try:
                components.validate_driver()
                self.revalidation_ok.labels(node=self.node_name).set(1)
            except components.ValidationFailed as e:
                # Report the failure via the gauge only. The barrier file is
                # OWNED by the validator DaemonSet — clearing it from here
                # would wedge every operand on the node whenever this
                # exporter pod merely lacks device visibility.
                log.warning("driver re-validation failed: %s", e)
                self.revalidation_ok.labels(node=self.node_name).set(0)
        for comp, fname in COMPONENT_FILES.items():
            self.ready.labels(component=comp, node=self.node_name).set(
                1 if barrier.is_ready(fname) else 0)
        if self._isolation_plane_present():
            for comp, fname in ISOLATION_COMPONENT_FILES.items():
                self.ready.labels(component=comp, node=self.node_name).set(
                    1 if barrier.is_ready(fname) else 0)
        info = barrier.read_status("driver-ready") or {}
        self.chips.labels(node=self.node_name).set(
            int(info.get("CHIP_COUNT", "0") or 0))
        self._publish_perf_figures()

    def _publish_perf_figures(self) -> None:
        """Re-publish the proofs' measured figures. A figure whose source
        (barrier file or key) has gone away is REMOVED, not left frozen:
        a stale series would show a degraded node's dashboard the old
        healthy perf floor as if it were current."""

        def as_float(s):
            try:
                return float(s)
            except (TypeError, ValueError):
                return None

        def set_or_remove(gauge, value, ordered_label_values):
            """``ordered_label_values`` in the gauge's declared labelname
            order (we declared them, so the caller knows it — no reliance
            on prometheus_client internals)."""
            if value is not None:
                gauge.labels(*ordered_label_values).set(value)
            else:
                try:
                    gauge.remove(*ordered_label_values)
                except KeyError:
                    pass  # never published

        node = self.node_name
        jax_info = barrier.read_status("jax-ready") or {}
        set_or_remove(self.mxu_utilization,
                      as_float(jax_info.get("MXU_UTILIZATION")), (node,))
        ici_info = barrier.read_status("ici-ready") or {}
        set_or_remove(self.ici_fraction,
                      as_float(ici_info.get("FRACTION_OF_PEAK")), (node,))
        present_ops = set()
        for key, val in ici_info.items():
            if key.startswith("SUITE_") and key.endswith("_BUS_GBPS"):
                bw = as_float(val)
                if bw is not None:
                    op = key[len("SUITE_"):-len("_BUS_GBPS")].lower()
                    present_ops.add(op)
                    self.collective_bus.labels(op=op, node=node).set(bw)
        for op in self._published_ops - present_ops:
            set_or_remove(self.collective_bus, None, (op, node))
        self._published_ops = present_ops
        hbm_info = barrier.read_status("hbm-ready") or {}
        set_or_remove(self.hbm_fraction,
                      as_float(hbm_info.get("FRACTION_OF_PEAK")), (node,))

    def render(self) -> bytes:
        return generate_latest(self.registry)


def serve(port: int, node_name: str = "",
          poll_interval: float = POLL_INTERVAL_S,
          revalidate_interval: float = REVALIDATE_INTERVAL_S,
          stop_event: threading.Event = None) -> ThreadingHTTPServer:
    """Start the exporter (returns the server; caller joins/stops)."""
    metrics = NodeMetrics(node_name)
    metrics.collect_once(revalidate=False)
    stop = stop_event or threading.Event()

    def poll_loop():
        last_reval = time.monotonic()
        while not stop.is_set():
            revalidate = time.monotonic() - last_reval >= revalidate_interval
            if revalidate:
                last_reval = time.monotonic()
            try:
                metrics.collect_once(revalidate=revalidate)
            except Exception:
                log.exception("metrics collection failed")
            stop.wait(poll_interval)

    threading.Thread(target=poll_loop, daemon=True).start()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/metrics":
                body = metrics.render()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
            elif self.path == "/healthz":
                body = b"ok"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
            else:
                body = b"not found"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    server._stop_event = stop  # type: ignore[attr-defined]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
