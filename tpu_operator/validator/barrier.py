"""Status-file barrier protocol.

The cross-pod synchronization mechanism of the whole system (SURVEY.md
section 2.3): each validation component writes
``<validation-dir>/<component>-ready`` on success; every downstream
operand's initContainer blocks on the file it needs. The directory is a
hostPath (default /run/tpu/validations) so the barrier spans pods on the
same node. Mirrors the reference's status-file handling
(validator/main.go:139-180 retry cadence, :801-812 driver-ready payload,
preStop cleanup in assets/state-operator-validation/0500_daemonset.yaml:
155-157).
"""

from __future__ import annotations

import os
import pathlib
import time
from typing import Dict, Optional

DEFAULT_DIR = "/run/tpu/validations"
RETRY_INTERVAL_S = 5.0      # validator/main.go:139 analog
DEFAULT_TIMEOUT_S = 300.0   # 60 x 5s pod-wait analog

KNOWN_STATUS_FILES = (
    "driver-ready",
    "runtime-ready",
    "jax-ready",
    "plugin-ready",
    "ici-ready",
    "hbm-ready",
    "dcn-ready",
    "topology-ready",
    "fencing-ready",
    "vtpu-ready",
    ".driver-ctr-ready",
)


def validation_dir() -> pathlib.Path:
    return pathlib.Path(os.environ.get("TPU_VALIDATION_DIR", DEFAULT_DIR))


def status_path(name: str) -> pathlib.Path:
    return validation_dir() / name


def write_status(name: str, info: Optional[Dict[str, str]] = None) -> pathlib.Path:
    """Write a status file atomically (tmp+rename) with KEY=VALUE payload
    lines, like the reference's driverInfo env-style lines
    (validator/driver.go:32-39)."""
    path = status_path(name)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    lines = [f"{k}={v}" for k, v in (info or {}).items()]
    tmp.write_text("\n".join(lines) + ("\n" if lines else ""))
    tmp.rename(path)
    return path


def read_status(name: str) -> Optional[Dict[str, str]]:
    path = status_path(name)
    if not path.exists():
        return None
    out: Dict[str, str] = {}
    for line in path.read_text().splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            out[k] = v
    return out


def is_ready(name: str) -> bool:
    return status_path(name).exists()


def clear_status(name: str) -> None:
    try:
        status_path(name).unlink()
    except FileNotFoundError:
        pass


def cleanup_all() -> None:
    """preStop: drop every status file so a departing validator re-gates
    the node."""
    d = validation_dir()
    if not d.is_dir():
        return
    for name in KNOWN_STATUS_FILES:
        clear_status(name)


def wait_for(name: str, timeout: float = DEFAULT_TIMEOUT_S,
             interval: float = RETRY_INTERVAL_S) -> bool:
    """Block until a status file exists (the wait initContainer primitive)."""
    deadline = time.monotonic() + timeout
    while True:
        if is_ready(name):
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(min(interval, max(0.01, deadline - time.monotonic())))
