"""tpu-operator: a TPU-native Kubernetes operator framework.

A ground-up rebuild of the capabilities of the NVIDIA gpu-operator
(reference: /root/reference, github.com/NVIDIA/gpu-operator v24.3.0) for
Google TPU node pools, designed TPU-first:

- The CUDA operand stack (driver kmod, container-toolkit, device plugin,
  DCGM, MIG manager) is replaced by a TPU operand stack (libtpu installer,
  TPU runtime hookup, TPU device plugin, libtpu metrics exporter,
  topology/slice manager).
- The validation plane proves each layer with real XLA programs: a bf16
  matmul sized for the MXU (single chip) and a psum ring allreduce over the
  ICI mesh (multi chip), instead of the CUDA ``vectorAdd`` sample.
- One state engine only, modeled on the reference's *destination*
  architecture (internal/state + internal/render, "engine B"), not the
  legacy 4876-line object_controls.go path.

Package map (SURVEY.md section 2 inventory -> here):

- ``runtime/``      mini controller-runtime: clients, workqueue, manager
- ``api/``          TPUClusterPolicy + TPUDriver CRD types, conditions
- ``controllers/``  ClusterPolicy / TPUDriver / Upgrade reconcilers, clusterinfo
- ``state/``        State interface, apply/readiness skeleton, node pools
- ``render/``       template renderer over manifests/
- ``validator/``    per-node validation plane + barrier protocol
- ``deviceplugin/`` kubelet device plugin (google.com/tpu)
- ``workloads/``    JAX/XLA validation workloads (matmul, collectives, burn-in)
- ``parallel/``     device mesh + sharding helpers for the workloads
- ``metrics/``      operator + node prometheus metrics
- ``cli/``          tpu-operator / tpu-validator / tpuop-cfg entrypoints
"""

__version__ = "0.1.0"
