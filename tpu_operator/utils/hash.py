"""Deterministic object hashing for change detection.

The reference guards DaemonSet updates with an FNV-32a hash of the spec
stored in an annotation (internal/utils/utils.go:71-84 GetObjectHash,
consumed at object_controls.go:4303-4346). We keep the same idea with a
canonical-JSON FNV-1a 64-bit hash: deterministic across processes, cheap,
and stable under dict ordering.
"""

from __future__ import annotations

import json
from typing import Any

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3


def fnv1a_64(data: bytes) -> int:
    h = _FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def object_hash(obj: Any) -> str:
    """Hex FNV-1a of the canonical JSON encoding of ``obj``."""
    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)
    return format(fnv1a_64(payload.encode("utf-8")), "016x")
