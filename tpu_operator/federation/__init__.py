"""Partition-tolerant multi-cluster federation.

A fleet is N operator *cells* (one apiserver + operator each). Each
cell distills its FleetIndex into a cheap, schema-stamped fleet digest
(federation/digest.py) published on a jittered cadence; a global router
(federation/router.py) places SliceRequests onto cells by digest score
plus data-locality preference and lets the cell's own placement engine
do the fine placement. Every cell sits behind a Healthy → Suspect →
Open circuit breaker, so a partitioned or browned-out cell is routed
around — its stale digest age-discounted rather than trusted, its
bound requests left alone (partition ≠ dead) until a configurable
condemnation horizon, past which they are migrated cross-cell by
replaying the elastic handshake (runtime/multicell.py).
"""

from .digest import (
    CELL_DIGEST_SCHEMA_VERSION,
    cell_digest,
    cell_digest_json,
    parse_cell_digest,
    publish_wait,
)
from .router import (
    CELL_HEALTHY,
    CELL_OPEN,
    CELL_SUSPECT,
    GlobalRouter,
    cells_report,
)

__all__ = [
    "CELL_DIGEST_SCHEMA_VERSION",
    "cell_digest",
    "cell_digest_json",
    "parse_cell_digest",
    "publish_wait",
    "CELL_HEALTHY",
    "CELL_SUSPECT",
    "CELL_OPEN",
    "GlobalRouter",
    "cells_report",
]
