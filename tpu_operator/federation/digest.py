"""Cell fleet digests — the federation plane's currency.

Each cell distills its :class:`~tpu_operator.topology.index.FleetIndex`
into one small schema-stamped dict (``FleetIndex.digest_stats`` does
the locked pass) and publishes it on a jittered cadence, exactly the
discipline the node health digests established (metrics/health_engine):

- schema-stamped (``v``): a router never guesses at an old producer's
  field meanings — unknown versions parse to None and the cell scores
  as digest-less (age-discounted to the floor), never wrongly.
- sequence-stamped (``seq``): watch echoes and out-of-order delivery
  dedupe by seq, so a router's view is a pure function of the digest
  SET it has seen, not the arrival order — the property the seeded
  permutation test pins.
- age-stamped (``at``): the router discounts by age instead of
  trusting a partitioned cell's last words forever.
"""

from __future__ import annotations

import json
import random
from typing import Optional

CELL_DIGEST_SCHEMA_VERSION = 1

# publish cadence defaults: same shape as the node health engine's
# (interval * (1 ± jitter)), seeded per cell so a fleet of cells never
# publishes in lockstep yet each cell's cadence is reproducible
PUBLISH_INTERVAL_S = 15.0
PUBLISH_JITTER = 0.2


def cell_digest(index, cell: str, seq: int, now: float) -> dict:
    """One publish: the index distilled + the federation envelope."""
    stats = index.digest_stats()
    return {
        "v": CELL_DIGEST_SCHEMA_VERSION,
        "cell": str(cell),
        "seq": int(seq),
        "at": float(now),
        "hosts": stats["hosts"],
        "chips_free": stats["chips_free"],
        "chips_placed": stats["chips_placed"],
        "utilization": stats["utilization"],
        "headroom": dict(stats["headroom"]),
        "fragmentation": stats["fragmentation"],
        "condemned": stats["condemned"],
    }


def cell_digest_json(digest: dict) -> str:
    """Compact, key-sorted wire form (annotation/report payload)."""
    return json.dumps(digest, sort_keys=True, separators=(",", ":"))


def parse_cell_digest(raw) -> Optional[dict]:
    """Parse a published digest; None on absent, malformed, or a schema
    version this consumer doesn't speak — the caller treats all three
    as 'no digest', never as a half-understood one."""
    if raw is None:
        return None
    if isinstance(raw, dict):
        d = raw
    else:
        try:
            d = json.loads(raw)
        except (TypeError, ValueError):
            return None
    if not isinstance(d, dict) or d.get("v") != CELL_DIGEST_SCHEMA_VERSION:
        return None
    if not d.get("cell") or not isinstance(d.get("seq"), int):
        return None
    return d


def publish_wait(cell: str, interval: float = PUBLISH_INTERVAL_S,
                 jitter: float = PUBLISH_JITTER) -> float:
    """Jittered wait before this cell's next publish — seeded per cell
    (reproducible) and spread ±jitter so N cells desynchronize."""
    rng = random.Random(f"cell-digest:{cell}")
    return max(0.0, interval * (1.0 + jitter * (2.0 * rng.random() - 1.0)))
