"""The global router: digest-scored cell choice behind circuit breakers.

One router instance owns the federation's *coarse* decision — which
cell a SliceRequest lands in — and nothing else: the chosen cell's own
placement engine does the fine placement. Three design rules keep the
global plane robust to exactly the failures that kill naive federations:

- **Per-cell circuit breaker** (Healthy → Suspect → Open): a failure
  streak against a cell's apiserver opens the breaker; an Open cell is
  never routed to, and is re-contacted only by capped-exponential-
  backoff probes — a partitioned cell costs the router one cheap probe
  per backoff window, not a timeout per request.
- **Age-discounted digests**: a stale digest is discounted toward
  zero, never trusted at face value — a cell that went quiet fades out
  of the score race instead of absorbing traffic its last words said
  it could take.
- **Arrival-order independence**: digests dedupe by (cell, seq), so
  the router's decision is a pure function of the digest set it holds
  and the clock — two routers fed the same digests in any order agree
  (the split-brain-router chaos scenario and the seeded permutation
  test both pin this).

Requests already bound in a partitioned cell are left alone — partition
is not death. Only past ``condemnation_horizon_s`` of continuous Open
does the federation condemn the cell and migrate its slices out by
replaying the elastic handshake (runtime/multicell.py).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional

from ..api import labels as L
from ..api.slicerequest import KIND_SLICE_REQUEST, V1ALPHA1
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime.client import ListOptions
from ..runtime.objects import annotations_of, get_nested, name_of
from .digest import parse_cell_digest

CELL_HEALTHY = "Healthy"
CELL_SUSPECT = "Suspect"
CELL_OPEN = "Open"

# breaker tuning (same shape as the cache's degraded-mode breaker:
# streak threshold, then capped exponential backoff between probes)
FAILURE_THRESHOLD = 3
PROBE_BACKOFF_BASE_S = 10.0
PROBE_BACKOFF_CAP_S = 300.0
# a digest this old scores at half weight; twice this, a third; ...
DIGEST_HALF_LIFE_S = 60.0
# continuous-Open time before a cell's bound slices are condemned to
# cross-cell migration
CONDEMNATION_HORIZON_S = 600.0
# a locality-preferred cell wins while it scores at least this fraction
# of the best cell — locality steers between comparable cells, it never
# overrides a collapsed one
LOCALITY_TOLERANCE = 0.5
# Suspect cells stay routable (one blip must not drain a cell) but at a
# discount, so a healthy twin wins ties
SUSPECT_PENALTY = 0.5

ROUTER_STATE_VERSION = 1


class CellState:
    """One cell's breaker + digest view. Plain mutable record; all
    transitions go through the router so the ledger stays consistent."""

    __slots__ = ("name", "state", "failure_streak", "open_since",
                 "last_probe_at", "probes", "digest", "booked",
                 "booked_by_gen", "routed_total")

    def __init__(self, name: str):
        self.name = name
        self.state = CELL_HEALTHY
        self.failure_streak = 0
        self.open_since: Optional[float] = None
        self.last_probe_at: Optional[float] = None
        self.probes = 0
        self.digest: Optional[dict] = None
        # chips routed here since the held digest's seq — the router's
        # own book against over-committing a cell between publishes
        self.booked = 0
        self.booked_by_gen: Dict[str, int] = {}
        self.routed_total = 0

    def to_dict(self) -> dict:
        return {
            "state": self.state,
            "failure_streak": self.failure_streak,
            "open_since": self.open_since,
            "last_probe_at": self.last_probe_at,
            "probes": self.probes,
            "digest": self.digest,
            "booked": self.booked,
            "booked_by_gen": dict(sorted(self.booked_by_gen.items())),
            "routed_total": self.routed_total,
        }

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "CellState":
        cs = cls(name)
        cs.state = d.get("state", CELL_HEALTHY)
        cs.failure_streak = int(d.get("failure_streak", 0) or 0)
        cs.open_since = d.get("open_since")
        cs.last_probe_at = d.get("last_probe_at")
        cs.probes = int(d.get("probes", 0) or 0)
        cs.digest = parse_cell_digest(d.get("digest"))
        cs.booked = int(d.get("booked", 0) or 0)
        cs.booked_by_gen = {str(g): int(v) for g, v in
                            (d.get("booked_by_gen") or {}).items()}
        cs.routed_total = int(d.get("routed_total", 0) or 0)
        return cs


class GlobalRouter:
    def __init__(self, cells: Iterable[str], now: Callable[[], float],
                 failure_threshold: int = FAILURE_THRESHOLD,
                 probe_base_s: float = PROBE_BACKOFF_BASE_S,
                 probe_cap_s: float = PROBE_BACKOFF_CAP_S,
                 digest_half_life_s: float = DIGEST_HALF_LIFE_S,
                 condemnation_horizon_s: float = CONDEMNATION_HORIZON_S):
        self.now = now
        self.failure_threshold = max(1, int(failure_threshold))
        self.probe_base_s = float(probe_base_s)
        self.probe_cap_s = float(probe_cap_s)
        self.digest_half_life_s = float(digest_half_life_s)
        self.condemnation_horizon_s = float(condemnation_horizon_s)
        self.cells: Dict[str, CellState] = {
            name: CellState(name) for name in sorted(cells)}

    # -- digest ingest ------------------------------------------------------

    def observe_digest(self, raw) -> bool:
        """Fold one published digest. Dedupe is by (cell, seq): an echo
        or an out-of-order older publish is dropped, which is what makes
        the held view — and therefore every decision — independent of
        arrival order. Returns True when the view advanced."""
        d = parse_cell_digest(raw)
        if d is None:
            return False
        cs = self.cells.get(d["cell"])
        if cs is None:
            return False
        if cs.digest is not None and d["seq"] <= cs.digest["seq"]:
            return False
        cs.digest = d
        # a fresh publish supersedes the router's own booking ledger:
        # the cell has since counted its own leases
        cs.booked = 0
        cs.booked_by_gen = {}
        return True

    # -- breaker ------------------------------------------------------------

    def record_success(self, cell: str) -> None:
        cs = self.cells.get(cell)
        if cs is None:
            return
        healed = cs.state != CELL_HEALTHY
        cs.state = CELL_HEALTHY
        cs.failure_streak = 0
        cs.open_since = None
        cs.last_probe_at = None
        cs.probes = 0
        if healed:
            self._export_state(cs)

    def record_failure(self, cell: str) -> None:
        cs = self.cells.get(cell)
        if cs is None:
            return
        now = self.now()
        if cs.state == CELL_OPEN:
            # a failed probe: back off further, stay Open
            cs.probes += 1
            cs.last_probe_at = now
            OPERATOR_METRICS.federation_breaker_probes.labels(
                cell=cell).inc()
            return
        cs.failure_streak += 1
        if cs.failure_streak >= self.failure_threshold:
            cs.state = CELL_OPEN
            cs.open_since = now
            cs.last_probe_at = now
            cs.probes = 0
        else:
            cs.state = CELL_SUSPECT
        self._export_state(cs)

    def probe_due(self, cell: str) -> bool:
        """Whether an Open cell's next backoff probe has come due:
        base * 2^probes, capped — the breaker's only path back."""
        cs = self.cells.get(cell)
        if cs is None or cs.state != CELL_OPEN:
            return True
        wait = min(self.probe_cap_s,
                   self.probe_base_s * (2 ** min(cs.probes, 16)))
        anchor = cs.last_probe_at if cs.last_probe_at is not None \
            else (cs.open_since or 0.0)
        return self.now() >= anchor + wait

    def cells_to_contact(self) -> List[str]:
        """Which cells this pass should talk to: every non-Open cell,
        plus any Open cell whose probe is due."""
        return [name for name in sorted(self.cells)
                if self.cells[name].state != CELL_OPEN
                or self.probe_due(name)]

    def condemned_cells(self) -> List[str]:
        """Cells Open continuously past the condemnation horizon —
        their bound slices are cross-cell migration candidates."""
        now = self.now()
        return [name for name in sorted(self.cells)
                if self.cells[name].state == CELL_OPEN
                and self.cells[name].open_since is not None
                and now - self.cells[name].open_since
                >= self.condemnation_horizon_s]

    # -- scoring ------------------------------------------------------------

    def _age_discount(self, cs: CellState) -> float:
        if cs.digest is None:
            return 0.0
        age = max(0.0, self.now() - float(cs.digest.get("at", 0.0)))
        return 1.0 / (1.0 + age / self.digest_half_life_s)

    def _free_for(self, cs: CellState, chips: int,
                  generation: Optional[str]) -> int:
        if cs.digest is None:
            return 0
        if generation:
            free = int((cs.digest.get("headroom") or {})
                       .get(generation, 0))
            free -= cs.booked_by_gen.get(generation, 0)
        else:
            free = int(cs.digest.get("chips_free", 0)) - cs.booked
        return max(0, free)

    def score(self, cell: str, chips: int = 0,
              generation: Optional[str] = None) -> float:
        """Digest score for one cell: gen-aware free headroom, shaved by
        fragmentation and condemned count, discounted by digest age and
        the Suspect penalty. Pure function of (held digest, booking,
        breaker state, now) — no RNG, no iteration order."""
        cs = self.cells.get(cell)
        if cs is None or cs.state == CELL_OPEN or cs.digest is None:
            return 0.0
        free = self._free_for(cs, chips, generation)
        if free < max(1, chips):
            return 0.0
        frag = float(cs.digest.get("fragmentation", 0.0))
        condemned = int(cs.digest.get("condemned", 0))
        hosts = max(1, int(cs.digest.get("hosts", 1)))
        s = free * (1.0 - 0.5 * frag) * (1.0 - min(1.0, condemned / hosts))
        s *= self._age_discount(cs)
        if cs.state == CELL_SUSPECT:
            s *= SUSPECT_PENALTY
        return s

    def route(self, chips: int, generation: Optional[str] = None,
              locality: Optional[str] = None) -> Optional[dict]:
        """Choose a cell for a request of ``chips`` (optionally pinned
        to a generation, optionally carrying a data-locality preferred
        cell). Open cells never score. Returns the decision record, or
        None when no cell can take the request right now (it stays on
        the global queue). Books the routed chips against the winner's
        digest so back-to-back routes between publishes don't stampede
        one cell."""
        best_name, best_score = None, 0.0
        scores = {}
        for name in sorted(self.cells):
            s = self.score(name, chips=chips, generation=generation)
            scores[name] = s
            if s > best_score:
                best_name, best_score = name, s
        if best_name is None:
            OPERATOR_METRICS.federation_route_decisions.labels(
                outcome="no-cell").inc()
            return None
        reason = "digest-score"
        chosen = best_name
        if locality and locality != best_name:
            ls = scores.get(locality, 0.0)
            if ls >= LOCALITY_TOLERANCE * best_score and ls > 0.0:
                chosen, reason = locality, "locality"
        cs = self.cells[chosen]
        cs.booked += max(1, chips)
        if generation:
            cs.booked_by_gen[generation] = \
                cs.booked_by_gen.get(generation, 0) + max(1, chips)
        cs.routed_total += 1
        OPERATOR_METRICS.federation_route_decisions.labels(
            outcome="routed").inc()
        return {
            "cell": chosen,
            "score": round(scores[chosen], 4),
            "state": cs.state,
            "seq": cs.digest["seq"] if cs.digest else -1,
            "reason": reason,
        }

    # -- state persistence (runtime/snapshot.py federation section) ---------

    def snapshot(self) -> dict:
        """JSON-able router state: breaker ledgers + held digests. What
        a successor needs to keep partition decisions coherent across a
        router crash — in-flight migrations recover from the requests'
        own status, not from here."""
        return {
            "v": ROUTER_STATE_VERSION,
            "cells": {name: self.cells[name].to_dict()
                      for name in sorted(self.cells)},
        }

    def adopt(self, state: Optional[dict]) -> bool:
        """Warm-restore from :meth:`snapshot` output. Unknown versions
        and malformed payloads are refused (cold breaker state is safe;
        a half-parsed one is not)."""
        if not isinstance(state, dict) \
                or state.get("v") != ROUTER_STATE_VERSION:
            return False
        cells = state.get("cells")
        if not isinstance(cells, dict):
            return False
        for name, d in cells.items():
            if name in self.cells and isinstance(d, dict):
                self.cells[name] = CellState.from_dict(name, d)
        return True

    @classmethod
    def restore(cls, state: dict, cells: Iterable[str],
                now: Callable[[], float], **kwargs) -> "GlobalRouter":
        router = cls(cells, now=now, **kwargs)
        router.adopt(state)
        return router

    # -- observability ------------------------------------------------------

    def _export_state(self, cs: CellState) -> None:
        OPERATOR_METRICS.federation_cell_state.labels(cell=cs.name).set(
            {CELL_HEALTHY: 0, CELL_SUSPECT: 1, CELL_OPEN: 2}[cs.state])

    def export_metrics(self) -> None:
        now = self.now()
        for cs in self.cells.values():
            self._export_state(cs)
            age = (now - float(cs.digest.get("at", 0.0))
                   if cs.digest is not None else -1.0)
            OPERATOR_METRICS.federation_digest_age.labels(
                cell=cs.name).set(age)

    def report(self) -> dict:
        """The cells.json / `tpuop-cfg cells` payload: one row per cell
        with its breaker state, probe ledger, and held digest."""
        now = self.now()
        rows = {}
        for name in sorted(self.cells):
            cs = self.cells[name]
            rows[name] = {
                "state": cs.state,
                "failure_streak": cs.failure_streak,
                "open_for_s": (round(now - cs.open_since, 1)
                               if cs.open_since is not None else None),
                "probes": cs.probes,
                "routed_total": cs.routed_total,
                "digest_age_s": (round(now - float(cs.digest["at"]), 1)
                                 if cs.digest is not None else None),
                "digest": cs.digest,
            }
        return {"cells": rows,
                "condemnation_horizon_s": self.condemnation_horizon_s}


def cells_report(client, namespace: str,
                 router: Optional[GlobalRouter] = None) -> dict:
    """Cluster-derived federation report (the must-gather
    ``federation/cells.json`` source): SliceRequests grouped by their
    cell pin, merged with the live router's breaker view when one is
    reachable. Works against any client — a cluster with no federation
    plane yields an empty, well-formed report."""
    cells: Dict[str, dict] = {}
    unrouted = []
    for cr in sorted(client.list(V1ALPHA1, KIND_SLICE_REQUEST,
                                 ListOptions(namespace=namespace)),
                     key=name_of):
        pin = annotations_of(cr).get(L.CELL_PIN)
        row = {
            "name": name_of(cr),
            "phase": get_nested(cr, "status", "phase") or "Pending",
            "chips": get_nested(cr, "spec", "chips", default=0) or 0,
        }
        if pin:
            ent = cells.setdefault(pin, {"requests": [], "chips": 0})
            ent["requests"].append(row)
            ent["chips"] += int(row["chips"])
        else:
            unrouted.append(row)
    out = {"cells": {k: cells[k] for k in sorted(cells)},
           "unrouted": unrouted}
    if router is not None:
        out["router"] = router.report()
    return out
