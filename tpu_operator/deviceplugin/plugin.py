"""TPU kubelet device plugin: advertises google.com/tpu.

The k8s-device-plugin slot (SURVEY.md section 2.4 row 3): a gRPC server on
a unix socket under /var/lib/kubelet/device-plugins/ that registers with
kubelet and serves the v1beta1 DevicePlugin API. One google.com/tpu is
advertised per discovered chip; Allocate hands containers their
/dev/accel* device nodes plus the TPU env contract.

The gRPC services are wired with generic handlers over the
protoc-generated message classes (api_pb2) — no grpc codegen plugin is
required at build time.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time
from concurrent import futures
from typing import Callable, Dict, List, Optional

import grpc

from . import api_pb2 as pb

log = logging.getLogger("tpu_device_plugin")

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

KUBELET_SOCKET_DIR = "/var/lib/kubelet/device-plugins"
KUBELET_SOCKET = "kubelet.sock"
PLUGIN_SOCKET = "tpu-device-plugin.sock"
API_VERSION = "v1beta1"
DEFAULT_RESOURCE = "google.com/tpu"

_SVC_PLUGIN = "v1beta1.DevicePlugin"
_SVC_REGISTRATION = "v1beta1.Registration"


# ---------------------------------------------------------------------------
# device discovery
# ---------------------------------------------------------------------------


def discover_chips() -> List[str]:
    """Chip IDs on this host. Sources: TPU_FAKE_CHIPS (tests), then
    /dev/accel* (TPU VMs), then /dev/vfio (passthrough)."""
    fake = os.environ.get("TPU_FAKE_CHIPS")
    if fake:
        return [f"accel{i}" for i in range(int(fake))]
    paths = sorted(glob.glob("/dev/accel*"))
    if not paths:
        paths = sorted(p for p in glob.glob("/dev/vfio/*")
                       if os.path.basename(p) != "vfio")
    return [os.path.basename(p) for p in paths]


REPLICA_SEP = "::"  # replica ID convention: <unit>::r<j>


def sharing_replicas() -> int:
    """Replication factor for time-shared chips (the MPS-control-daemon
    slot, SURVEY.md 2.2 #7: CUDA MPS shares one GPU between processes; the
    TPU analog is advertising each allocation unit N times so N pods can
    time-share a chip). 1 = exclusive."""
    try:
        n = int(os.environ.get("SHARING_REPLICAS", "1"))
    except ValueError:
        return 1
    return max(1, n)


# ---------------------------------------------------------------------------
# per-node plugin config (devicePlugin.config ConfigMap slot)
# ---------------------------------------------------------------------------


class PluginConfig:
    """One named config from the devicePlugin.config ConfigMap
    (handleDevicePluginConfig, object_controls.go:2442-2552). The
    reference ships a config-manager init+sidecar that picks a config by
    node label and SIGHUPs the plugin through a shared PID namespace;
    here the plugin process itself selects and live-reloads — one
    process, no shareProcessNamespace.

    Config keys (YAML): ``sharingPolicy`` (exclusive|time-shared) and
    ``sharingReplicas`` — per-node overrides of the cluster-wide spec."""

    def __init__(self, name: str, sharing_policy: str = "exclusive",
                 sharing_replicas: int = 1):
        self.name = name
        self.sharing_policy = sharing_policy
        self.sharing_replicas = max(1, int(sharing_replicas))

    @property
    def effective_replicas(self) -> int:
        return self.sharing_replicas \
            if self.sharing_policy == "time-shared" else 1

    def __eq__(self, other):
        return isinstance(other, PluginConfig) and vars(self) == vars(other)

    def __repr__(self):
        return (f"PluginConfig({self.name!r}, {self.sharing_policy!r}, "
                f"replicas={self.sharing_replicas})")


def parse_plugin_config(name: str, text: str) -> PluginConfig:
    import yaml

    raw = yaml.safe_load(text) or {}
    if not isinstance(raw, dict):
        raise ValueError(f"config {name!r} must be a mapping")
    policy = raw.get("sharingPolicy", "exclusive")
    if policy not in ("exclusive", "time-shared"):
        raise ValueError(f"config {name!r}: unknown sharingPolicy "
                         f"{policy!r} (exclusive|time-shared)")
    try:
        replicas = int(raw.get("sharingReplicas") or 1)
    except (TypeError, ValueError):
        raise ValueError(f"config {name!r}: sharingReplicas must be an "
                         f"integer, got {raw.get('sharingReplicas')!r}")
    return PluginConfig(name, sharing_policy=policy,
                        sharing_replicas=replicas)


def read_plugin_config(config_dir: str, name: str) -> PluginConfig:
    """Load one named config from the mounted ConfigMap dir (kubelet keeps
    the mount in sync with the ConfigMap, so re-reading sees updates)."""
    with open(os.path.join(config_dir, name)) as f:
        return parse_plugin_config(name, f.read())


def discover_devices(replicas: Optional[int] = None) -> List[pb.Device]:
    """Advertised allocation units. Without a slice config each chip is one
    device; with one (written by the topology manager,
    topology/manager.py), each sub-slice group is one device — allocating
    a unit grants all its chips, preserving ICI locality. With sharing
    enabled every unit is advertised ``sharing_replicas()`` times (or
    ``replicas`` when the caller resolved a per-node plugin config).

    Fenced chips (isolation/fencing.py) never appear here: they belong to
    the isolated plugin's pool — the advertisement-level equivalent of a
    GPU bound to vfio-pci being invisible to the default device plugin."""
    from ..isolation.fencing import fenced_chips

    fenced = set(fenced_chips())
    groups = slice_groups()
    if groups:
        units = [u for u, members in groups.items()
                 if not fenced.intersection(members)]
    else:
        units = [c for c in discover_chips() if c not in fenced]
    n = sharing_replicas() if replicas is None else max(1, replicas)
    if n > 1:
        return [pb.Device(ID=f"{u}{REPLICA_SEP}r{j}", health="Healthy")
                for u in units for j in range(n)]
    return [pb.Device(ID=u, health="Healthy") for u in units]


def slice_groups() -> Optional[Dict[str, List[str]]]:
    """slice-unit ID -> member chip IDs, from the topology manager's file."""
    from ..topology.manager import DEFAULT_SLICE_FILE, read_slice_file

    cfg = read_slice_file(os.environ.get("TPU_SLICE_FILE",
                                         DEFAULT_SLICE_FILE))
    if not cfg or not cfg.get("groups"):
        return None
    if int(cfg.get("subslices", 1)) <= 1:
        return None  # full profile: advertise per chip
    return {f"slice{i}": g for i, g in enumerate(cfg["groups"])}


def expand_to_chips(device_ids: List[str]) -> List[str]:
    """Replica IDs collapse to their unit; slice units expand to member
    chips; duplicates (two replicas of one chip in a request) dedup."""
    groups = slice_groups() or {}
    chips: List[str] = []
    for device_id in device_ids:
        unit = device_id.split(REPLICA_SEP, 1)[0]
        for chip in groups.get(unit, [unit]):
            if chip not in chips:
                chips.append(chip)
    return chips


def device_host_path(device_id: str) -> str:
    if device_id.startswith("accel"):
        return f"/dev/{device_id}"
    return f"/dev/vfio/{device_id}"


# ---------------------------------------------------------------------------
# per-device health (the NVML/XID slot behind object_controls.go:1310)
# ---------------------------------------------------------------------------


def health_engine_chip_status(timeout: float = 2.0) -> Dict[str, str]:
    """chip_id -> ok|warn|fail from the node's health engine
    (``TPU_HEALTH_ENGINE_INFO``, the DCGM_REMOTE_HOSTENGINE_INFO analog).
    The reference plugin drives per-device health from NVML/XID events;
    here the health engine owns the telemetry session and this plugin
    consumes its verdicts. Unset env or an unreachable engine returns {}
    — no verdicts, not all-unhealthy: a telemetry outage must not
    deschedule a node's TPUs."""
    info = os.environ.get("TPU_HEALTH_ENGINE_INFO")
    if not info:
        return {}
    import urllib.error
    import urllib.request

    url = f"http://{info}/v1/health"
    try:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                doc = json.loads(r.read())
        except urllib.error.HTTPError as e:
            # the engine answers 503 when any chip FAILs — that body IS
            # the verdict payload, not an outage
            doc = json.loads(e.read())
    except Exception as e:
        log.warning("health engine %s unreachable (%s); no verdicts", info, e)
        return {}
    return {c.get("chip_id", ""): c.get("status", "ok")
            for c in doc.get("chips", [])}


FAIL_STATUS = "fail"  # health_engine.FAIL without importing jax-adjacent code


# ---------------------------------------------------------------------------
# isolated pool (sandbox-device-plugin slot)
# ---------------------------------------------------------------------------


def discover_isolated_devices() -> List[pb.Device]:
    """The isolated plugin's inventory: vTPU devices when the vTPU
    manager has published a config (the vGPU slot), else the fenced
    chips whole (the passthrough slot). Empty until chip-fencing runs —
    the isolated plugin has nothing to serve before the fence exists."""
    from ..isolation.fencing import fenced_chips
    from ..isolation.vtpu import read_vtpu_file

    vtpu = read_vtpu_file()
    if vtpu and vtpu.get("devices"):
        return [pb.Device(ID=d["id"], health="Healthy")
                for d in vtpu["devices"]]
    return [pb.Device(ID=c, health="Healthy") for c in fenced_chips()]


def vtpu_lookup() -> Dict[str, dict]:
    """vTPU device ID -> its inventory entry (chip, hbm_mb, fraction)."""
    from ..isolation.vtpu import read_vtpu_file

    vtpu = read_vtpu_file()
    if not vtpu:
        return {}
    return {d["id"]: d for d in vtpu.get("devices", [])}


# ---------------------------------------------------------------------------
# gRPC service wiring (generic handlers over api_pb2 messages)
# ---------------------------------------------------------------------------


def _replica_sort_key(device_id: str):
    """(replica index, unit): all r0s across units sort before any r1."""
    unit, _, rep = device_id.partition(REPLICA_SEP)
    try:
        idx = int(rep.lstrip("r")) if rep else 0
    except ValueError:
        idx = 0
    return (idx, unit)


def _unary(fn: Callable, req_cls, resp_cls) -> grpc.RpcMethodHandler:
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString)


def _stream(fn: Callable, req_cls, resp_cls) -> grpc.RpcMethodHandler:
    return grpc.unary_stream_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString)


class TPUDevicePlugin:
    """The DevicePlugin service + kubelet registration client."""

    def __init__(self, resource_name: str = DEFAULT_RESOURCE,
                 socket_dir: str = KUBELET_SOCKET_DIR,
                 plugin_socket: str = PLUGIN_SOCKET,
                 discover: Optional[Callable[[], List[pb.Device]]] = None,
                 health_interval_s: float = 30.0,
                 config_dir: Optional[str] = None,
                 default_config: Optional[str] = None,
                 config_selector: Optional[
                     Callable[[], Optional[str]]] = None,
                 health_source: Optional[
                     Callable[[], Dict[str, str]]] = None):
        self.resource_name = resource_name
        # chip_id -> ok|warn|fail; default consults the node's health
        # engine when TPU_HEALTH_ENGINE_INFO is set
        self.health_source = health_source or health_engine_chip_status
        # unit -> last advertised device IDs: a unit that vanishes from
        # discovery without a legitimate reason (fenced away, slice
        # regrouping) is re-advertised Unhealthy instead of silently
        # shrinking the list — kubelet then drops allocatable and stops
        # scheduling, and the operator can see WHY
        self._seen_units: Dict[str, List[str]] = {}
        self._group_sig: Optional[tuple] = None
        self.socket_dir = socket_dir
        self.plugin_socket = plugin_socket
        self.discover = discover or self._default_discover
        self.health_interval_s = health_interval_s
        # per-node config ConfigMap (devicePlugin.config slot): dir where
        # the ConfigMap is mounted, default key, and the selector that
        # names this node's config (usually the node-label watcher built
        # by the CLI entrypoint; None -> env/default fallbacks)
        self.config_dir = config_dir \
            if config_dir is not None \
            else os.environ.get("TPU_PLUGIN_CONFIG_DIR")
        self.default_config = default_config \
            if default_config is not None \
            else os.environ.get("TPU_PLUGIN_CONFIG_DEFAULT")
        self.config_selector = config_selector
        self.plugin_config: Optional[PluginConfig] = None
        self._devices: List[pb.Device] = []
        self._cond = threading.Condition()
        self._stopped = threading.Event()
        self._reregister = threading.Event()  # force a kubelet re-register
        self._server: Optional[grpc.Server] = None
        self.allocations: List[Dict] = []  # audit trail of Allocate calls

    # -- DevicePlugin RPCs -------------------------------------------------

    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=True)

    def ListAndWatch(self, request, context):
        """Initial device list, then an update whenever discovery changes
        (kubelet keeps this stream open for the plugin's lifetime). The
        yield happens OUTSIDE the condition lock: gRPC may park the
        generator mid-send on a stalled peer, and holding the lock there
        would deadlock refresh_devices()/stop()."""
        last: Optional[List[tuple]] = None
        while not self._stopped.is_set():
            response = None
            with self._cond:
                snapshot = [(d.ID, d.health) for d in self._devices]
                if snapshot != last:
                    last = snapshot
                    response = pb.ListAndWatchResponse(
                        devices=list(self._devices))
                else:
                    self._cond.wait(timeout=1.0)
            if response is not None:
                yield response

    def GetPreferredAllocation(self, request, context):
        """Prefer low-numbered contiguous chips — neighboring chips share
        ICI links, so contiguous allocation preserves torus locality. With
        sharing enabled, spread across distinct units first so one request
        never time-shares a chip with itself."""
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            ids = sorted(creq.available_deviceIDs, key=_replica_sort_key)
            must = list(creq.must_include_deviceIDs)
            picked = must + [i for i in ids if i not in must]
            resp.container_responses.add(
                deviceIDs=picked[:creq.allocation_size])
        return resp

    def Allocate(self, request, context):
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            ids = list(creq.devicesIDs)
            chips = expand_to_chips(ids)  # slice units -> member chips
            cresp = resp.container_responses.add()
            for chip in chips:
                host = device_host_path(chip)
                cresp.devices.add(container_path=host, host_path=host,
                                  permissions="rw")
            # the TPU env contract workloads expect
            cresp.envs["TPU_VISIBLE_CHIPS"] = ",".join(
                c.removeprefix("accel") for c in chips)
            cresp.envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,1,{len(chips)}"
            cresp.envs["TPU_RUNTIME_METRICS_PORTS"] = ""
            self.allocations.append({"devices": ids, "chips": chips})
            log.info("allocated %s -> chips %s", ids, chips)
        return resp

    def PreStartContainer(self, request, context):
        return pb.PreStartContainerResponse()

    # -- lifecycle ---------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(_SVC_PLUGIN, {
            "GetDevicePluginOptions": _unary(self.GetDevicePluginOptions,
                                             pb.Empty,
                                             pb.DevicePluginOptions),
            "ListAndWatch": _stream(self.ListAndWatch, pb.Empty,
                                    pb.ListAndWatchResponse),
            "GetPreferredAllocation": _unary(self.GetPreferredAllocation,
                                             pb.PreferredAllocationRequest,
                                             pb.PreferredAllocationResponse),
            "Allocate": _unary(self.Allocate, pb.AllocateRequest,
                               pb.AllocateResponse),
            "PreStartContainer": _unary(self.PreStartContainer,
                                        pb.PreStartContainerRequest,
                                        pb.PreStartContainerResponse),
        })

    @property
    def socket_path(self) -> str:
        return os.path.join(self.socket_dir, self.plugin_socket)

    def _default_discover(self) -> List[pb.Device]:
        cfg = self.plugin_config
        return discover_devices(
            replicas=cfg.effective_replicas if cfg else None)

    def reload_plugin_config(self) -> bool:
        """Re-resolve this node's named config; True if it changed. Any
        failure — selector (apiserver read error) or config file
        (missing/invalid) — keeps the last good config rather than
        flapping the advertised inventory: shrinking kubelet capacity
        because of a transient read error would reject pods for nothing
        (the reference's FALLBACK_STRATEGIES=empty never bricks a
        running plugin either)."""
        if not self.config_dir:
            return False
        if self.config_selector is not None:
            try:
                selected = self.config_selector()
            except Exception as e:
                log.warning("config selector failed (%s); keeping %r",
                            e, self.plugin_config)
                return False
        else:
            selected = None
        # selector None = genuinely unlabeled -> static env, then default
        name = selected or os.environ.get("TPU_PLUGIN_CONFIG_SELECT") \
            or self.default_config or None
        if not name:
            changed = self.plugin_config is not None
            self.plugin_config = None
            return changed
        try:
            cfg = read_plugin_config(self.config_dir, name)
        except Exception as e:  # OSError, YAMLError, ValueError, TypeError
            log.warning("plugin config %r unusable (%s); keeping %r",
                        name, e, self.plugin_config)
            return False
        if cfg != self.plugin_config:
            log.info("plugin config now %r (was %r)", cfg,
                     self.plugin_config)
            self.plugin_config = cfg
            return True
        return False

    def _chip_status(self) -> Dict[str, str]:
        try:
            return self.health_source() or {}
        except Exception as e:
            log.warning("health source failed (%s); no verdicts", e)
            return {}

    def _apply_health(self, devices: List[pb.Device]) -> List[pb.Device]:
        """Health-engine verdicts + vanished-unit tracking. A unit whose
        member chip FAILs goes Unhealthy; a unit that disappears from
        discovery stays advertised Unhealthy until it returns (or was
        legitimately removed: fenced into the isolated pool, or the slice
        grouping changed so its unit ID no longer exists)."""
        from ..isolation.fencing import fenced_chips

        status = self._chip_status()
        groups = slice_groups() or {}
        try:
            fenced = set(fenced_chips())
        except Exception:
            fenced = set()
        out: List[pb.Device] = []
        seen_now: Dict[str, List[str]] = {}
        for d in devices:
            unit = d.ID.split(REPLICA_SEP, 1)[0]
            members = groups.get(unit, [unit])
            bad = any(status.get(m) == FAIL_STATUS for m in members)
            out.append(pb.Device(
                ID=d.ID, health=UNHEALTHY if bad else d.health))
            seen_now.setdefault(unit, []).append(d.ID)
        # a slice-regroup renames every unit; stale unit IDs are not
        # vanished hardware — reset tracking instead of ghost-advertising
        sig = tuple(sorted(groups)) if groups else None
        if sig != self._group_sig:
            self._group_sig = sig
            self._seen_units = {}
        for unit, ids in self._seen_units.items():
            if unit in seen_now:
                continue
            if set(groups.get(unit, [unit])) & fenced:
                continue  # moved to the isolated pool, not dead
            for device_id in ids:
                out.append(pb.Device(ID=device_id, health=UNHEALTHY))
            seen_now[unit] = list(ids)
            log.warning("unit %s vanished from discovery; advertising "
                        "Unhealthy", unit)
        self._seen_units = seen_now
        return out

    def refresh_devices(self) -> None:
        self.reload_plugin_config()
        devices = self._apply_health(self.discover())
        with self._cond:
            if [(d.ID, d.health) for d in devices] != \
                    [(d.ID, d.health) for d in self._devices]:
                self._devices = devices
                log.info("device inventory: %s",
                         [(d.ID, d.health) for d in devices])
            self._cond.notify_all()

    def _health_loop(self):
        while not self._stopped.wait(self.health_interval_s):
            try:
                self.refresh_devices()
            except Exception:
                log.exception("device re-discovery failed")

    def _converge_node_regime(self) -> None:
        """This plugin is only scheduled on container-routed nodes, so
        isolation files found here are leftovers from a node that left
        the isolated plane (the fencing/vtpu DaemonSets are gone and
        can't withdraw them — a preStop would instead fire on every pod
        restart and briefly re-admit fenced chips). Withdrawing them at
        startup is the convergence point for the plane's exit path."""
        from ..isolation.fencing import DEFAULT_FENCING_FILE
        from ..isolation.vtpu import DEFAULT_VTPU_FILE

        for env_key, default in (("TPU_FENCING_FILE", DEFAULT_FENCING_FILE),
                                 ("TPU_VTPU_FILE", DEFAULT_VTPU_FILE)):
            path = os.environ.get(env_key, default)
            try:
                os.unlink(path)
                log.info("withdrew stale isolation file %s (node is "
                         "container-routed)", path)
            except FileNotFoundError:
                pass

    def start(self) -> None:
        os.makedirs(self.socket_dir, exist_ok=True)
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        self._converge_node_regime()
        self.refresh_devices()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        threading.Thread(target=self._health_loop, daemon=True).start()
        log.info("device plugin serving on %s (%d devices)",
                 self.socket_path, len(self._devices))

    def register_with_kubelet(self, kubelet_socket: Optional[str] = None,
                              timeout: float = 10.0) -> None:
        """Dial kubelet's registration socket and announce ourselves."""
        target = f"unix://{kubelet_socket or os.path.join(self.socket_dir, KUBELET_SOCKET)}"
        with grpc.insecure_channel(target) as channel:
            grpc.channel_ready_future(channel).result(timeout=timeout)
            register = channel.unary_unary(
                f"/{_SVC_REGISTRATION}/Register",
                request_serializer=pb.RegisterRequest.SerializeToString,
                response_deserializer=pb.Empty.FromString)
            register(pb.RegisterRequest(
                version=API_VERSION,
                endpoint=self.plugin_socket,
                resource_name=self.resource_name,
                options=pb.DevicePluginOptions(
                    get_preferred_allocation_available=True)),
                timeout=timeout)
        log.info("registered %s with kubelet", self.resource_name)

    def stop(self) -> None:
        self._stopped.set()
        with self._cond:
            self._cond.notify_all()
        if self._server:
            self._server.stop(grace=1.0)

    def serve_forever(self, register: bool = True) -> None:
        """Entrypoint for the DaemonSet container: serve, register, and
        recover from kubelet restarts. A restarting kubelet wipes the
        device-plugins dir (deleting OUR socket) and recreates
        kubelet.sock — so on either signal the plugin re-binds its socket
        first, then re-registers; re-registering alone would advertise a
        dead endpoint."""
        self.start()
        kubelet_sock = os.path.join(self.socket_dir, KUBELET_SOCKET)
        registered_ino = None
        while not self._stopped.is_set():
            if not os.path.exists(self.socket_path):
                log.warning("plugin socket vanished (kubelet restart?); "
                            "re-binding %s", self.socket_path)
                if self._server:
                    self._server.stop(grace=1.0)
                self._stopped.clear()
                self.start()
                registered_ino = None  # force re-registration below
            if register and os.path.exists(kubelet_sock):
                try:
                    ino = os.stat(kubelet_sock).st_ino
                except OSError:
                    ino = None
                if ino is not None and (ino != registered_ino
                                        or self._reregister.is_set()):
                    try:
                        self.register_with_kubelet()
                        registered_ino = ino
                        self._reregister.clear()
                    except Exception as e:
                        log.warning("kubelet registration failed: %s", e)
            self._stopped.wait(5.0)


class IsolatedTPUDevicePlugin(TPUDevicePlugin):
    """Second plugin instance serving the fenced pool (the
    sandbox-device-plugin slot, object_controls.go:1472): whole fenced
    chips as google.com/tpu-isolated, or vTPU fractions as
    google.com/vtpu when the vTPU manager has published a profile.

    A vTPU allocation grants the backing chip's device node plus a
    memory-budget env contract (XLA_PYTHON_CLIENT_MEM_FRACTION /
    TPU_HBM_LIMIT_MB) that the XLA client allocator enforces — the
    runtime-level stand-in for the mediated-device isolation vGPU gets
    from the kernel."""

    ISOLATED_RESOURCE = "google.com/tpu-isolated"
    VTPU_RESOURCE = "google.com/vtpu"
    ISOLATED_SOCKET = "tpu-isolated-device-plugin.sock"

    def __init__(self, resource_name: Optional[str] = None,
                 vtpu_resource_name: Optional[str] = None, **kw):
        self._whole_resource = resource_name or self.ISOLATED_RESOURCE
        self._vtpu_resource = vtpu_resource_name or self.VTPU_RESOURCE
        kw.setdefault("plugin_socket", self.ISOLATED_SOCKET)
        kw.setdefault("discover", discover_isolated_devices)
        super().__init__(resource_name=self._pick_resource(), **kw)

    def _pick_resource(self) -> str:
        return self._vtpu_resource if vtpu_lookup() else self._whole_resource

    def _converge_node_regime(self) -> None:
        # the isolated plugin runs where the fence BELONGS — never
        # withdraw it here
        pass

    def _apply_health(self, devices: List[pb.Device]) -> List[pb.Device]:
        # vTPU device IDs carry their backing chip's health; no
        # vanished-unit tracking here — leaving this pool (unfencing,
        # profile withdrawal) is the normal exit path, not a dead chip
        status = self._chip_status()
        vtpus = vtpu_lookup()
        return [pb.Device(
            ID=d.ID,
            health=UNHEALTHY
            if status.get((vtpus.get(d.ID) or {}).get("chip", d.ID))
            == FAIL_STATUS else d.health)
            for d in devices]

    def refresh_devices(self) -> None:
        # the advertised resource follows the pool's mode: flipping a node
        # between whole-chip and vTPU profiles must RE-REGISTER with
        # kubelet (kubelet binds this endpoint to the resource name given
        # at Register time — a new device list alone would be advertised
        # under the old resource)
        picked = self._pick_resource()
        if picked != self.resource_name:
            self.resource_name = picked
            self._reregister.set()
            log.info("isolated pool mode changed; re-registering as %s",
                     picked)
        super().refresh_devices()

    def Allocate(self, request, context):
        from ..isolation.fencing import fenced_chips

        vtpus = vtpu_lookup()
        fenced = set(fenced_chips())
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            ids = list(creq.devicesIDs)
            chips: List[str] = []
            per_chip_hbm: Dict[str, int] = {}
            per_chip_fraction: Dict[str, float] = {}
            any_vtpu = False
            for device_id in ids:
                entry = vtpus.get(device_id)
                if entry is None and device_id not in fenced:
                    # a withdrawn vTPU id (or never-fenced chip) must fail
                    # the RPC cleanly, not fabricate a /dev path that
                    # doesn't exist and strand the container at mount time
                    msg = (f"unknown isolated device {device_id!r}: not in "
                           f"the vTPU inventory and not a fenced chip "
                           f"(inventory withdrawn?)")
                    log.error("%s", msg)
                    if context is not None:
                        context.abort(grpc.StatusCode.NOT_FOUND, msg)
                    raise ValueError(msg)
                chip = entry["chip"] if entry else device_id
                if chip not in chips:
                    chips.append(chip)
                if entry:
                    any_vtpu = True
                    per_chip_hbm[chip] = per_chip_hbm.get(chip, 0) + int(
                        entry.get("hbm_mb") or 0)
                    per_chip_fraction[chip] = per_chip_fraction.get(
                        chip, 0.0) + float(entry.get("fraction") or 0.0)
            cresp = resp.container_responses.add()
            for chip in chips:
                host = device_host_path(chip)
                cresp.devices.add(container_path=host, host_path=host,
                                  permissions="rw")
            cresp.envs["TPU_VISIBLE_CHIPS"] = ",".join(
                c.removeprefix("accel") for c in chips)
            cresp.envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,1,{len(chips)}"
            cresp.envs["TPU_WORKLOAD_ISOLATION"] = "isolated"
            if any_vtpu:
                # XLA's fraction applies PER DEVICE, so the safe value is
                # the smallest per-chip share in the request — averaging
                # would over-grant on chips where this pod owns less
                hbm_total = sum(per_chip_hbm.values())
                if hbm_total:
                    cresp.envs["TPU_HBM_LIMIT_MB"] = str(hbm_total)
                fractions = [f for f in per_chip_fraction.values() if f > 0]
                if fractions and min(fractions) < 1.0:
                    cresp.envs["XLA_PYTHON_CLIENT_MEM_FRACTION"] = (
                        f"{min(min(fractions), 1.0):.4f}")
            self.allocations.append({"devices": ids, "chips": chips})
            log.info("isolated allocation %s -> chips %s", ids, chips)
        return resp
