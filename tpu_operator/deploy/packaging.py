"""Deployment manifest generation — the Helm-chart slot.

The reference packages via a 546-line values.yaml Helm chart rendering the
ClusterPolicy CR plus operator Deployment/RBAC
(deployments/gpu-operator/). Here the same artifacts are generated from
code, so they cannot drift from the API types:

    tpuop-cfg generate crds     # both CRDs (from the dataclass schemas)
    tpuop-cfg generate operator # namespace + RBAC + Deployment + sample CR
    tpuop-cfg generate all
"""

from __future__ import annotations

from typing import List

from .. import __version__
from ..api.crd import all_crds


def namespace_manifest(namespace: str) -> dict:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": namespace}}


def service_account(namespace: str) -> dict:
    return {"apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": "tpu-operator", "namespace": namespace}}


def cluster_role() -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "tpu-operator"},
        "rules": [
            {"apiGroups": ["tpu.graft.dev"],
             "resources": ["tpuclusterpolicies", "tpudrivers",
                           "tpuclusterpolicies/status", "tpudrivers/status"],
             "verbs": ["get", "list", "watch", "update", "patch"]},
            {"apiGroups": [""],
             "resources": ["nodes"],
             "verbs": ["get", "list", "watch", "patch"]},
            {"apiGroups": [""],
             "resources": ["pods", "pods/eviction", "services",
                           "serviceaccounts", "configmaps", "namespaces",
                           "endpoints"],
             "verbs": ["get", "list", "watch", "create", "update", "patch",
                       "delete"]},
            {"apiGroups": ["apps"],
             "resources": ["daemonsets", "deployments", "controllerrevisions"],
             "verbs": ["get", "list", "watch", "create", "update", "patch",
                       "delete"]},
            {"apiGroups": ["rbac.authorization.k8s.io"],
             "resources": ["roles", "rolebindings", "clusterroles",
                           "clusterrolebindings"],
             "verbs": ["get", "list", "watch", "create", "update", "patch",
                       "delete"]},
            {"apiGroups": ["node.k8s.io"],
             "resources": ["runtimeclasses"],
             "verbs": ["get", "list", "watch", "create", "update", "patch",
                       "delete"]},
            {"apiGroups": ["coordination.k8s.io"],
             "resources": ["leases"],
             "verbs": ["get", "list", "watch", "create", "update", "patch"]},
            {"apiGroups": ["monitoring.coreos.com"],
             "resources": ["servicemonitors"],
             "verbs": ["get", "list", "watch", "create", "update", "patch",
                       "delete"]},
        ],
    }


def cluster_role_binding(namespace: str) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": "tpu-operator"},
        "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole", "name": "tpu-operator"},
        "subjects": [{"kind": "ServiceAccount", "name": "tpu-operator",
                      "namespace": namespace}],
    }


def operator_deployment(namespace: str, image: str) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "tpu-operator", "namespace": namespace,
                     "labels": {"app": "tpu-operator"}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "tpu-operator"}},
            "template": {
                "metadata": {"labels": {"app": "tpu-operator"}},
                "spec": {
                    "serviceAccountName": "tpu-operator",
                    "priorityClassName": "system-cluster-critical",
                    "containers": [{
                        "name": "tpu-operator",
                        "image": image,
                        "command": ["tpu-operator", "--health-port", "8080"],
                        "env": [{"name": "OPERATOR_NAMESPACE",
                                 "valueFrom": {"fieldRef": {
                                     "fieldPath": "metadata.namespace"}}}],
                        "ports": [{"name": "metrics", "containerPort": 8080}],
                        "livenessProbe": {
                            "httpGet": {"path": "/healthz", "port": 8080},
                            "initialDelaySeconds": 10,
                            "periodSeconds": 20},
                        "readinessProbe": {
                            "httpGet": {"path": "/readyz", "port": 8080},
                            "initialDelaySeconds": 5,
                            "periodSeconds": 10},
                    }],
                },
            },
        },
    }


def sample_cluster_policy() -> dict:
    from ..api import new_cluster_policy

    cr = new_cluster_policy()
    cr["spec"] = {
        "libtpu": {"channel": "stable"},
        "metricsExporter": {"serviceMonitor": False},
        "validator": {"matmulSize": 4096, "iciBandwidthThreshold": 0.8},
        "upgradePolicy": {"autoUpgrade": False, "maxParallelUpgrades": 1},
    }
    return cr


def generate(what: str, namespace: str = "tpu-operator",
             image: str = "") -> List[dict]:
    image = image or f"ghcr.io/tpu-operator/tpu-operator:v{__version__}"
    crds = all_crds()
    operator = [
        namespace_manifest(namespace),
        service_account(namespace),
        cluster_role(),
        cluster_role_binding(namespace),
        operator_deployment(namespace, image),
        sample_cluster_policy(),
    ]
    if what == "crds":
        return crds
    if what == "operator":
        return operator
    if what == "all":
        return crds + operator
    raise ValueError(f"unknown target {what!r} (crds|operator|all)")
