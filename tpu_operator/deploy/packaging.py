"""Deployment manifest generation — the Helm-chart slot.

The reference packages via a 546-line values.yaml Helm chart rendering the
ClusterPolicy CR plus operator Deployment/RBAC
(deployments/gpu-operator/). Here the same artifacts are generated from
code, so they cannot drift from the API types:

    tpuop-cfg generate crds     # both CRDs (from the dataclass schemas)
    tpuop-cfg generate operator # namespace + RBAC + Deployment + sample CR
    tpuop-cfg generate all
"""

from __future__ import annotations

from typing import List, Optional

from .. import __version__
from ..api.crd import all_crds


def namespace_manifest(namespace: str) -> dict:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": namespace}}


def service_account(namespace: str) -> dict:
    return {"apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": "tpu-operator", "namespace": namespace}}


# the reference's chart splits RBAC into a ClusterRole for what is
# genuinely cluster-scoped and a namespaced Role for the write-heavy
# operand management (deployments/gpu-operator/templates/clusterrole.yaml
# + role.yaml); same shape here. The stale/uninstall sweeps scope their
# namespaced-kind passes to the operator namespace to match (skel.py
# _delete_stale, deploy/apply.py sweep_operands); the ClusterRole keeps
# cluster-wide READ on those kinds for observability and drift checks,
# WRITES on them are namespace-scoped.

_RW = ["get", "list", "watch", "create", "update", "patch", "delete"]
_RO = ["get", "list", "watch"]


def cluster_role() -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "tpu-operator"},
        "rules": [
            {"apiGroups": ["tpu.graft.dev"],
             "resources": ["tpuclusterpolicies", "tpudrivers",
                           "tpuclusterpolicies/status", "tpudrivers/status"],
             "verbs": ["get", "list", "watch", "update", "patch"]},
            {"apiGroups": [""],
             "resources": ["nodes"],
             "verbs": ["get", "list", "watch", "patch"]},
            # drain evicts TPU workload pods from ANY namespace
            {"apiGroups": [""],
             "resources": ["pods", "pods/eviction"],
             "verbs": list(_RW)},
            # PSA enforcement labels on the operator namespace
            {"apiGroups": [""],
             "resources": ["namespaces"],
             "verbs": ["get", "list", "watch", "patch"]},
            # cluster-wide read for the stale/uninstall sweeps; writes on
            # these kinds live in the namespaced Role below
            {"apiGroups": [""],
             "resources": ["services", "serviceaccounts", "configmaps",
                           "endpoints"],
             "verbs": list(_RO)},
            {"apiGroups": ["apps"],
             "resources": ["daemonsets", "deployments",
                           "controllerrevisions"],
             "verbs": list(_RO)},
            {"apiGroups": ["rbac.authorization.k8s.io"],
             "resources": ["roles", "rolebindings"],
             "verbs": list(_RO)},
            {"apiGroups": ["monitoring.coreos.com"],
             "resources": ["servicemonitors", "prometheusrules"],
             "verbs": list(_RO)},
            # genuinely cluster-scoped operand kinds
            {"apiGroups": ["rbac.authorization.k8s.io"],
             "resources": ["clusterroles", "clusterrolebindings"],
             "verbs": list(_RW)},
            {"apiGroups": ["node.k8s.io"],
             "resources": ["runtimeclasses"],
             "verbs": list(_RW)},
        ],
    }


def namespaced_role(namespace: str) -> dict:
    """Write grants for operand management, confined to the operator
    namespace (templates/role.yaml analog: the operator renders every
    namespaced operand object into its own namespace)."""
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "Role",
        "metadata": {"name": "tpu-operator", "namespace": namespace},
        "rules": [
            {"apiGroups": [""],
             "resources": ["pods", "services", "serviceaccounts",
                           "configmaps", "endpoints", "events"],
             "verbs": list(_RW)},
            {"apiGroups": ["apps"],
             "resources": ["daemonsets", "deployments",
                           "controllerrevisions"],
             "verbs": list(_RW)},
            {"apiGroups": ["rbac.authorization.k8s.io"],
             "resources": ["roles", "rolebindings"],
             "verbs": list(_RW)},
            {"apiGroups": ["coordination.k8s.io"],
             "resources": ["leases"],
             "verbs": ["get", "list", "watch", "create", "update",
                       "patch"]},
            {"apiGroups": ["monitoring.coreos.com"],
             "resources": ["servicemonitors", "prometheusrules"],
             "verbs": list(_RW)},
        ],
    }


def role_binding(namespace: str) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {"name": "tpu-operator", "namespace": namespace},
        "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                    "kind": "Role", "name": "tpu-operator"},
        "subjects": [{"kind": "ServiceAccount", "name": "tpu-operator",
                      "namespace": namespace}],
    }


def cluster_role_binding(namespace: str) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": "tpu-operator"},
        "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole", "name": "tpu-operator"},
        "subjects": [{"kind": "ServiceAccount", "name": "tpu-operator",
                      "namespace": namespace}],
    }


def operator_deployment(namespace: str, image: str,
                        op: Optional[dict] = None) -> dict:
    """The manager Deployment, shaped by the values `operator:` section
    (the chart-level operator config of the reference's values.yaml:
    scheduling, resources, leader election, health port)."""
    op = op or {}
    port = int(op["healthPort"] if op.get("healthPort") is not None else 8080)
    command = ["tpu-operator", "--health-port", str(port)]
    if op.get("leaderElect"):
        command.append("--leader-elect")
    container = {
        "name": "tpu-operator",
        "image": image,
        "imagePullPolicy": op.get("imagePullPolicy") or "IfNotPresent",
        "command": command,
        "env": [{"name": "OPERATOR_NAMESPACE",
                 "valueFrom": {"fieldRef": {
                     "fieldPath": "metadata.namespace"}}}]
        + list(op.get("env") or []),
        "ports": [{"name": "metrics", "containerPort": port}],
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": port},
            "initialDelaySeconds": 10,
            "periodSeconds": 20},
        "readinessProbe": {
            "httpGet": {"path": "/readyz", "port": port},
            "initialDelaySeconds": 5,
            "periodSeconds": 10},
    }
    if op.get("resources"):
        container["resources"] = op["resources"]
    pod_spec = _pod_spec_passthrough(op, {
        "serviceAccountName": "tpu-operator",
        "priorityClassName": op.get("priorityClassName")
        or "system-cluster-critical",
        "containers": [container],
    })
    # "app" is the selector identity — user labels must not break
    # spec.selector/template agreement (same protection operand renders
    # give their selector labels)
    labels = {**(op.get("labels") or {}), "app": "tpu-operator"}
    meta = {"name": "tpu-operator", "namespace": namespace,
            "labels": dict(labels)}
    # fresh dict: sharing one labels object across metadata and the pod
    # template makes yaml.safe_dump emit anchors/aliases, which strict
    # consumers and text-diff GitOps pipelines choke on
    pod_meta: dict = {"labels": dict(labels)}
    if op.get("annotations"):
        meta["annotations"] = dict(op["annotations"])
        pod_meta["annotations"] = dict(op["annotations"])
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": meta,
        "spec": {
            "replicas": int(op["replicas"]
                            if op.get("replicas") is not None else 1),
            "selector": {"matchLabels": {"app": "tpu-operator"}},
            "template": {"metadata": pod_meta, "spec": pod_spec},
        },
    }


def _hook_annotations(hook: str, weight: str) -> dict:
    """Helm hook metadata (upgrade_crd.yaml/cleanup_crd.yaml carry the
    same): meaningful when the stream is wrapped in a chart. Applied
    plainly, Jobs are immutable run-once objects — which is why the
    upgrade hook Job's NAME is versioned by image (a re-apply with a new
    version creates a fresh Job instead of failing on spec immutability)
    and finished Jobs self-clean via ttlSecondsAfterFinished."""
    return {"helm.sh/hook": hook,
            "helm.sh/hook-weight": weight,
            "helm.sh/hook-delete-policy":
                "hook-succeeded,before-hook-creation"}


def _pod_spec_passthrough(op: dict, pod_spec: dict) -> dict:
    """Shared operator-values -> pod-spec plumbing for the manager
    Deployment and the hook Jobs: one copy, so a new knob cannot reach
    operator pods but miss hook pods (whose unschedulability would hang
    a release operation)."""
    if op.get("imagePullSecrets"):
        pod_spec["imagePullSecrets"] = [
            {"name": s} if isinstance(s, str) else s
            for s in op["imagePullSecrets"]]
    for key in ("nodeSelector", "affinity", "tolerations",
                "priorityClassName"):
        if op.get(key):
            pod_spec[key] = op[key]
    return pod_spec


def _hook_rbac(name: str, namespace: str, hook: str, rules: list) -> list:
    meta = lambda: {"name": name,  # noqa: E731
                    "annotations": _hook_annotations(hook, "0")}
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {**meta(), "namespace": namespace}},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
         "metadata": meta(), "rules": rules},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": meta(),
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole", "name": name},
         "subjects": [{"kind": "ServiceAccount", "name": name,
                       "namespace": namespace}]},
    ]


def _hook_job(name: str, namespace: str, hook: str, image: str,
              command: list, op: dict,
              job_name: Optional[str] = None) -> dict:
    pod_spec = _pod_spec_passthrough(op, {
        "serviceAccountName": name,
        "restartPolicy": "OnFailure",
        "containers": [{
            "name": name,
            "image": image,
            "imagePullPolicy": op.get("imagePullPolicy") or "IfNotPresent",
            "command": command,
        }],
    })
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": job_name or name, "namespace": namespace,
                     "annotations": _hook_annotations(hook, "1")},
        "spec": {"backoffLimit": 6,
                 # plain-apply installs have no Helm hook-delete; finished
                 # hook Jobs clean themselves up
                 "ttlSecondsAfterFinished": 3600,
                 "template": {"metadata": {"labels": {"app": name}},
                              "spec": pod_spec}},
    }


def upgrade_crd_hook(namespace: str, image: str,
                     op: Optional[dict] = None) -> List[dict]:
    """Pre-upgrade CRD-apply Job (upgrade_crd.yaml slot): package
    managers don't upgrade CRDs, so schema changes in a new version must
    be applied by an explicit hook before the operator rolls."""
    op = op or {}
    name = "tpu-operator-upgrade-crd"
    docs = _hook_rbac(name, namespace, "pre-upgrade", [
        {"apiGroups": ["apiextensions.k8s.io"],
         "resources": ["customresourcedefinitions"],
         "verbs": ["create", "get", "list", "watch", "patch", "update"]},
    ])
    # Jobs are immutable and run-once: version the name by image so a
    # plain re-apply after a version bump creates a FRESH Job (and thus
    # actually re-applies the CRDs) instead of erroring on the completed
    # one; ttlSecondsAfterFinished reaps the old names
    import hashlib

    suffix = hashlib.sha256(image.encode()).hexdigest()[:8]
    docs.append(_hook_job(name, namespace, "pre-upgrade", image,
                          ["tpu-operator-maintenance", "apply-crds"], op,
                          job_name=f"{name}-{suffix}"))
    return docs


def cleanup_crd_hook(namespace: str, image: str,
                     op: Optional[dict] = None) -> List[dict]:
    """Pre-delete cleanup Job (cleanup_crd.yaml slot): delete the CRs
    while the operator still runs (operands tear down via owner GC),
    wait, then drop the CRDs."""
    op = op or {}
    name = "tpu-operator-cleanup-crd"
    docs = _hook_rbac(name, namespace, "pre-delete", [
        {"apiGroups": ["tpu.graft.dev"],
         "resources": ["tpuclusterpolicies", "tpudrivers"],
         "verbs": ["get", "list", "delete"]},
        {"apiGroups": ["apiextensions.k8s.io"],
         "resources": ["customresourcedefinitions"],
         "verbs": ["get", "list", "delete"]},
    ])
    docs.append(_hook_job(name, namespace, "pre-delete", image,
                          ["tpu-operator-maintenance", "cleanup"], op))
    return docs


def sample_cluster_policy() -> dict:
    from ..api import new_cluster_policy

    cr = new_cluster_policy()
    cr["spec"] = {
        "libtpu": {"channel": "stable"},
        "metricsExporter": {"serviceMonitor": False},
        "validator": {"matmulSize": 4096, "iciBandwidthThreshold": 0.8},
        "upgradePolicy": {"autoUpgrade": False, "maxParallelUpgrades": 1},
    }
    return cr


def generate(what: str, namespace: str = "tpu-operator",
             image: str = "") -> List[dict]:
    image = image or f"ghcr.io/tpu-operator/tpu-operator:v{__version__}"
    crds = all_crds()
    operator = [
        namespace_manifest(namespace),
        service_account(namespace),
        cluster_role(),
        cluster_role_binding(namespace),
        namespaced_role(namespace),
        role_binding(namespace),
        operator_deployment(namespace, image),
        sample_cluster_policy(),
    ]
    if what == "crds":
        return crds
    if what == "operator":
        return operator
    if what == "all":
        return crds + operator
    raise ValueError(f"unknown target {what!r} (crds|operator|all)")
