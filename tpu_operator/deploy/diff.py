"""Live-vs-rendered drift detection — the `kubectl diff` / helm-diff
slot for the install stream.

`tpuop-cfg generate` says what the cluster SHOULD run; this module asks
the cluster what it DOES run and reports, per rendered object: missing,
match, or drift (with a unified diff of normalized YAML). Server-owned
noise (status, resourceVersion/uid/timestamps, the operator's own
last-applied-hash annotation) is stripped before comparing, and fields
the desired doc doesn't set are ignored — an admission-defaulted field
is not drift.
"""

from __future__ import annotations

import copy
import difflib
from typing import List, Optional, Tuple

import yaml

from ..api.labels import LAST_APPLIED_HASH
from ..runtime.client import Client
from ..runtime.objects import name_of, namespace_of

# metadata keys the apiserver owns; never drift
_SERVER_META = {"resourceVersion", "uid", "creationTimestamp",
                "generation", "managedFields", "selfLink",
                "ownerReferences", "finalizers"}
_OPERATOR_ANNOTATIONS = {LAST_APPLIED_HASH}


def _strip(obj: dict) -> dict:
    out = copy.deepcopy(obj)
    out.pop("status", None)
    meta = out.get("metadata") or {}
    for key in _SERVER_META:
        meta.pop(key, None)
    anns = meta.get("annotations")
    if isinstance(anns, dict):
        for key in _OPERATOR_ANNOTATIONS:
            anns.pop(key, None)
        if not anns:
            meta.pop("annotations", None)
    return out


def _project(live, desired):
    """Reduce ``live`` to the shape ``desired`` actually specifies:
    dict keys absent from desired are dropped — an admission-defaulted
    field is not drift — recursively, INCLUDING inside list items
    (apiservers default container fields like terminationMessagePath and
    ports[].protocol on every pod spec). Scalars and list length/order
    compare whole: those are part of what the manifest says."""
    if isinstance(desired, dict) and isinstance(live, dict):
        return {k: _project(live[k], v)
                for k, v in desired.items() if k in live}
    if isinstance(desired, list) and isinstance(live, list):
        # project the common prefix even when lengths differ (an added
        # sidecar must not pollute the diff with the ORIGINAL items'
        # server defaults); extra live items stay whole
        return [_project(lv, dv) for lv, dv in zip(live, desired)] \
            + live[len(desired):]
    return live


class _NoAliasDumper(yaml.SafeDumper):
    """Rendered docs reuse sub-dicts (one labels dict in two places);
    anchors/aliases in the dump would show identical blocks as changed
    against the live side, which never has them."""

    def ignore_aliases(self, data):
        return True


def _dump(obj: dict) -> List[str]:
    return yaml.dump(obj, Dumper=_NoAliasDumper,
                     sort_keys=True).splitlines(keepends=True)


def diff_object(client: Client, desired: dict) -> Tuple[str, Optional[str]]:
    """('missing'|'match'|'drift', unified diff text or None)."""
    av = desired.get("apiVersion", "")
    kind = desired.get("kind", "")
    name = name_of(desired)
    ns = namespace_of(desired) or None
    live = client.get_or_none(av, kind, name, ns)
    if live is None:
        return "missing", None
    want = _strip(desired)
    have = _project(_strip(live), want)
    if have == want:
        return "match", None
    ident = f"{kind}/{(ns + '/') if ns else ''}{name}"
    text = "".join(difflib.unified_diff(
        _dump(have), _dump(want),
        fromfile=f"live/{ident}", tofile=f"rendered/{ident}"))
    return "drift", text


def diff_bundle(client: Client, docs: List[dict]) -> List[dict]:
    """One verdict dict per rendered object, cluster order preserved."""
    results = []
    for doc in docs:
        if not doc:
            continue
        verdict, text = diff_object(client, doc)
        results.append({
            "kind": doc.get("kind", ""),
            "name": name_of(doc),
            "namespace": namespace_of(doc),
            "verdict": verdict,
            "diff": text,
        })
    return results


def render_report(results: List[dict]) -> Tuple[str, bool]:
    """(human-readable report, clean) — clean means nothing missing or
    drifted (kubectl-diff exit-code semantics)."""
    lines = []
    clean = True
    for r in results:
        ident = (f"{r['kind']}/"
                 f"{(r['namespace'] + '/') if r['namespace'] else ''}"
                 f"{r['name']}")
        if r["verdict"] == "match":
            lines.append(f"  OK      {ident}")
            continue
        clean = False
        if r["verdict"] == "missing":
            lines.append(f"  MISSING {ident}")
        else:
            lines.append(f"  DRIFT   {ident}")
            lines.append(r["diff"] or "")
    counts = {"match": 0, "missing": 0, "drift": 0}
    for r in results:
        counts[r["verdict"]] += 1
    lines.append(f"{counts['match']} in sync, {counts['missing']} missing, "
                 f"{counts['drift']} drifted")
    return "\n".join(lines), clean
