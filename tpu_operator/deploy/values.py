"""Values-driven bundle rendering — the Helm values.yaml slot.

The reference's chart (deployments/gpu-operator/values.yaml, 546 lines)
renders the ClusterPolicy CR plus the operator Deployment/RBAC from one
values file, and CI keeps values and CRD schema consistent
(``make validate-helm-values``/``validate-csv``, Makefile:233-243). Here
the same contract is code:

- ``deploy/values.yaml`` is the documented default values file,
- ``load_values()`` deep-merges a user file over the defaults and rejects
  unknown top-level keys,
- ``render_bundle()`` produces the full install stream (CRDs, namespace,
  RBAC, Deployment, ClusterPolicy) and **validates the rendered CR
  against the CRD schema before emitting it** — the drift gate runs at
  render time, not in a separate CI step.

CLI: ``tpuop-cfg generate all --values my-values.yaml``.
"""

from __future__ import annotations

import pathlib
from typing import Any, Dict, List, Optional

import yaml

from .. import __version__
from ..api import new_cluster_policy
from .packaging import (
    cleanup_crd_hook,
    cluster_role,
    cluster_role_binding,
    namespace_manifest,
    namespaced_role,
    operator_deployment,
    role_binding,
    service_account,
    upgrade_crd_hook,
)

# shipped as package data so pip installs carry it (see pyproject
# [tool.setuptools.package-data])
VALUES_FILE = pathlib.Path(__file__).resolve().parent / "values.yaml"

TOP_LEVEL_KEYS = {"namespace", "operator", "clusterPolicy", "pluginConfig",
                  "tpuDrivers"}


def default_values() -> Dict[str, Any]:
    with open(VALUES_FILE) as f:
        return yaml.safe_load(f) or {}


def deep_merge(base: Dict, override: Dict) -> Dict:
    """Helm-style merge: maps merge recursively, scalars/lists replace."""
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def load_values(path: Optional[str] = None) -> Dict[str, Any]:
    values = default_values()
    if path:
        with open(path) as f:
            user = yaml.safe_load(f) or {}
        if not isinstance(user, dict):
            raise ValueError(f"{path}: values file must be a mapping")
        unknown = set(user) - TOP_LEVEL_KEYS
        if unknown:
            raise ValueError(
                f"{path}: unknown top-level keys {sorted(unknown)} "
                f"(known: {sorted(TOP_LEVEL_KEYS)})")
        values = deep_merge(values, user)
    return values


def operator_image(values: Dict[str, Any]) -> str:
    op = values.get("operator") or {}
    if not isinstance(op, dict):
        raise ValueError("operator: must be a mapping")
    # `or` (not dict defaults) so explicit nulls fall back too; reject
    # non-string scalars (a YAML float version would otherwise crash or
    # render a bogus image reference)
    repo = op.get("repository") or "ghcr.io/tpu-operator"
    image = op.get("image") or "tpu-operator"
    version = op.get("version") or f"v{__version__}"
    for name, val in (("repository", repo), ("image", image),
                      ("version", version)):
        if not isinstance(val, str):
            raise ValueError(
                f"operator.{name}: expected string, got {val!r} "
                f"(quote it in the values file)")
    if version.startswith("sha256:"):
        return f"{repo}/{image}@{version}"
    return f"{repo}/{image}:{version}"


def render_cluster_policy(values: Dict[str, Any]) -> Optional[dict]:
    cp = values.get("clusterPolicy") or {}
    if not cp.get("enabled", True):
        return None
    cr = new_cluster_policy(name=cp.get("name", "tpu-cluster-policy"),
                            spec=cp.get("spec") or {})
    # the validate-helm-values gate, inline: a values file that renders an
    # invalid CR fails at render time with the schema errors
    from ..api.validate import validate_cr

    errs, _ = validate_cr(cr)
    if errs:
        raise ValueError("values render an invalid TPUClusterPolicy:\n  " +
                         "\n  ".join(errs))
    return cr


def render_tpu_drivers(values: Dict[str, Any]) -> List[dict]:
    """Per-pool TPUDriver CRs from values (the chart's nvidiadriver.yaml
    slot: `driver.nvidiaDriverCRD` renders an NVIDIADriver CR alongside
    the ClusterPolicy). Each entry is {name, spec}; every rendered CR is
    schema+CEL validated at render time like the ClusterPolicy."""
    from ..api.tpudriver import new_tpu_driver
    from ..api.validate import validate_cr

    out: List[dict] = []
    seen: set = set()
    for i, entry in enumerate(values.get("tpuDrivers") or []):
        if not isinstance(entry, dict) or not entry.get("name"):
            raise ValueError(f"tpuDrivers[{i}]: each entry needs a name "
                             f"(and optionally a spec mapping)")
        if entry["name"] in seen:
            raise ValueError(
                f"tpuDrivers[{i}]: duplicate name {entry['name']!r} — the "
                f"later spec would silently overwrite the earlier one")
        seen.add(entry["name"])
        cr = new_tpu_driver(entry["name"], spec=entry.get("spec") or {})
        errs, _ = validate_cr(cr)
        if errs:
            raise ValueError(
                f"values render an invalid TPUDriver {entry['name']!r}:"
                "\n  " + "\n  ".join(errs))
        out.append(cr)
    # an empty nodeSelector selects ALL TPU nodes, so two selector-less
    # entries can never be valid — catch it at render time instead of
    # leaving both CRs NotReady (controllers/validation.py enforces the
    # full per-node disjointness at reconcile, which needs the cluster)
    selectorless = [d["metadata"]["name"] for d in out
                    if not (d.get("spec") or {}).get("nodeSelector")]
    if len(selectorless) > 1:
        raise ValueError(
            f"tpuDrivers: entries {selectorless} all omit nodeSelector; "
            f"an empty selector matches every TPU node, so at most one "
            f"entry may omit it")
    return out


def render_plugin_config_map(values: Dict[str, Any]) -> Optional[dict]:
    """Ship the per-node plugin-config ConfigMap from values
    (devicePlugin.config.create/data slot, templates/plugin_config.yaml).
    Every entry is parsed with the plugin's own loader at render time, so
    a config the plugin would reject fails the install render instead of
    being silently kept-out at reload time."""
    pc = values.get("pluginConfig") or {}
    if not pc.get("create") or not pc.get("data"):
        return None
    cp = values.get("clusterPolicy") or {}
    name = ((cp.get("spec") or {}).get("devicePlugin") or {}).get("configMap")
    if not name:
        raise ValueError(
            "pluginConfig.create is true but "
            "clusterPolicy.spec.devicePlugin.configMap names no ConfigMap")
    from ..deviceplugin.plugin import parse_plugin_config

    data = {}
    for key, text in pc["data"].items():
        if not isinstance(text, str):
            raise ValueError(f"pluginConfig.data.{key}: must be a YAML "
                             f"string (use a block scalar)")
        try:
            parse_plugin_config(key, text)
        except Exception as e:  # surface WITH the key, whatever the type
            raise ValueError(f"pluginConfig.data.{key}: {e}")
        data[key] = text
    # the most common typo: a defaultConfig that names no shipped entry
    # would strand every unlabeled node on the built-in sharing policy at
    # reload time — both values are in hand here, so fail the render
    default = ((cp.get("spec") or {}).get("devicePlugin")
               or {}).get("defaultConfig")
    if default and default not in data:
        raise ValueError(
            f"clusterPolicy.spec.devicePlugin.defaultConfig {default!r} "
            f"is not a key of pluginConfig.data {sorted(data)}")
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name,
                         "namespace": values.get("namespace",
                                                 "tpu-operator")},
            "data": data}


def render_bundle(values: Dict[str, Any], include_crds: bool = True) -> List[dict]:
    from ..api.crd import all_crds

    ns = values.get("namespace", "tpu-operator")
    docs: List[dict] = []
    if include_crds:
        docs.extend(all_crds())
    docs.extend([
        namespace_manifest(ns),
        service_account(ns),
        cluster_role(),
        cluster_role_binding(ns),
        namespaced_role(ns),
        role_binding(ns),
        operator_deployment(ns, operator_image(values),
                            values.get("operator") or {}),
    ])
    # lifecycle hooks: only the idempotent pre-upgrade CRD-apply rides in
    # the install stream (operator.upgradeCRD slot). The pre-delete
    # cleanup Job is NEVER part of the install bundle — plain `kubectl
    # apply` ignores helm.sh/hook annotations and would run it at install
    # time, deleting the freshly created CRs and CRDs. It is emitted only
    # by the explicit `tpuop-cfg generate cleanup` target (see
    # render_cleanup).
    op = values.get("operator") or {}
    if op.get("upgradeCRD"):
        docs.extend(upgrade_crd_hook(ns, operator_image(values), op))
    pc = render_plugin_config_map(values)
    if pc is not None:
        docs.append(pc)
    cr = render_cluster_policy(values)
    if cr is not None:
        docs.append(cr)
    docs.extend(render_tpu_drivers(values))
    return docs


def render_cleanup(values: Dict[str, Any]) -> List[dict]:
    """The pre-delete cleanup hook (cleanup_crd.yaml slot), emitted as a
    standalone stream for the explicit uninstall step:

        tpuop-cfg generate cleanup | kubectl apply -f -
        kubectl wait --for=condition=complete job/tpu-operator-cleanup-crd
        tpuop-cfg generate all | kubectl delete -f -

    Deliberately excluded from render_bundle: applied plainly at install
    time it would delete the CRs/CRDs it finds (helm.sh/hook annotations
    are inert outside Helm). A Helm-wrapped chart can include this stream
    and get true pre-delete sequencing from the annotations."""
    ns = values.get("namespace", "tpu-operator")
    return cleanup_crd_hook(ns, operator_image(values),
                            values.get("operator") or {})


# the former render_bundle_metadata (a custom BundleMetadata blob) is
# replaced by deploy/csv.py's real ClusterServiceVersion bundle
