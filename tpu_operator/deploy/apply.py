"""Take the rendered install stream to a cluster — the Helm-verb slot.

The reference's primary install path is its chart
(deployments/gpu-operator/values.yaml, templates/clusterpolicy.yaml):
`helm install/upgrade --wait` and `helm uninstall` with pre-upgrade /
pre-delete hook Jobs (templates/upgrade_crd.yaml, cleanup_crd.yaml).
This framework renders the same stream offline (deploy/values.py); this
module supplies the verbs so ONE command takes an empty cluster to
all-operands-ready:

    tpuop-cfg install  --values f.yaml --wait
    tpuop-cfg upgrade  --values f.yaml --wait     # re-applies CRDs first
    tpuop-cfg uninstall [--purge-crds]

Create-or-update carries the live resourceVersion (optimistic
concurrency); uninstall sequences the cleanup the way the pre-delete
hook does: CRs first (operands tear down through owner GC while the
operator still runs), then the operator stream, then optionally the
CRDs.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

from ..api import KIND_CLUSTER_POLICY, V1
from ..runtime.client import Client, NotFoundError
from ..runtime.objects import name_of, namespace_of

Log = Callable[[str], None]


def _ident(doc: dict) -> Tuple[str, str, str, Optional[str]]:
    return (doc.get("apiVersion", ""), doc.get("kind", ""),
            name_of(doc), namespace_of(doc) or None)


def apply_docs(client: Client, docs: List[dict],
               log: Log = lambda s: None) -> List[Tuple[str, str, str]]:
    """Create-or-update every document, in stream order (render_bundle
    already emits install order: CRDs -> Namespace -> RBAC -> operator
    -> CR, matching Helm's kind ordering). Returns (verb, kind, name)
    per document."""
    # groups whose CRDs ship in this very stream: only their CRs can hit
    # the establishment window and deserve the create retry
    stream_groups = {d.get("spec", {}).get("group", "")
                     for d in docs
                     if d.get("kind") == "CustomResourceDefinition"}
    out: List[Tuple[str, str, str]] = []
    for doc in docs:
        av, kind, name, ns = _ident(doc)
        existing = client.get_or_none(av, kind, name, ns)
        if existing is None:
            _create_with_establish_retry(client, doc, stream_groups)
            verb = "created"
        else:
            # never mutate the caller's rendered doc: the stream may be
            # reused (reinstall, delete) and a stamped resourceVersion
            # would then poison a later create
            merged = dict(doc)
            merged["metadata"] = dict(doc.get("metadata") or {})
            merged["metadata"]["resourceVersion"] = (
                existing.get("metadata") or {}).get("resourceVersion")
            client.update(merged)
            verb = "configured"
        log(f"{verb} {kind}/{name}")
        out.append((verb, kind, name))
    return out


def _create_with_establish_retry(client: Client, doc: dict,
                                 stream_groups: set,
                                 attempts: int = 10,
                                 backoff_s: float = 1.0) -> None:
    """Create, riding out the CRD-establishment window: on a real
    apiserver a CR POSTed right after its CRD returns 404 'no matches
    for kind' until the discovery cache catches up (a few seconds). Only
    CRs of groups whose CRD ships in the SAME stream get the retry — a
    404 on anything else (built-in kinds, dotted built-in groups like
    rbac.authorization.k8s.io, absent third-party CRDs) is a genuine
    error and fails immediately."""
    last: Optional[Exception] = None
    group = doc.get("apiVersion", "").split("/")[0]
    n = attempts if group in stream_groups else 1
    for attempt in range(n):
        try:
            client.create(doc)
            return
        except NotFoundError as e:
            last = e
            if attempt < n - 1:
                time.sleep(backoff_s)
    raise last  # type: ignore[misc]


def delete_docs(client: Client, docs: List[dict], log: Log = lambda s: None,
                keep_kinds: Tuple[str, ...] = ()) -> int:
    """Delete the stream in reverse order (CR before its CRD, workloads
    before RBAC), ignoring already-gone objects. ``keep_kinds`` skips
    kinds the caller wants to survive (Namespace by default at the CLI:
    deleting a shared namespace is not an uninstaller's call)."""
    deleted = 0
    for doc in reversed(docs):
        av, kind, name, ns = _ident(doc)
        if kind in keep_kinds:
            continue
        try:
            client.delete(av, kind, name, ns)
            log(f"deleted {kind}/{name}")
            deleted += 1
        except NotFoundError:
            pass
    return deleted


def sweep_operands(client: Client, log: Log = lambda s: None,
                   settle_s: float = 0.5, max_s: float = 30.0,
                   namespace: str = "") -> int:
    """Delete any operand object still carrying the state label after CR
    teardown. Owner GC removes almost everything, but a reconcile pass
    that fetched the CR just before deletion keeps applying states for
    several seconds afterward, re-creating operands with dangling
    ownerRefs (cluster GC would collect them eventually — an uninstaller
    shouldn't leave that to chance). Sweep repeatedly until two
    consecutive passes find nothing, so the in-flight pass has drained."""
    from ..api.labels import STATE_LABEL
    from ..runtime.client import ListOptions
    from ..runtime.objects import is_namespaced, labels_of
    from ..state.skel import SWEEPABLE_KINDS

    selector = {"matchExpressions": [
        {"key": STATE_LABEL, "operator": "Exists"}]}

    def one_pass() -> int:
        n = 0
        for av, kind in SWEEPABLE_KINDS:
            # namespaced kinds sweep within the install namespace (the
            # operator's RBAC write scope); cluster kinds cluster-wide
            opts = ListOptions(label_selector=selector,
                               namespace=namespace
                               if namespace and is_namespaced(kind)
                               else None)
            try:
                objs = client.list(av, kind, opts)
            except NotFoundError:
                continue
            for obj in objs:
                if STATE_LABEL not in labels_of(obj):
                    continue
                try:
                    client.delete(av, kind, name_of(obj),
                                  namespace_of(obj) or None)
                    log(f"swept leftover {kind}/{name_of(obj)}")
                    n += 1
                except NotFoundError:
                    pass
        return n

    swept = 0
    clean = 0
    deadline = time.monotonic() + max_s
    while clean < 2 and time.monotonic() < deadline:
        n = one_pass()
        swept += n
        clean = clean + 1 if n == 0 else 0
        if clean < 2:
            time.sleep(settle_s)
    return swept


def wait_policy_ready(client: Client, timeout_s: float = 300.0,
                      poll_s: float = 2.0,
                      log: Log = lambda s: None) -> bool:
    """Block until every TPUClusterPolicy AND every TPUDriver reports
    status.state == ready — the `helm install --wait` contract, with the
    reference e2e's 5-minute default budget
    (tests/e2e/gpu_operator_test.go:83-88). TPUDrivers matter because
    their presence stands the policy's built-in libtpu state down: a
    policy can be 'ready' while per-pool driver rollout is still
    pending."""
    from ..api.tpudriver import KIND_TPU_DRIVER, V1ALPHA1

    deadline = time.monotonic() + timeout_s
    last = "no TPUClusterPolicy observed yet"
    while time.monotonic() < deadline:
        states = {}
        any_policy = False
        for av, kind in ((V1, KIND_CLUSTER_POLICY),
                         (V1ALPHA1, KIND_TPU_DRIVER)):
            try:
                crs = client.list(av, kind)
            except NotFoundError:
                crs = []
            for c in crs:
                any_policy = any_policy or kind == KIND_CLUSTER_POLICY
                states[f"{kind}/{name_of(c)}"] = (
                    (c.get("status") or {}).get("state") or "unset")
        if any_policy:
            if all(s == "ready" for s in states.values()):
                log(f"ready: {states}")
                return True
            last = str(states)
        time.sleep(poll_s)
    log(f"timed out after {timeout_s:.0f}s waiting for ready; last: {last}")
    return False
