"""Helm chart generation + offline rendering (VERDICT r4 #7).

The reference's primary install UX is its chart
(deployments/gpu-operator/values.yaml:1, templates/clusterpolicy.yaml:1,
hook Jobs templates/upgrade_crd.yaml:1). This module gives the TPU
operator the same surface WITHOUT forking the install logic:

- ``deployments/tpu-operator/`` is a real Helm v2 chart a helm shop can
  ``helm install``: ``crds/`` carries the CRDs (helm applies them before
  templates), ``values.yaml`` is byte-identical to the canonical
  ``deploy/values.yaml``, and ``templates/`` renders the same objects
  ``tpuop-cfg generate all`` emits.
- The RBAC/namespace templates are DERIVED from packaging.py at chart
  generation time (rendered with a sentinel namespace, then
  ``{{ .Release.Namespace }}`` substituted) — they cannot drift by
  construction. The parameterized templates (deployment, CRs, hooks)
  are authored here and pinned by tests/test_helm_chart.py's golden
  matrix: chart-render == render_bundle for a spread of values files.
- ``render_chart()`` renders the chart with the in-repo go-template
  engine (render/engine.py — the same subset helm's text/template+sprig
  implements), so the equality is proven in CI without a helm binary,
  and users without helm can still preview the chart.

Split from the reference's layout: the pre-delete cleanup hook IS part
of the chart (helm gives it true pre-delete sequencing) but stays out of
the plain-apply bundle, where helm.sh/hook annotations are inert and the
Job would fire at install time (deploy/values.py render_cleanup).
"""

from __future__ import annotations

import pathlib
from typing import Any, Dict, List, Optional

import yaml

from .. import __version__
from . import values as values_mod
from .packaging import (
    cluster_role,
    cluster_role_binding,
    namespaced_role,
    role_binding,
    service_account,
)

CHART_DIR = pathlib.Path(__file__).resolve().parents[2] / \
    "deployments" / "tpu-operator"

_NS_SENTINEL = "HELM-RELEASE-NAMESPACE-SENTINEL"
_NS_EXPR = "{{ .Release.Namespace }}"

# one image expression, used verbatim for image: fields and (hashed) for
# the versioned upgrade-hook Job name — keep in lockstep with
# values.operator_image(): repository/image joined with ':' for tags and
# '@' for digests, falling back to the packaged version
_REPO = '(.Values.operator.repository | default "ghcr.io/tpu-operator")'
_IMG = '(.Values.operator.image | default "tpu-operator")'
_VER = f'(.Values.operator.version | default "v{__version__}")'
_SEP = f'(ternary "@" ":" (hasPrefix "sha256:" {_VER}))'
IMAGE_EXPR = f'printf "%s/%s%s%s" {_REPO} {_IMG} {_SEP} {_VER}'

# nil-aware defaults for knobs whose python renderer uses `is not None`
# (a plain sprig `default` would swallow the legitimate value 0)
_REPLICAS_EXPR = ('ternary 1 .Values.operator.replicas '
                  '(eq .Values.operator.replicas nil) | int')
_PORT_EXPR = ('ternary 8080 .Values.operator.healthPort '
              '(eq .Values.operator.healthPort nil) | int')

# pod-spec passthrough shared by the operator Deployment and the hook
# Jobs (packaging._pod_spec_passthrough parity). Indent levels differ per
# consumer, so this is a format template over {ind}. imagePullSecrets
# entries may be bare Secret names or {{name: ...}} maps, exactly like
# the python renderer normalizes.
_POD_PASSTHROUGH = """\
{{{{- if .Values.operator.imagePullSecrets }}}}
{ind}imagePullSecrets:
{{{{- range .Values.operator.imagePullSecrets }}}}
{{{{- if (kindIs "string" .) }}}}
{ind}- name: {{{{ . }}}}
{{{{- else }}}}
{ind}-
{{{{ toYaml . | indent {m} }}}}
{{{{- end }}}}
{{{{- end }}}}
{{{{- end }}}}
{{{{- if .Values.operator.nodeSelector }}}}
{ind}nodeSelector:
{{{{ toYaml .Values.operator.nodeSelector | indent {n} }}}}
{{{{- end }}}}
{{{{- if .Values.operator.affinity }}}}
{ind}affinity:
{{{{ toYaml .Values.operator.affinity | indent {n} }}}}
{{{{- end }}}}
{{{{- if .Values.operator.tolerations }}}}
{ind}tolerations:
{{{{ toYaml .Values.operator.tolerations | indent {n} }}}}
{{{{- end }}}}"""


def _pod_passthrough(indent: int) -> str:
    return _POD_PASSTHROUGH.format(ind=" " * indent, n=indent + 2,
                                   m=indent + 4)


DEPLOYMENT_TEMPLATE = f"""\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: tpu-operator
  namespace: {_NS_EXPR}
  labels:
{{{{- if .Values.operator.labels }}}}
{{{{ toYaml .Values.operator.labels | indent 4 }}}}
{{{{- end }}}}
    app: tpu-operator
{{{{- if .Values.operator.annotations }}}}
  annotations:
{{{{ toYaml .Values.operator.annotations | indent 4 }}}}
{{{{- end }}}}
spec:
  replicas: {{{{ {_REPLICAS_EXPR} }}}}
  selector:
    matchLabels:
      app: tpu-operator
  template:
    metadata:
      labels:
{{{{- if .Values.operator.labels }}}}
{{{{ toYaml .Values.operator.labels | indent 8 }}}}
{{{{- end }}}}
        app: tpu-operator
{{{{- if .Values.operator.annotations }}}}
      annotations:
{{{{ toYaml .Values.operator.annotations | indent 8 }}}}
{{{{- end }}}}
    spec:
      serviceAccountName: tpu-operator
      priorityClassName: {{{{ .Values.operator.priorityClassName | default "system-cluster-critical" }}}}
{_pod_passthrough(6)}
      containers:
      - name: tpu-operator
        image: {{{{ {IMAGE_EXPR} }}}}
        imagePullPolicy: {{{{ .Values.operator.imagePullPolicy | default "IfNotPresent" }}}}
        command:
        - tpu-operator
        - --health-port
        - {{{{ {_PORT_EXPR} | quote }}}}
{{{{- if .Values.operator.leaderElect }}}}
        - --leader-elect
{{{{- end }}}}
        env:
        - name: OPERATOR_NAMESPACE
          valueFrom:
            fieldRef:
              fieldPath: metadata.namespace
{{{{- if .Values.operator.env }}}}
{{{{ toYaml .Values.operator.env | indent 8 }}}}
{{{{- end }}}}
        ports:
        - name: metrics
          containerPort: {{{{ {_PORT_EXPR} }}}}
        livenessProbe:
          httpGet:
            path: /healthz
            port: {{{{ {_PORT_EXPR} }}}}
          initialDelaySeconds: 10
          periodSeconds: 20
        readinessProbe:
          httpGet:
            path: /readyz
            port: {{{{ {_PORT_EXPR} }}}}
          initialDelaySeconds: 5
          periodSeconds: 10
{{{{- if .Values.operator.resources }}}}
        resources:
{{{{ toYaml .Values.operator.resources | indent 10 }}}}
{{{{- end }}}}
"""

# `clusterPolicy:` may be nulled wholesale in a values file (deep_merge
# scalar-replaces); the python renderer treats that as `{}` (enabled,
# all defaults) — the chart must match, hence the get-over-defaulted-map
# accesses instead of direct member paths
_CP = '(.Values.clusterPolicy | default (dict))'
CLUSTERPOLICY_TEMPLATE = f"""\
{{{{- if (ne (get {_CP} "enabled") false) }}}}
apiVersion: tpu.graft.dev/v1
kind: TPUClusterPolicy
metadata:
  name: {{{{ get {_CP} "name" | default "tpu-cluster-policy" }}}}
spec:
{{{{- if (get {_CP} "spec") }}}}
{{{{ toYaml (get {_CP} "spec") | indent 2 }}}}
{{{{- else }}}}
  {{}}
{{{{- end }}}}
{{{{- end }}}}
"""

TPUDRIVERS_TEMPLATE = """\
{{- range .Values.tpuDrivers }}
---
apiVersion: tpu.graft.dev/v1alpha1
kind: TPUDriver
metadata:
  name: {{ .name }}
spec:
{{- if (get . "spec") }}
{{ toYaml (get . "spec") | indent 2 }}
{{- else }}
  {}
{{- end }}
{{- end }}
"""

_PC = '(.Values.pluginConfig | default (dict))'
PLUGINCONFIG_TEMPLATE = f"""\
{{{{- if (get {_PC} "create") }}}}
{{{{- if (get {_PC} "data") }}}}
apiVersion: v1
kind: ConfigMap
metadata:
  name: {{{{ .Values.clusterPolicy.spec.devicePlugin.configMap }}}}
  namespace: {_NS_EXPR}
data:
{{{{ toYaml (get {_PC} "data") | indent 2 }}}}
{{{{- end }}}}
{{{{- end }}}}
"""


def _hook_templates() -> Dict[str, str]:
    """The pre-upgrade CRD-apply and pre-delete cleanup hooks
    (packaging.upgrade_crd_hook / cleanup_crd_hook parity)."""

    def rbac(name: str, hook: str, rules_yaml: str) -> str:
        ann = (f'    helm.sh/hook: {hook}\n'
               f'    helm.sh/hook-weight: "0"\n'
               f'    helm.sh/hook-delete-policy: '
               f'hook-succeeded,before-hook-creation')
        return f"""\
apiVersion: v1
kind: ServiceAccount
metadata:
  name: {name}
  namespace: {_NS_EXPR}
  annotations:
{ann}
---
apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRole
metadata:
  name: {name}
  annotations:
{ann}
rules:
{rules_yaml}
---
apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRoleBinding
metadata:
  name: {name}
  annotations:
{ann}
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: ClusterRole
  name: {name}
subjects:
- kind: ServiceAccount
  name: {name}
  namespace: {_NS_EXPR}
"""

    def job(name: str, hook: str, command_yaml: str,
            job_name_expr: str) -> str:
        return f"""\
apiVersion: batch/v1
kind: Job
metadata:
  name: {job_name_expr}
  namespace: {_NS_EXPR}
  annotations:
    helm.sh/hook: {hook}
    helm.sh/hook-weight: "1"
    helm.sh/hook-delete-policy: hook-succeeded,before-hook-creation
spec:
  backoffLimit: 6
  ttlSecondsAfterFinished: 3600
  template:
    metadata:
      labels:
        app: {name}
    spec:
      serviceAccountName: {name}
      restartPolicy: OnFailure
{{{{- if .Values.operator.priorityClassName }}}}
      priorityClassName: {{{{ .Values.operator.priorityClassName }}}}
{{{{- end }}}}
{_pod_passthrough(6)}
      containers:
      - name: {name}
        image: {{{{ {IMAGE_EXPR} }}}}
        imagePullPolicy: {{{{ .Values.operator.imagePullPolicy | default "IfNotPresent" }}}}
        command:
{command_yaml}
"""

    upgrade = ("{{- if .Values.operator.upgradeCRD }}\n"
               + rbac("tpu-operator-upgrade-crd", "pre-upgrade", """\
- apiGroups: ["apiextensions.k8s.io"]
  resources: ["customresourcedefinitions"]
  verbs: ["create", "get", "list", "watch", "patch", "update"]""")
               + "---\n"
               + job("tpu-operator-upgrade-crd", "pre-upgrade", """\
        - tpu-operator-maintenance
        - apply-crds""",
                     "tpu-operator-upgrade-crd-"
                     f"{{{{ {IMAGE_EXPR} | sha256sum | trunc 8 }}}}")
               + "{{- end }}\n")
    cleanup = ("{{- if .Values.operator.cleanupCRD }}\n"
               + rbac("tpu-operator-cleanup-crd", "pre-delete", """\
- apiGroups: ["tpu.graft.dev"]
  resources: ["tpuclusterpolicies", "tpudrivers"]
  verbs: ["get", "list", "delete"]
- apiGroups: ["apiextensions.k8s.io"]
  resources: ["customresourcedefinitions"]
  verbs: ["get", "list", "delete"]""")
               + "---\n"
               + job("tpu-operator-cleanup-crd", "pre-delete", """\
        - tpu-operator-maintenance
        - cleanup""",
                     "tpu-operator-cleanup-crd")
               + "{{- end }}\n")
    return {"templates/hooks-upgrade-crd.yaml": upgrade,
            "templates/hooks-cleanup-crd.yaml": cleanup}


def _derived_template(obj: dict) -> str:
    """A template mechanically derived from a packaging.py object: render
    with the sentinel namespace, substitute the Release expression."""
    text = yaml.safe_dump(obj, default_flow_style=False, sort_keys=False)
    return text.replace(_NS_SENTINEL, _NS_EXPR)


def generate_chart() -> Dict[str, str]:
    """relpath -> content for the whole chart."""
    from ..api.crd import all_crds

    ns = _NS_SENTINEL
    files: Dict[str, str] = {
        "Chart.yaml": yaml.safe_dump({
            "apiVersion": "v2",
            "name": "tpu-operator",
            "description": "TPU operator: installs and lifecycle-manages "
                           "the TPU software stack on GKE TPU nodes",
            "type": "application",
            "version": __version__,
            "appVersion": f"v{__version__}",
            "kubeVersion": ">=1.24.0-0",
        }, sort_keys=False),
        # the chart values ARE the canonical values — one file, two
        # consumers (helm and tpuop-cfg), zero drift.
        # NO templates/namespace.yaml: helm owns the release namespace
        # (`--create-namespace`); a templated Namespace object would fail
        # helm 3's release-ownership check on install. The plain-apply
        # bundle (`generate all`) still carries the Namespace.
        "values.yaml": values_mod.VALUES_FILE.read_text(),
        "templates/serviceaccount.yaml": _derived_template(
            service_account(ns)),
        "templates/clusterrole.yaml": _derived_template(cluster_role()),
        "templates/clusterrolebinding.yaml": _derived_template(
            cluster_role_binding(ns)),
        "templates/role.yaml": _derived_template(namespaced_role(ns)),
        "templates/rolebinding.yaml": _derived_template(role_binding(ns)),
        "templates/deployment.yaml": DEPLOYMENT_TEMPLATE,
        "templates/clusterpolicy.yaml": CLUSTERPOLICY_TEMPLATE,
        "templates/tpudrivers.yaml": TPUDRIVERS_TEMPLATE,
        "templates/pluginconfig.yaml": PLUGINCONFIG_TEMPLATE,
        **_hook_templates(),
        ".helmignore": "*.tgz\n",
    }
    for i, crd in enumerate(all_crds()):
        files[f"crds/{crd['metadata']['name'].split('.')[0]}.yaml"] = \
            yaml.safe_dump(crd, default_flow_style=False, sort_keys=False)
    return files


def write_chart(directory: Optional[pathlib.Path] = None) -> pathlib.Path:
    directory = pathlib.Path(directory or CHART_DIR)
    files = generate_chart()
    # the directory is chart-owned: files the generator no longer emits
    # (renamed/removed templates) must not survive as stale manifests a
    # helm install would still apply
    if directory.exists():
        for p in directory.rglob("*"):
            if p.is_file() and \
                    p.relative_to(directory).as_posix() not in files:
                p.unlink()
    for rel, content in files.items():
        path = directory / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content)
    return directory


def render_chart(values: Optional[Dict[str, Any]] = None,
                 chart_files: Optional[Dict[str, str]] = None,
                 include_crds: bool = True) -> List[dict]:
    """Render the chart the way ``helm template`` would: user values
    deep-merged over the chart's values.yaml, ``.Release.Namespace``
    bound (here from values.namespace — the offline stand-in for
    ``helm -n``), every templates/*.yaml rendered and the object stream
    parsed. The golden tests pin this equal to render_bundle()."""
    from ..render.engine import render_string

    files = chart_files or generate_chart()
    defaults = yaml.safe_load(files["values.yaml"]) or {}
    merged = values_mod.deep_merge(defaults, values or {})
    data = {
        "Values": merged,
        "Release": {"Namespace": merged.get("namespace", "tpu-operator"),
                    "Name": "tpu-operator"},
        "Chart": yaml.safe_load(files["Chart.yaml"]),
    }
    docs: List[dict] = []
    if include_crds:
        for rel in sorted(files):
            if rel.startswith("crds/"):
                docs.extend(d for d in yaml.safe_load_all(files[rel]) if d)
    for rel in sorted(files):
        if not rel.startswith("templates/"):
            continue
        rendered = render_string(files[rel], data, name=rel)
        docs.extend(d for d in yaml.safe_load_all(rendered) if d)
    return docs
