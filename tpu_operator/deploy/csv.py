"""OLM ClusterServiceVersion generation — the bundle/ slot.

The reference ships a real OLM bundle per release
(bundle/manifests/gpu-operator-certified.clusterserviceversion.yaml:
alm-examples annotation, owned CRDs with descriptors, an install strategy
embedding the manager Deployment + clusterPermissions, installModes,
relatedImages) and CI keeps it consistent with the CRD
(``make validate-csv``, Makefile:233-236). Here the CSV is generated from
the same code that renders the Deployment/RBAC/CRDs, so it cannot drift:

    tpuop-cfg generate bundle [--values my-values.yaml]

emits the bundle manifest stream: the CSV, every CRD, and the OLM bundle
annotations document (metadata/annotations.yaml content).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from .. import __version__
from ..api import KIND_CLUSTER_POLICY, KIND_TPU_DRIVER
from ..api.crd import all_crds
from .packaging import (
    cluster_role,
    namespaced_role,
    operator_deployment,
    sample_cluster_policy,
)

PACKAGE_NAME = "tpu-operator"
DEFAULT_CHANNEL = "stable"

_DESCRIPTION = """\
The TPU Operator manages the software stack TPU nodes need to serve
accelerated workloads in Kubernetes: libtpu installation, device/runtime
hookup, the google.com/tpu device plugin, telemetry exporters, feature
discovery, topology/slice shaping, and a per-node validation gate that
proves each layer (through a real JAX matmul + ICI collective) before
workloads schedule. A singleton TPUClusterPolicy CR configures the whole
stack; per-pool TPUDriver CRs manage libtpu flavors per node pool."""


def _sample_tpudriver() -> dict:
    from ..api.tpudriver import V1ALPHA1

    return {
        "apiVersion": V1ALPHA1,
        "kind": KIND_TPU_DRIVER,
        "metadata": {"name": "v5e-stable"},
        "spec": {"channel": "stable",
                 "nodeSelector": {
                     "cloud.google.com/gke-tpu-accelerator": "tpu-v5e"}},
    }


def _sample_slicerequest() -> dict:
    from ..api.slicerequest import new_slice_request

    return new_slice_request(
        "train-8x", spec={"chips": 8, "topology": "2x4",
                          "preferredGenerations": ["v5p", "v5e"]})


def _owned_crds() -> List[dict]:
    from ..api import V1, V1ALPHA1
    from ..api.slicerequest import KIND_SLICE_REQUEST
    from ..api.slicerequest import V1ALPHA1 as SR_V1ALPHA1

    return [
        {
            "name": "tpuclusterpolicies.tpu.graft.dev",
            "kind": KIND_CLUSTER_POLICY,
            "version": V1.split("/")[-1],
            "displayName": "TPUClusterPolicy",
            "description": "Singleton configuration of the whole TPU "
                           "software stack; one sub-spec per operand.",
            "resources": [
                {"kind": "DaemonSet", "name": "", "version": "apps/v1"},
                {"kind": "Service", "name": "", "version": "v1"},
                {"kind": "RuntimeClass", "name": "",
                 "version": "node.k8s.io/v1"},
            ],
            "specDescriptors": [
                {"path": "devicePlugin.enabled",
                 "displayName": "Device Plugin",
                 "description": "Advertise google.com/tpu to kubelet",
                 "x-descriptors": [
                     "urn:alm:descriptor:com.tectonic.ui:booleanSwitch"]},
                {"path": "validator.iciBandwidthThreshold",
                 "displayName": "ICI bandwidth threshold",
                 "description": "Fraction of theoretical ICI bandwidth "
                                "the collective proof must reach"},
                {"path": "upgradePolicy.autoUpgrade",
                 "displayName": "Auto upgrade",
                 "description": "Allow automatic rolling libtpu upgrades",
                 "x-descriptors": [
                     "urn:alm:descriptor:com.tectonic.ui:booleanSwitch"]},
            ],
            "statusDescriptors": [
                {"path": "state", "displayName": "State",
                 "description": "ignored|ready|notReady|disabled"},
            ],
        },
        {
            "name": "tpudrivers.tpu.graft.dev",
            "kind": KIND_TPU_DRIVER,
            "version": V1ALPHA1.split("/")[-1],
            "displayName": "TPUDriver",
            "description": "Per-node-pool libtpu flavor (channel/version "
                           "per generation x topology pool).",
            "statusDescriptors": [
                {"path": "state", "displayName": "State"},
            ],
        },
        {
            "name": "slicerequests.tpu.graft.dev",
            "kind": KIND_SLICE_REQUEST,
            "version": SR_V1ALPHA1.split("/")[-1],
            "displayName": "SliceRequest",
            "description": "A request for a TPU slice; the placement "
                           "engine binds it to concrete nodes over the "
                           "ICI topology.",
            "statusDescriptors": [
                {"path": "phase", "displayName": "Phase",
                 "description": "Pending|Placed|Unschedulable"},
            ],
        },
    ]


def render_csv(values: Dict[str, Any]) -> dict:
    """A real, structurally-complete ClusterServiceVersion for the
    current version and values-resolved operator image."""
    from .values import operator_image

    image = operator_image(values)
    deployment = operator_deployment(
        values.get("namespace", "tpu-operator"), image,
        values.get("operator") or {})
    # OLM owns name/namespace placement; the install strategy embeds only
    # the Deployment's spec
    alm_examples = [sample_cluster_policy(), _sample_tpudriver(),
                    _sample_slicerequest()]
    return {
        "apiVersion": "operators.coreos.com/v1alpha1",
        "kind": "ClusterServiceVersion",
        "metadata": {
            "name": f"{PACKAGE_NAME}.v{__version__}",
            "namespace": "placeholder",
            "labels": {
                "operatorframework.io/arch.amd64": "supported",
                "operatorframework.io/arch.arm64": "supported",
                "pod-security.kubernetes.io/enforce": "privileged",
                "pod-security.kubernetes.io/audit": "privileged",
                "pod-security.kubernetes.io/warn": "privileged",
            },
            "annotations": {
                "alm-examples": json.dumps(alm_examples, indent=2),
                "capabilities": "Deep Insights",
                "categories": "AI/Machine Learning, OpenShift Optional",
                "containerImage": image,
                "description": "Automates TPU software stack lifecycle "
                               "management in Kubernetes",
                "support": PACKAGE_NAME,
                # OLM reads this from the CSV object (the copy in
                # metadata/annotations.yaml is informational)
                "operatorframework.io/suggested-namespace": "tpu-operator",
            },
        },
        "spec": {
            "displayName": "TPU Operator",
            "description": _DESCRIPTION,
            "keywords": ["tpu", "jax", "xla", "device-plugin",
                         "accelerator", "operator"],
            "maintainers": [{"name": "tpu-operator maintainers",
                             "email": "maintainers@tpu-operator.dev"}],
            "provider": {"name": PACKAGE_NAME},
            "links": [{"name": "Source",
                       "url": "https://github.com/tpu-operator/tpu-operator"}],
            "maturity": "stable",
            "version": __version__,
            "minKubeVersion": "1.27.0",
            "installModes": [
                {"type": "OwnNamespace", "supported": True},
                {"type": "SingleNamespace", "supported": True},
                {"type": "MultiNamespace", "supported": False},
                {"type": "AllNamespaces", "supported": False},
            ],
            "install": {
                "strategy": "deployment",
                "spec": {
                    "clusterPermissions": [{
                        "serviceAccountName": "tpu-operator",
                        "rules": cluster_role()["rules"],
                    }],
                    # OLM's native namespaced-permission slot carries the
                    # Role rules (the chart's role.yaml split)
                    "permissions": [{
                        "serviceAccountName": "tpu-operator",
                        "rules": namespaced_role("tpu-operator")["rules"],
                    }],
                    "deployments": [{
                        "name": "tpu-operator",
                        "spec": deployment["spec"],
                    }],
                },
            },
            "customresourcedefinitions": {"owned": _owned_crds()},
            "relatedImages": [{"name": "tpu-operator", "image": image}],
        },
    }


def bundle_annotations() -> dict:
    """metadata/annotations.yaml content of an OLM registry+v1 bundle,
    including the scorecard test-config pointers OLM tooling reads
    (ref bundle/metadata/annotations.yaml)."""
    return {
        "annotations": {
            "operators.operatorframework.io.bundle.mediatype.v1":
                "registry+v1",
            "operators.operatorframework.io.bundle.manifests.v1":
                "manifests/",
            "operators.operatorframework.io.bundle.metadata.v1": "metadata/",
            "operators.operatorframework.io.bundle.package.v1": PACKAGE_NAME,
            "operators.operatorframework.io.bundle.channels.v1":
                DEFAULT_CHANNEL,
            "operators.operatorframework.io.bundle.channel.default.v1":
                DEFAULT_CHANNEL,
            "operators.operatorframework.io.test.config.v1":
                "tests/scorecard/",
            "operators.operatorframework.io.test.mediatype.v1":
                "scorecard+v1",
            "operatorframework.io/suggested-namespace": "tpu-operator",
        },
    }


def scorecard_config() -> dict:
    """tests/scorecard/config.yaml — the operator-sdk scorecard stages
    the reference bundle carries (bundle/tests/scorecard/config.yaml):
    basic spec sanity plus OLM bundle validation, run in parallel."""
    test = "quay.io/operator-framework/scorecard-test:latest"
    return {
        "kind": "Configuration",
        "apiVersion": "scorecard.operatorframework.io/v1alpha3",
        "metadata": {"name": "config"},
        "stages": [{
            "parallel": True,
            "tests": [
                {"image": test,
                 "entrypoint": ["scorecard-test", "basic-check-spec"],
                 "labels": {"suite": "basic",
                            "test": "basic-check-spec-test"}},
                {"image": test,
                 "entrypoint": ["scorecard-test", "olm-bundle-validation"],
                 "labels": {"suite": "olm",
                            "test": "olm-bundle-validation-test"}},
            ],
        }],
    }


def render_bundle_stream(values: Dict[str, Any]) -> List[dict]:
    """The full bundle: CSV + owned CRDs (the manifests/ dir content)
    followed by the bundle annotations (the metadata/ dir content)."""
    return [render_csv(values)] + all_crds() + [bundle_annotations()]


def write_bundle_dir(values: Dict[str, Any], out_dir: str) -> List[str]:
    """Write the registry+v1 bundle DIRECTORY layout OLM tooling
    consumes (`opm`, `operator-sdk bundle validate`, scorecard):

        manifests/<csv>.clusterserviceversion.yaml + one file per CRD
        metadata/annotations.yaml
        tests/scorecard/config.yaml

    CRD filenames follow the reference's `<group>_<plural>.yaml` form
    (bundle/v24.3.0/manifests/nvidia.com_clusterpolicies.yaml). Returns
    the relative paths written."""
    import os

    import yaml

    def write(rel: str, doc: dict) -> str:
        path = os.path.join(out_dir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            yaml.safe_dump(doc, f, sort_keys=False)
        return rel

    written = [write(
        f"manifests/{PACKAGE_NAME}.clusterserviceversion.yaml",
        render_csv(values))]
    for crd in all_crds():
        group, plural = crd["spec"]["group"], crd["spec"]["names"]["plural"]
        written.append(write(f"manifests/{group}_{plural}.yaml", crd))
    written.append(write("metadata/annotations.yaml",
                         bundle_annotations()))
    written.append(write("tests/scorecard/config.yaml",
                         scorecard_config()))
    return written
