from .agent import FeatureDiscovery, compute_feature_labels  # noqa: F401
