"""TPU feature discovery — the gpu-feature-discovery slot.

The reference deploys GFD (external image, state dir
``assets/gpu-feature-discovery``, ``TransformGPUDiscoveryPlugin``
object_controls.go:867) to label nodes with GPU *properties* (product,
memory, MIG profile) discovered on-node via NFD. The TPU analog discovers
chip properties from the hardware actually present — device nodes, the
native libtpu probe, GKE-provided labels as hints — and stamps
``tpu.graft.dev/tpu.*`` property labels so schedulers and the topology
manager can select by topology/HBM/ICI class without GKE-specific keys.

Ownership split (why this can't fight the operator's labeler): the
operator's StateManager owns presence/deploy/generation/chips labels;
this agent owns only ``labels.FEATURE_LABELS``. Stale feature labels are
removed when the property disappears.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Dict, Optional

from ..api import labels as L
from ..runtime.client import Client
from ..runtime.objects import label_delta, labels_of, name_of
from ..state.nodepool import NodePool
from ..validator.components import discover_chips
from ..workloads.hardware import CHIPS

log = logging.getLogger("tpu_feature_discovery")


def compute_feature_labels(node_labels: Dict[str, str],
                           chips: Dict) -> Dict[str, Optional[str]]:
    """Property labels for a node; ``None`` marks removal of a stale key.

    ``chips`` is the validator-style discovery dict (count/source/devices,
    optional kind/libtpu_version). GKE labels act as hints for topology and
    accelerator naming; generation falls back to the operator-stamped label
    so discovery works on non-GKE TPU-VMs too.
    """
    want: Dict[str, Optional[str]] = {}
    accel = node_labels.get(L.GKE_TPU_ACCELERATOR, "")
    topo = node_labels.get(L.GKE_TPU_TOPOLOGY,
                           os.environ.get("TPU_TOPOLOGY", ""))
    if accel:
        want[L.TPU_ACCELERATOR] = accel
    if topo:
        want[L.TPU_TOPOLOGY] = topo
        want[L.TPU_MULTIHOST] = str(
            NodePool(accelerator=accel, topology=topo).multi_host).lower()
    gen = (L.accelerator_generation(accel) if accel
           else node_labels.get(L.TPU_GENERATION, ""))
    spec = CHIPS.get(gen)
    if spec is not None:
        want[L.TPU_MEMORY_GB] = str(int(spec.hbm_gb))
        want[L.TPU_ICI_GBPS] = str(int(spec.ici_bw_gbps))
    if chips.get("libtpu_version"):
        want[L.LIBTPU_VERSION] = str(chips["libtpu_version"])
    # anything we own but can no longer derive gets removed
    for key in L.FEATURE_LABELS:
        if key not in want and key in node_labels:
            want[key] = None
    return want


@dataclass
class FeatureDiscovery:
    client: Client
    node_name: str

    def apply_once(self) -> Dict[str, Optional[str]]:
        node = self.client.get("v1", "Node", self.node_name)
        have = labels_of(node)
        want = compute_feature_labels(have, discover_chips())
        delta = label_delta(have, want)
        if delta:
            self.client.patch("v1", "Node", name_of(node),
                              {"metadata": {"labels": delta}})
            log.info("node %s feature labels: %s", self.node_name, delta)
        return delta

    def run_forever(self, interval: float = 60.0) -> None:  # pragma: no cover
        while True:
            try:
                self.apply_once()
            except Exception:
                log.exception("feature discovery failed")
            time.sleep(interval)


def main() -> int:  # pragma: no cover - container entrypoint
    logging.basicConfig(level=logging.INFO)
    from ..runtime.kubeclient import HTTPClient, KubeConfig

    agent = FeatureDiscovery(client=HTTPClient(KubeConfig.load()),
                             node_name=os.environ["NODE_NAME"])
    agent.run_forever(interval=float(os.environ.get("INTERVAL", "60")))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
