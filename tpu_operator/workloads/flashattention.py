"""Pallas flash attention: the long-context hot op as a TPU kernel.

XLA's attention materializes the [Sq, Sk] score matrix in HBM once the
fusion budget is exceeded; flash attention keeps it in VMEM by tiling Q
and streaming K/V chunks through an online softmax (running max +
normalizer), so HBM traffic stays O(S*D) instead of O(S^2). This kernel
is the local-block engine of the context-parallel path
(workloads/ringattention.py): each ring hop's (Q-block, KV-block) attend
runs here, and the kernel's (m, l) statistics are exactly what the ring
merge needs, so the fused path composes with ppermute instead of
replacing it.

Layout [BH, S, D]: batch*heads on the grid's first axis, one Q tile per
second axis step, K/V streamed in ``chunk`` slices by an inner loop.
Causal masking is positional (global offsets passed as SMEM scalars)
because in ring attention the K block's global position depends on which
hop it arrived on. Runs in interpret mode on CPU (tests) and compiled on
TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(offs_ref, q_ref, k_ref, v_ref, out_ref, m_ref, l_ref,
                  *, chunk: int, causal: bool, scale: float):
    """One (bh, q-tile) program: stream K/V chunks, online softmax.

    offs_ref (SMEM): [q_offset, k_offset] global positions for masking.
    q_ref: [1, Tq, D]; k_ref/v_ref: [1, Sk, D]; out_ref: [1, Tq, D];
    m_ref/l_ref: [1, Tq, 128] stat outputs (lane 0 meaningful, the lane
    dim exists to satisfy TPU tiling).
    """
    q = q_ref[0].astype(jnp.float32)  # [Tq, D]
    tq = q.shape[0]
    sk = k_ref.shape[1]
    qi = pl.program_id(1)
    q_pos = offs_ref[0] + qi * tq + jax.lax.broadcasted_iota(
        jnp.int32, (tq, chunk), 0)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * chunk, chunk), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * chunk, chunk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Tq, chunk]
        if causal:
            k_pos = offs_ref[1] + j * chunk + jax.lax.broadcasted_iota(
                jnp.int32, (tq, chunk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        bm = jnp.max(s, axis=1, keepdims=True)            # [Tq, 1]
        m_new = jnp.maximum(m, bm)
        # fully-masked tiles keep exp well-defined
        p = jnp.exp(s - jnp.where(m_new <= NEG_INF / 2, 0.0, m_new))
        p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m_new <= NEG_INF / 2, 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((tq, q.shape[1]), jnp.float32)
    m0 = jnp.full((tq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((tq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, sk // chunk, body, (acc0, m0, l0))
    out_ref[0] = (acc / jnp.where(l == 0.0, 1.0, l)).astype(out_ref.dtype)
    m_ref[0] = jnp.broadcast_to(m, (tq, 128)).astype(jnp.float32)
    l_ref[0] = jnp.broadcast_to(l, (tq, 128)).astype(jnp.float32)


def flash_attention_blocks(
        q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
        q_offset, k_offset, causal: bool = True,
        q_tile: int = 256, chunk: int = 512,
        interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused attend of q against (k, v) with positional causal masking.

    q, k, v: [BH, S, D]. Returns (out [BH, Sq, D] — NORMALIZED,
    m [BH, Sq], l [BH, Sq]) so a ring merge can combine blocks:
    unnormalized partial = out * l.

    ``q_offset``/``k_offset`` are global sequence positions of element 0
    (traced values are fine — they ride in SMEM), which is how ring hops
    express "this K block came from device (i - hop) % n".
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    bh, sq, d = q.shape
    sk = k.shape[1]
    q_tile = min(q_tile, sq)
    chunk = min(chunk, sk)
    assert sq % q_tile == 0 and sk % chunk == 0
    offs = jnp.asarray([q_offset, k_offset], jnp.int32)
    scale = 1.0 / np.sqrt(d)
    grid = (bh, sq // q_tile)
    out, m, l = pl.pallas_call(
        partial(_flash_kernel, chunk=chunk, causal=causal, scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, q_tile, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, q_tile, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, q_tile, 128), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, q_tile, 128), lambda b, i: (b, i, 0)),
        ),
        interpret=interpret,
    )(offs, q, k, v)
    return out, m[..., 0], l[..., 0]


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Plain single-device flash attention, [B, S, H, D] layout (the
    drop-in for reference_attention). Differentiable: the backward pass
    is the memory-efficient chunked recomputation (see _flash_bwd)."""
    B, S, H, D = q.shape
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    out = _flash_fwd_core(fold(q), fold(k), fold(v), causal, interpret)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# backward pass: O(S * chunk) memory via chunked recomputation
# ---------------------------------------------------------------------------
# The forward saves only (out, m, l) — the flash residuals — and the
# backward re-materializes the probability tiles one K-chunk at a time
# (the standard flash-attention backward recurrence: D = rowsum(dO * O),
# dS = P * (dP - D)), so HBM stays O(S*D) end to end instead of the
# O(S^2) a naive autodiff of attention would spill.


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_fwd_core(q, k, v, causal: bool, interpret):
    out, _, _ = flash_attention_blocks(q, k, v, 0, 0, causal=causal,
                                       interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, interpret):
    out, m, l = flash_attention_blocks(q, k, v, 0, 0, causal=causal,
                                       interpret=interpret)
    return out, (q, k, v, out, m, l)


def _flash_bwd_rule(causal, interpret, res, dout, chunk: int = 512):
    q, k, v, out, m, l = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    chunk = min(chunk, sk)
    scale = 1.0 / np.sqrt(d)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    in_dtypes = (q.dtype, k.dtype, v.dtype)
    # compute in f32 like the forward kernel does (lines 44,53-54):
    # recomputed P must match the forward's P, not a bf16 quantization
    q = q.astype(jnp.float32)
    dout = dout.astype(jnp.float32)
    out = out.astype(jnp.float32)
    # D_i = sum_j dO_ij * O_ij (the softmax-normalizer gradient term)
    delta = jnp.sum(dout * out, axis=-1)                     # [BH, Sq]
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, chunk), 0)

    def per_chunk(dq_acc, j):
        ks = jax.lax.dynamic_slice_in_dim(
            k, j * chunk, chunk, axis=1).astype(jnp.float32)
        vs = jax.lax.dynamic_slice_in_dim(
            v, j * chunk, chunk, axis=1).astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", q, ks) * scale        # [BH,Sq,C]
        if causal:
            k_pos = j * chunk + jax.lax.broadcasted_iota(
                jnp.int32, (sq, chunk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - m[..., None]) / l_safe[..., None]
        p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
        dv_c = jnp.einsum("bqk,bqd->bkd", p, dout)
        dp = jnp.einsum("bqd,bkd->bqk", dout, vs)
        ds = p * (dp - delta[..., None])                     # [BH,Sq,C]
        # dq accumulates in the carry (stacking per-chunk dq would be
        # O(Sq*Sk*D/chunk) — the spill this backward exists to avoid);
        # dk/dv chunks stack to O(Sk*D) total, which is fine
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, ks) * scale
        dk_c = jnp.einsum("bqk,bqd->bkd", ds, q) * scale
        return dq_acc, (dk_c, dv_c)

    n_chunks = sk // chunk
    dq, (dk_cs, dv_cs) = jax.lax.scan(
        per_chunk, jnp.zeros((bh, sq, d), jnp.float32),
        jnp.arange(n_chunks))
    dk = jnp.moveaxis(dk_cs, 0, 1).reshape(bh, sk, d)
    dv = jnp.moveaxis(dv_cs, 0, 1).reshape(bh, sk, d)
    # cotangents must match the primal input dtypes (bf16 on TPU)
    return tuple(t.astype(dt) for t, dt in zip((dq, dk, dv), in_dtypes))


_flash_fwd_core.defvjp(_flash_fwd_rule, _flash_bwd_rule)
