"""Expert parallelism: a Switch-style top-1 MoE layer over a 1D mesh.

The ep slot of the dp/tp/pp/sp/ep strategy set: one expert FFN per
device along an ``expert`` mesh axis; each device routes its resident
tokens (top-1, fixed capacity, overflow dropped — static shapes so XLA
compiles one program), dispatches them to their experts with
``jax.lax.all_to_all``, applies its own expert, and all-to-alls the
results back — the canonical MoE exchange that stresses the all-to-all
path of the interconnect, complementing ring attention's neighbor
ppermute and the allreduce validator.

Like every workload here it is also a proof: the sharded layer must
match a single-device oracle running the identical routing math, so a
corrupted all-to-all cannot pass. No reference analog (SURVEY.md §2.5:
the GPU operator ships no parallelism implementations).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import shard_map

from .backend import pins_platform


def init_moe_params(key, n_experts: int, d_model: int, d_ff: int) -> dict:
    """Router (replicated) + stacked per-expert FFN weights (leading axis
    = expert, sharded one-per-device)."""
    kr, k1, k2 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(kr, (d_model, n_experts),
                                    jnp.float32) / np.sqrt(d_model),
        "w1": jax.random.normal(k1, (n_experts, d_model, d_ff),
                                jnp.float32) / np.sqrt(d_model),
        "w2": jax.random.normal(k2, (n_experts, d_ff, d_model),
                                jnp.float32) / np.sqrt(d_ff),
    }


def _route(x, router, n_experts: int, capacity: int):
    """Top-1 routing with fixed capacity. x: [b, D]. Returns the
    combine weights [b, E, C] (zero for dropped tokens) and the boolean
    dispatch mask of the same shape."""
    logits = x @ router                          # [b, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)          # [b]
    gate = jnp.max(probs, axis=-1)               # [b]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)  # [b, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1          # [b, E]
    kept = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                            dtype=jnp.float32)             # [b, E, C]
    dispatch = pos_oh * kept[..., None]                    # [b, E, C]
    combine = dispatch * gate[:, None, None]
    return combine, dispatch


def expert_ffn(w1, w2, x):
    return jax.nn.gelu(x @ w1) @ w2


def _moe_local(params, x, axis_name: str, capacity: int):
    """Per-device body (inside shard_map). x: [b, D] resident tokens;
    params: router replicated, expert weights sharded (leading axis 1)."""
    n_experts = lax.psum(1, axis_name)
    combine, dispatch = _route(x, params["router"], n_experts, capacity)
    # gather this device's outgoing tokens per expert: [E, C, D]
    sent = jnp.einsum("bec,bd->ecd", dispatch, x)
    # exchange: dim 0 becomes the SOURCE device, my expert everywhere
    received = lax.all_to_all(sent, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)   # [E*1... -> [S, C, D] with S = n_devices
    w1 = params["w1"][0]
    w2 = params["w2"][0]
    flat = received.reshape(-1, received.shape[-1])
    done = expert_ffn(w1, w2, flat).reshape(received.shape)
    # route results back to their source devices
    returned = lax.all_to_all(done, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)   # [E, C, D]
    # combine weights zero out dropped tokens (they contribute nothing,
    # matching the oracle's capacity semantics)
    return jnp.einsum("bec,ecd->bd", combine, returned)


def moe_forward(params: dict, x: jax.Array, mesh: Mesh,
                axis_name: str = "expert",
                capacity: "int | None" = None) -> jax.Array:
    """x: [B, D], batch sharded across the expert axis (each device owns
    B / n_devices resident tokens). One expert per device. An explicit
    capacity=0 means drop everything (it is not a falsy default)."""
    n_dev = int(np.prod(list(mesh.shape.values())))
    b_local = x.shape[0] // n_dev
    cap = b_local if capacity is None else capacity
    fn = shard_map(
        partial(_moe_local, axis_name=axis_name, capacity=cap),
        mesh=mesh,
        in_specs=({"router": P(), "w1": P(axis_name), "w2": P(axis_name)},
                  P(axis_name)),
        out_specs=P(axis_name),
    )
    return fn(params, x)


def reference_moe(params: dict, x: jax.Array, n_devices: int,
                  capacity: int) -> jax.Array:
    """Single-device oracle with the identical per-device routing and
    capacity math (tokens are grouped by resident device first, because
    capacity is enforced per source device per expert)."""
    n_experts = params["w1"].shape[0]
    b_local = x.shape[0] // n_devices
    outs = []
    for d in range(n_devices):
        xd = x[d * b_local:(d + 1) * b_local]
        combine, dispatch = _route(xd, params["router"], n_experts,
                                   capacity)
        sent = jnp.einsum("bec,bd->ecd", dispatch, xd)       # [E, C, D]
        done = jnp.stack([
            expert_ffn(params["w1"][e], params["w2"][e], sent[e])
            for e in range(n_experts)])
        outs.append(jnp.einsum("bec,ecd->bd", combine, done))
    return jnp.concatenate(outs, axis=0)


@dataclass
class MoEResult:
    experts: int
    tokens: int
    capacity: int
    dropped_fraction: float
    max_abs_err: float
    correct: bool
    device_kind: str


@pins_platform
def run(mesh: Mesh = None, axis_name: str = "expert",
        tokens_per_expert: int = 16, d_model: int = 32, d_ff: int = 64,
        seed: int = 0) -> MoEResult:
    """Expert-parallel MoE over the mesh, diffed against the oracle."""
    from ..parallel.mesh import ring_mesh

    if mesh is None:
        mesh = ring_mesh(axis_name=axis_name)
    n_dev = int(np.prod(list(mesh.shape.values())))
    key = jax.random.PRNGKey(seed)
    kp, kx = jax.random.split(key)
    params = init_moe_params(kp, n_dev, d_model, d_ff)
    x = jax.random.normal(kx, (n_dev * tokens_per_expert, d_model),
                          jnp.float32)
    cap = tokens_per_expert

    sharded_params = jax.device_put(params, {
        "router": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(axis_name)),
        "w2": NamedSharding(mesh, P(axis_name)),
    })
    xs = jax.device_put(x, NamedSharding(mesh, P(axis_name)))
    out = jax.jit(partial(moe_forward, mesh=mesh, axis_name=axis_name,
                          capacity=cap))(sharded_params, xs)
    oracle = reference_moe(params, x, n_dev, cap)
    err = float(jnp.max(jnp.abs(out - oracle)))

    # dropped fraction (oracle math): tokens beyond an expert's capacity
    # on their device produce zero output
    dropped = float(jnp.mean(jnp.all(oracle == 0.0, axis=-1)))
    dev = jax.devices()[0]
    return MoEResult(
        experts=n_dev, tokens=x.shape[0], capacity=cap,
        dropped_fraction=dropped, max_abs_err=err,
        correct=bool(err < 1e-4),
        device_kind=getattr(dev, "device_kind", dev.platform))


def main() -> int:  # pragma: no cover - manual entry
    res = run()
    print(res)
    return 0 if res.correct else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
