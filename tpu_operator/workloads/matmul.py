"""Single-chip MXU proof: sustained bf16 matmul throughput.

Replaces the reference's CUDA ``vectorAdd`` workload proof
(validator/cuda-workload-validation.yaml, spawned at validator/main.go:1350)
with something that exercises the TPU where its FLOPs live: a chained NxN
bf16 matmul under ``lax.scan`` (static shapes, one compile, MXU-aligned
tiles), measured with a remote-runtime-safe protocol.

Measurement protocol (matters on tunneled/async PJRT backends, where
``block_until_ready`` can return before remote execution finishes): chain
``calls`` executions through a data dependency (each call consumes the
previous call's output) and synchronize ONCE at the end by fetching a
single element to the host. The fixed host roundtrip is amortized across
calls*iters matmuls, so the conservative (latency-included) figure
converges to true device throughput.

B is pre-scaled by 1/sqrt(N) so the chained products stay O(1) in bf16
without any per-iteration elementwise renormalization polluting the
matmul stream.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .backend import pins_platform
from .hardware import chip_spec_for


@dataclass
class MatmulResult:
    size: int
    iters: int
    calls: int
    seconds: float
    tflops: float
    peak_tflops: Optional[float]
    utilization: Optional[float]
    device_kind: str
    checksum_ok: bool


@pins_platform
def run(size: int = 8192, iters: int = 32, calls: int = 8, repeats: int = 3,
        device: Optional[jax.Device] = None) -> MatmulResult:
    device = device or jax.devices()[0]
    dtype = jnp.bfloat16
    key = jax.random.PRNGKey(0)
    ka, kb = jax.random.split(key)
    a = jax.device_put(jax.random.normal(ka, (size, size), dtype=dtype), device)
    b = jax.device_put(
        jax.random.normal(kb, (size, size), dtype=dtype)
        / jnp.sqrt(jnp.float32(size)).astype(dtype), device)

    def chain(a, b):
        def step(c, _):
            return c @ b, ()

        out, _ = lax.scan(step, a, None, length=iters)
        return out

    # inputs were device_put above; jit follows input placement (the
    # device= kwarg is deprecated)
    g = jax.jit(chain)
    out = g(a, b)
    np.asarray(out[:1, :1])  # compile + full sync

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = a
        for _ in range(calls):
            out = g(out, b)
        probe = np.asarray(out[:1, :1])  # single end-of-chain sync
        best = min(best, time.perf_counter() - t0)

    flops = 2.0 * size * size * size * iters * calls
    tflops = flops / best / 1e12
    spec = chip_spec_for(getattr(device, "device_kind", ""))
    checksum = bool(np.isfinite(probe).all())
    return MatmulResult(
        size=size, iters=iters, calls=calls, seconds=best, tflops=tflops,
        peak_tflops=spec.peak_bf16_tflops if spec else None,
        utilization=(tflops / spec.peak_bf16_tflops) if spec else None,
        device_kind=getattr(device, "device_kind", "cpu"),
        checksum_ok=checksum)


def main() -> int:
    import json

    res = run()
    print(json.dumps(res.__dict__))
    return 0 if res.checksum_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
