"""Hardened JAX backend bring-up for validator workloads and the bench.

libtpu is single-client: a second process touching the chip gets
``UNAVAILABLE: TPU backend setup/compile error``. The reference's validator
retries its proofs on a 5 s cadence until the layer below is actually ready
(validator/main.go:139-180); this module gives the TPU workloads the same
discipline for backend *initialization*:

- ``init_devices()`` — call ``jax.devices()`` with bounded retries and
  exponential backoff, clearing JAX's cached backend-failure state between
  attempts so a retry is a real retry.
- ``diagnose_holders()`` — best-effort report of which processes hold the
  TPU device nodes (``/dev/accel*``, ``/dev/vfio*``) or the libtpu
  single-client lockfile, so an UNAVAILABLE failure is attributable.

No k8s dependencies: this runs inside validator pods and on bare hosts.
"""

from __future__ import annotations

import glob
import os
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

_DEVICE_GLOBS = ("/dev/accel*", "/dev/vfio/*", "/dev/tpu*")
_LOCKFILES = ("/tmp/libtpu_lockfile",)


@dataclass
class HolderInfo:
    pid: int
    cmdline: str
    paths: List[str] = field(default_factory=list)


def _read_cmdline(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            raw = f.read().replace(b"\x00", b" ").decode("utf-8", "replace")
        return raw.strip()[:200] or "?"
    except OSError:
        return "?"


def diagnose_holders() -> List[HolderInfo]:
    """Scan /proc/*/fd for open handles on TPU device nodes / lockfiles.

    Returns holders other than the current process. Needs no root when the
    scanner and the holder run as the same user (both true in the validator
    pod and on the bench host); silently skips pids it cannot inspect.
    """
    targets = set()
    for pattern in _DEVICE_GLOBS:
        targets.update(glob.glob(pattern))
    targets.update(p for p in _LOCKFILES if os.path.exists(p))
    if not targets:
        return []
    me = os.getpid()
    holders = {}
    for proc in glob.glob("/proc/[0-9]*"):
        try:
            pid = int(proc.rsplit("/", 1)[1])
        except ValueError:
            continue
        if pid == me:
            continue
        hits = []
        try:
            for fd in os.listdir(f"{proc}/fd"):
                try:
                    dest = os.readlink(f"{proc}/fd/{fd}")
                except OSError:
                    continue
                if dest in targets:
                    hits.append(dest)
        except OSError:
            continue
        if hits:
            holders[pid] = HolderInfo(pid, _read_cmdline(pid), sorted(set(hits)))
    return [holders[p] for p in sorted(holders)]


def describe_environment() -> str:
    """One-line summary of the TPU-relevant environment for diagnostics."""
    bits = []
    for var in ("JAX_PLATFORMS", "TPU_SKIP_MDS_QUERY", "TPU_PROCESS_BOUNDS",
                "TPU_CHIPS_PER_PROCESS_BOUNDS", "TPU_VISIBLE_DEVICES"):
        if os.environ.get(var):
            bits.append(f"{var}={os.environ[var]}")
    devs = [d for pat in _DEVICE_GLOBS for d in glob.glob(pat)]
    bits.append(f"device_nodes={devs or 'none'}")
    return " ".join(bits)


def log_holders(log, holders: Optional[list] = None) -> None:
    """Report chip holders (or the absence of any) through ``log``.
    Pass ``holders`` to reuse an existing ``diagnose_holders()`` scan."""
    if holders is None:
        holders = diagnose_holders()
    for h in holders:
        log(f"#   chip held by pid={h.pid} ({h.cmdline}) via {h.paths}")
    if not holders:
        log(f"#   no local holder found; env: {describe_environment()}")


def _clear_backend_cache() -> None:
    """Drop JAX's cached backend state so the next jax.devices() retries
    initialization instead of replaying a cached failure."""
    try:
        import jax.extend  # not pulled in by bare `import jax`

        jax.extend.backend.clear_backends()
    except Exception:
        try:
            from jax._src import xla_bridge

            xla_bridge._clear_backends()
        except Exception:
            pass


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS authoritative even under out-of-tree PJRT
    plugins that override it at import time: jax.config wins over a
    plugin, so a caller exporting JAX_PLATFORMS=cpu (tests, the shell
    e2e, fake clusters) must never end up blocked on an unreachable
    remote backend. No-op when the env var is unset."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax

        jax.config.update("jax_platforms", plat)
    except Exception:
        pass  # no jax / unknown platform: the caller will surface it


def pins_platform(fn):
    """Decorator for workload ``run()`` entry points that touch
    ``jax.devices()`` directly (no multihost.initialize in their path):
    applies honor_jax_platforms_env before the body runs, so every
    current and future entry point gets the pin from one place."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        honor_jax_platforms_env()
        return fn(*args, **kwargs)

    return wrapper


def init_devices(attempts: int = 3, backoff_s: float = 5.0,
                 platform: Optional[str] = None, log=None) -> "list":
    """jax.devices() with retry/backoff on backend-init failure.

    ``platform`` pins the backend via ``jax.config`` — required rather than
    the JAX_PLATFORMS env var because out-of-tree PJRT plugins (e.g. the
    tunneled remote-TPU plugin in this image) can override the env var at
    import time; only jax.config wins over a plugin.

    Raises the final exception (annotated with holder diagnostics) if every
    attempt fails. ``log`` is a callable for diagnostic lines (defaults to
    stderr).
    """
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if log is None:
        def log(msg):
            print(msg, file=sys.stderr)

    delay = backoff_s
    last_exc: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        try:
            return jax.devices()
        except Exception as exc:  # RuntimeError / JaxRuntimeError
            last_exc = exc
            log(f"# backend init attempt {attempt}/{attempts} failed: "
                f"{type(exc).__name__}: {str(exc)[:200]}")
            log_holders(log)
            if attempt < attempts:
                time.sleep(delay)
                delay = min(delay * 2, 60.0)
                _clear_backend_cache()
    assert last_exc is not None
    raise last_exc
