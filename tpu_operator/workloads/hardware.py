"""TPU hardware identification + public peak numbers.

Peaks are the published per-chip figures (cloud.google.com/tpu/docs system
architecture pages); they anchor the validator's utilization fractions and
the ICI-bandwidth threshold from BASELINE.md (>=80% of link bandwidth).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ChipSpec:
    generation: str
    peak_bf16_tflops: float    # per chip
    hbm_gb: float
    hbm_bw_gbps: float         # GB/s per chip
    ici_bw_gbps: float         # GB/s per chip, aggregate across links


# public per-chip numbers (TFLOP/s bf16, HBM GB, HBM GB/s, ICI GB/s)
CHIPS = {
    "v2": ChipSpec("v2", 45.0, 16, 600, 62.5),
    "v3": ChipSpec("v3", 123.0, 32, 900, 87.5),
    "v4": ChipSpec("v4", 275.0, 32, 1228, 300.0),
    "v5e": ChipSpec("v5e", 197.0, 16, 819, 200.0),
    "v5p": ChipSpec("v5p", 459.0, 95, 2765, 600.0),
    "v6e": ChipSpec("v6e", 918.0, 32, 1640, 448.0),
}

_KIND_HINTS = (
    ("v6e", "v6e"), ("v6 lite", "v6e"),
    ("v5p", "v5p"),
    ("v5 lite", "v5e"), ("v5litepod", "v5e"), ("v5e", "v5e"),
    ("v5", "v5p"),
    ("v4", "v4"),
    ("v3", "v3"),
    ("v2", "v2"),
)


def chip_spec_for(device_kind: str) -> Optional[ChipSpec]:
    """Map jax.Device.device_kind (e.g. 'TPU v5p chip') to a ChipSpec."""
    kind = (device_kind or "").lower()
    for hint, gen in _KIND_HINTS:
        if hint in kind:
            return CHIPS[gen]
    return None


def detect() -> tuple:
    """(platform, device_count, device_kind, ChipSpec|None) for the default
    JAX backend."""
    import jax

    devices = jax.devices()
    platform = devices[0].platform
    kind = getattr(devices[0], "device_kind", "")
    return platform, len(devices), kind, chip_spec_for(kind)
