"""Pallas HBM-bandwidth probe: STREAM-triad as a hand-written TPU kernel.

Complements the matmul (MXU) and psum (ICI) proofs with the third leg of
the roofline: sustained HBM bandwidth. A grid of Pallas programs streams
row-blocks HBM -> VMEM, computes ``out = a + alpha * b`` on the VPU, and
streams back — the classic STREAM triad, whose byte traffic (3 arrays per
element) divided by wall time is the achieved HBM bandwidth, compared to
the chip's published figure.

Runs in interpret mode on CPU (tests) and compiled on TPU. Tile shapes
respect the TPU constraints: last dim 128, float32 sublane multiple of 8.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .backend import pins_platform
from .hardware import chip_spec_for


def _triad_kernel(a_ref, b_ref, out_ref, *, alpha: float):
    out_ref[:] = a_ref[:] + alpha * b_ref[:]


def triad(a: jnp.ndarray, b: jnp.ndarray, alpha: float = 2.0,
          block_rows: int = 128, interpret: Optional[bool] = None) -> jnp.ndarray:
    """out = a + alpha*b, writing in place over ``a``'s buffer.

    Two tuning decisions measured on v5e (each worth knowing):
    - block budget: 3 buffers x double-buffering x block bytes must fit
      the ~16MB scoped VMEM; 128x4096xf32 = 2MB/block -> 12MB total.
    - ``input_output_aliases={0: 0}``: without it, chaining triads in a
      fori_loop carries a hidden full-array copy per iteration and
      sustained bandwidth drops from ~673 GB/s (82% of v5e peak, parity
      with XLA's fused loop) to ~400 GB/s.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    rows, cols = a.shape
    assert cols % 128 == 0, "last dim must be a multiple of 128 (lane width)"
    assert rows % block_rows == 0 and block_rows % 8 == 0
    grid = (rows // block_rows,)
    spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    return pl.pallas_call(
        partial(_triad_kernel, alpha=alpha),
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        input_output_aliases={0: 0},
        interpret=interpret,
    )(a, b)


@dataclass
class TriadResult:
    bytes_moved: int
    seconds: float
    bandwidth_gbps: float
    peak_hbm_gbps: Optional[float]
    fraction_of_peak: Optional[float]
    device_kind: str
    correct: bool


@pins_platform
def run(size_mb: float = 512.0, iters: int = 24, repeats: int = 3,
        interpret: Optional[bool] = None) -> TriadResult:
    """Two-point measurement: time ``lo`` and ``lo+iters`` triad loops and
    take the marginal rate, cancelling fixed dispatch/transfer latency
    (essential through tunneled PJRT runtimes, where a host round-trip
    costs tens of ms)."""
    device = jax.devices()[0]
    cols = 4096
    rows_total = max(128, int(size_mb * 1e6 / 4 / cols) // 128 * 128)
    a = jnp.ones((rows_total, cols), jnp.float32)
    b = jnp.full((rows_total, cols), 2.0, jnp.float32)

    @jax.jit
    def chain(a, b, n):
        # alpha=0.5 with b=2 keeps values stable: +1 per iteration
        return jax.lax.fori_loop(
            0, n, lambda i, acc: triad(acc, b, alpha=0.5,
                                       interpret=interpret), a)

    lo = 2
    np.asarray(chain(a, b, lo)[:1, :1])  # compile + sync

    def timed(n):
        best = float("inf")
        probe = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = chain(a, b, n)
            probe = np.asarray(out[:1, :1])
            best = min(best, time.perf_counter() - t0)
        return best, probe

    t_lo, _ = timed(lo)
    t_hi, probe = timed(lo + iters)
    bytes_per_iter = a.size * 4 * 3  # read a, read b, write out
    seconds = max(t_hi - t_lo, 1e-9)
    bw = bytes_per_iter * iters / seconds / 1e9
    spec = chip_spec_for(getattr(device, "device_kind", ""))
    correct = bool(np.isclose(probe[0, 0], 1.0 + lo + iters, rtol=1e-5))
    return TriadResult(
        bytes_moved=bytes_per_iter * iters, seconds=seconds,
        bandwidth_gbps=bw,
        peak_hbm_gbps=spec.hbm_bw_gbps if spec else None,
        fraction_of_peak=(bw / spec.hbm_bw_gbps) if spec else None,
        device_kind=getattr(device, "device_kind", "cpu"),
        correct=correct)


def main() -> int:
    import json

    res = run()
    print(json.dumps(res.__dict__))
    return 0 if res.correct else 1


if __name__ == "__main__":
    raise SystemExit(main())
