"""Sharded burn-in/diagnostic training step — the fleet-exercise workload.

The GPU world burns in nodes with dcgmproftester; this framework's
equivalent is a small transformer LM training step that exercises every
subsystem the operator certifies at once: MXU (matmuls), HBM (activations
+ optimizer state), and ICI (data-parallel gradient psums + tensor-parallel
activation collectives). The topology manager and validator can run it as
a scheduled diagnostic; it is also the flagship entry for __graft_entry__.

Sharding is GSPMD-style: parameters carry NamedShardings over a
(data, model) mesh, sequence-parallel constraints are placed on the
norm/residual sections, and XLA inserts the collectives (scaling-book
recipe; no hand-written all-reduces).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class BurninConfig:
    vocab: int = 512
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    seq_len: int = 64
    batch: int = 8
    learning_rate: float = 1e-3
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# --- parameter construction + shardings -----------------------------------


def init_params(cfg: BurninConfig, key) -> Dict:
    k = iter(jax.random.split(key, 4 + 6 * cfg.n_layers))
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    p: Dict[str, Any] = {
        "embed": jax.random.normal(next(k), (cfg.vocab, cfg.d_model)) * 0.02,
        "unembed": jax.random.normal(next(k), (cfg.d_model, cfg.vocab))
        * scale(cfg.d_model),
        "final_norm": jnp.ones((cfg.d_model,)),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        p["layers"].append({
            "norm1": jnp.ones((cfg.d_model,)),
            "qkv": jax.random.normal(next(k), (cfg.d_model, 3 * cfg.d_model))
            * scale(cfg.d_model),
            "attn_out": jax.random.normal(next(k), (cfg.d_model, cfg.d_model))
            * scale(cfg.d_model),
            "norm2": jnp.ones((cfg.d_model,)),
            "ff_in": jax.random.normal(next(k), (cfg.d_model, cfg.d_ff))
            * scale(cfg.d_model),
            "ff_out": jax.random.normal(next(k), (cfg.d_ff, cfg.d_model))
            * scale(cfg.d_ff),
        })
    return p


def param_specs(cfg: BurninConfig, fsdp: bool = False) -> Dict:
    """Megatron-style tensor-parallel layout: column-parallel first matmul,
    row-parallel second, so each block needs one psum on its output.

    ``fsdp=True`` additionally shards every parameter's non-tensor-
    parallel dimension across the ``data`` axis — the ZeRO-3/FSDP
    layout: parameters (and, through optax's tree mapping, the optimizer
    moments) live fully sharded, XLA's SPMD partitioner inserts the
    all-gather before each use and the reduce-scatter on the gradients.
    Composes with tp: weights end up 2D-sharded (data x model)."""
    d = "data" if fsdp else None
    layer = {
        "norm1": P(d),
        "qkv": P(d, "model"),
        "attn_out": P("model", d),
        "norm2": P(d),
        "ff_in": P(d, "model"),
        "ff_out": P("model", d),
    }
    return {
        "embed": P(d, "model"),
        "unembed": P("model", d),
        "final_norm": P(d),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def shard_params(params: Dict, mesh: Mesh, cfg: BurninConfig,
                 fsdp: bool = False) -> Dict:
    specs = param_specs(cfg, fsdp=fsdp)
    # tree.map flattens by the FIRST tree (params); each PartitionSpec in
    # the specs tree is taken whole at the matching leaf position
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


# --- model -----------------------------------------------------------------


def _rmsnorm(x, w):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * w


def forward(params: Dict, tokens: jnp.ndarray, cfg: BurninConfig,
            mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """tokens [B, S] int32 -> logits [B, S, vocab]. With a mesh, activation
    sharding constraints are applied (dp/tp/sp); without one the same code
    runs single-device (the validator's single-chip proof path)."""
    if mesh is not None:
        csc = lambda x, spec: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))
    else:
        csc = lambda x, spec: x
    x = params["embed"].astype(cfg.dtype)[tokens]
    B, S, D = x.shape
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))
    for lp in params["layers"]:
        # sequence-parallel section: norm runs with sequence sharded over
        # the model axis (no tensor dim is sharded here)
        h = csc(x, P("data", "model"))
        h = _rmsnorm(h, lp["norm1"].astype(cfg.dtype))
        h = csc(h, P("data"))
        qkv = h @ lp["qkv"].astype(cfg.dtype)
        qkv = csc(qkv, P("data", None, "model"))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(B, S, cfg.n_heads, cfg.head_dim)
        q, k, v = split(q), split(k), split(v)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.array(cfg.head_dim, dtype=cfg.dtype))
        scores = jnp.where(causal[None, None], scores, -1e9)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, D)
        x = x + attn @ lp["attn_out"].astype(cfg.dtype)
        h = csc(x, P("data", "model"))
        h = _rmsnorm(h, lp["norm2"].astype(cfg.dtype))
        h = csc(h, P("data"))
        ff = jax.nn.gelu(h @ lp["ff_in"].astype(cfg.dtype))
        x = x + ff @ lp["ff_out"].astype(cfg.dtype)
    x = _rmsnorm(x, params["final_norm"].astype(cfg.dtype))
    return (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)


def loss_fn(params: Dict, batch: Dict, cfg: BurninConfig,
            mesh: Optional[Mesh] = None) -> jnp.ndarray:
    logits = forward(params, batch["tokens"], cfg, mesh)
    targets = batch["targets"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# --- training step ---------------------------------------------------------


def make_train_step(mesh: Mesh, cfg: BurninConfig, optimizer=None,
                    fsdp: bool = False):
    """Returns (step_fn, init_state): jitted full training step with dp
    gradient reduction + tp/sp sharding, all via GSPMD. ``fsdp=True``
    fully shards parameters and optimizer state across the data axis
    (ZeRO-3 layout; see param_specs)."""
    optimizer = optimizer or optax.adamw(cfg.learning_rate)

    def init_state(key):
        params = shard_params(init_params(cfg, key), mesh, cfg, fsdp=fsdp)
        opt_state = optimizer.init(params)
        state = {"params": params, "opt": opt_state,
                 "step": jnp.zeros((), jnp.int32)}
        # commit EVERY leaf to the mesh (scalars/counters replicated):
        # uncommitted leaves would conflict with mesh-committed restores
        # when a checkpointed state re-enters the jitted step
        replicated = NamedSharding(mesh, P())

        def commit(x):
            if isinstance(x, jax.Array) and \
                    not isinstance(x.sharding, NamedSharding):
                return jax.device_put(x, replicated)
            return x

        return jax.tree_util.tree_map(commit, state)

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch, cfg,
                                                  mesh)
        updates, new_opt = optimizer.update(grads, state["opt"],
                                            state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt,
                "step": state["step"] + 1}, loss

    batch_sharding = {
        "tokens": NamedSharding(mesh, P("data", None)),
        "targets": NamedSharding(mesh, P("data", None)),
    }
    step = jax.jit(train_step, donate_argnums=0)
    return step, init_state, batch_sharding


def make_batch(cfg: BurninConfig, mesh: Mesh, key) -> Dict:
    tokens = jax.random.randint(key, (cfg.batch, cfg.seq_len), 0, cfg.vocab)
    batch = {"tokens": tokens,
             "targets": jnp.roll(tokens, -1, axis=1)}
    return {
        k: jax.device_put(v, NamedSharding(mesh, P("data", None)))
        for k, v in batch.items()
    }


def run(cfg: Optional[BurninConfig] = None, steps: int = 5,
        model_parallel: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0) -> Tuple[float, float]:
    """Run the burn-in; returns (first_loss, last_loss). Loss must fall —
    that is the correctness proof that grads flowed through every shard.

    With ``checkpoint_dir`` the run is preemption-safe: it resumes from
    the latest checkpoint found there and (with ``checkpoint_every`` > 0)
    saves the sharded train state on that cadence."""
    cfg = cfg or BurninConfig()
    # joins the multi-host runtime when the env contract says so (no-op
    # single-process) and keeps the model axis inside one slice
    from ..parallel.multihost import initialize, training_mesh

    initialize()
    mesh = training_mesh(model_parallel=model_parallel)
    step, init_state, _ = make_train_step(mesh, cfg)
    key = jax.random.PRNGKey(0)
    state = init_state(key)
    ckpt = None
    start = 0
    first = last = None
    meta_path = None
    if checkpoint_dir:
        import json
        import pathlib

        from .checkpoint import TrainCheckpointer

        ckpt = TrainCheckpointer(checkpoint_dir)
        # the run's FIRST loss lives in a sidecar, so the loss-must-fall
        # proof spans the whole run across preemptions, not just the tail
        meta_path = pathlib.Path(checkpoint_dir) / "run-meta.json"
        if ckpt.latest_step() is not None:
            state = ckpt.restore(state)
            start = int(state["step"])
            if meta_path.exists():
                first = json.loads(meta_path.read_text()).get("first_loss")
    try:
        if start >= steps:
            # checkpoint already at/past the target: nothing to train,
            # report the current loss so the (first, last) contract holds
            batch = make_batch(cfg, mesh, jax.random.fold_in(key, steps - 1))
            last = float(jax.jit(
                lambda p, b: loss_fn(p, b, cfg, mesh))(state["params"],
                                                       batch))
            first = last if first is None else first
            return first, last
        for i in range(start, steps):
            batch = make_batch(cfg, mesh, jax.random.fold_in(key, i))
            state, loss = step(state, batch)
            loss = float(loss)
            if first is None:
                first = loss
                if meta_path is not None and start == 0:
                    meta_path.parent.mkdir(parents=True, exist_ok=True)
                    meta_path.write_text(json.dumps({"first_loss": first}))
            last = loss
            if ckpt and checkpoint_every and (i + 1) % checkpoint_every == 0:
                ckpt.save(state, i + 1)
    finally:
        if ckpt:
            ckpt.close()
    return first, last


def main() -> int:
    import json

    first, last = run()
    ok = last < first
    print(json.dumps({"first_loss": first, "last_loss": last,
                      "improved": ok,
                      "devices": jax.device_count()}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
