"""Workload half of the elastic-slice protocol (Tenplex-style reshard).

The operator (upgrade FSM migrate stage, placement resize path) posts a
``tpu.graft.dev/slice-intent`` annotation on the SliceRequest; this
module is the training job's side of the handshake:

    intent seen -> checkpoint at the next step boundary -> ack the
    durable step (annotation + ``status.migration`` Checkpointed) ->
    ... operator rebinds (Rebound) ... -> restore the acked step on the
    new topology and report Resumed + ``restoredStep``.

The ONLY thing a step may be acked on is a *finalized* checkpoint —
orbax's finalize-rename atomicity means a crash mid-save leaves a
partial step that was never acked, so restoring an older retained step
(TrainCheckpointer's corrupt-latest fallback) can never violate the
no-acked-work-lost invariant.

Two bindings of the same state machine live here:

- ``MemoryCheckpointStore`` + ``ElasticWorkload``: deterministic
  in-process store + shim used by the chaos runner and the migration
  bench — no jax, all time through an injectable clock, so seeded runs
  produce byte-identical verdicts.
- ``OrbaxCheckpointStore``: the same store interface over
  ``TrainCheckpointer`` for real multi-host jobs (jax imported lazily).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import labels as L
from ..api.conditions import update_status_with_retry
from ..api.slicerequest import (
    KIND_SLICE_REQUEST,
    MIG_CHECKPOINTED,
    MIG_MIGRATING,
    MIG_REBOUND,
    MIG_RESHARDING,
    MIG_RESUMED,
    V1ALPHA1,
)
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime.objects import (
    annotations_of,
    get_nested,
    name_of,
    namespace_of,
    set_nested,
    thaw_obj,
)
from ..runtime.timeline import TIMELINE

log = logging.getLogger("tpu_operator.elastic")


def env_sharded_ckpt_enabled(env=None) -> bool:
    """Sharded checkpoints default ON; OPERATOR_SHARDED_CKPT=0 (or
    false/no/off) disables them — same spelling as the other kill
    switches."""
    import os

    val = (env or os.environ).get("OPERATOR_SHARDED_CKPT", "1")
    return str(val).strip().lower() not in ("0", "false", "no", "off")


class ShardedCkptGate:
    """Process-wide switch for the sharded checkpoint layout and the
    same-domain shard handoff built on it. Disabled, every save is the
    legacy single blob and every resize rides the full
    checkpoint->rebind->restore path — the debugging escape hatch when a
    suspected partial handoff masks lost state."""

    def __init__(self):
        self.enabled = env_sharded_ckpt_enabled()


SHARDED_CKPT_GATE = ShardedCkptGate()


# --- sharded checkpoint layout + shard-movement planner --------------------
#
# Tenplex's observation, applied to the handshake: the train state is a
# parallelizable collection that can be re-split onto a new host set by
# moving ONLY the sub-tensors that change owner. The layout below is the
# manifest's map of that collection: a fixed set of logical shards, each
# owned by one host. A resize re-partitions ownership; plan_reshard()
# diffs two layouts into the minimal move set (and its byte bill), and
# the controllers fall back to the full-checkpoint path whenever the
# layouts cannot be diffed (version skew, different shard sets).

LAYOUT_VERSION = 1
DEFAULT_SHARD_COUNT = 16


def build_layout(hosts: List[str], total_bytes: int,
                 n_shards: int = DEFAULT_SHARD_COUNT,
                 version: int = LAYOUT_VERSION) -> dict:
    """Fresh layout: ``total_bytes`` of state split into ``n_shards``
    near-equal logical shards, owners assigned round-robin over the
    sorted host list. Deterministic — the workload and the controller
    compute identical layouts from the same inputs."""
    hosts = sorted(hosts)
    if not hosts:
        raise ValueError("a layout needs at least one host")
    n = max(1, int(n_shards))
    base, extra = divmod(max(0, int(total_bytes)), n)
    shards = {}
    for sid in range(n):
        shards[str(sid)] = {"owner": hosts[sid % len(hosts)],
                            "bytes": base + (1 if sid < extra else 0)}
    return {"version": int(version), "shards": shards}


def rebalance_layout(layout: dict, new_hosts: List[str]) -> dict:
    """Minimal-movement re-split of ``layout`` onto ``new_hosts``: every
    shard whose owner survives stays put (up to the balanced per-host
    ceiling); only orphaned shards and overflow move, each to the
    least-loaded new host. Deterministic: shards walk in numeric order,
    ties break on host name."""
    new_hosts = sorted(set(new_hosts))
    if not new_hosts:
        raise ValueError("a layout needs at least one host")
    shards = layout["shards"]
    cap = -(-len(shards) // len(new_hosts))  # ceil
    load = {h: 0 for h in new_hosts}
    out: Dict[str, dict] = {}
    homeless = []
    for sid in sorted(shards, key=int):
        owner = shards[sid]["owner"]
        if owner in load and load[owner] < cap:
            out[sid] = {"owner": owner,
                        "bytes": int(shards[sid]["bytes"])}
            load[owner] += 1
        else:
            homeless.append(sid)
    for sid in homeless:
        target = min(new_hosts, key=lambda h: (load[h], h))
        out[sid] = {"owner": target, "bytes": int(shards[sid]["bytes"])}
        load[target] += 1
    return {"version": int(layout.get("version", LAYOUT_VERSION)),
            "shards": out}


def plan_reshard(old_layout: Optional[dict],
                 new_layout: Optional[dict]) -> dict:
    """Pure shard-movement planner: the minimal set of shards changing
    owner between two layouts, bytes accounted. ``compatible`` is False
    (with the reason) whenever the layouts cannot be diffed — version
    skew or differing shard sets — which is the controllers' signal to
    fall back to the full-checkpoint path."""
    plan = {"moves": [], "shardsMoved": 0, "bytesMoved": 0,
            "shardsTotal": 0, "bytesTotal": 0,
            "compatible": True, "reason": ""}
    olds = (old_layout or {}).get("shards") or {}
    news = (new_layout or {}).get("shards") or {}
    if not olds or not news:
        plan.update(compatible=False, reason="missing layout")
        return plan
    old_v = int((old_layout or {}).get("version", -1))
    new_v = int((new_layout or {}).get("version", -1))
    if old_v != new_v:
        plan.update(compatible=False,
                    reason=f"layout version {old_v} != {new_v}")
        return plan
    if set(olds) != set(news):
        plan.update(compatible=False, reason="shard sets differ")
        return plan
    plan["shardsTotal"] = len(news)
    for sid in sorted(news, key=int):
        b = int(news[sid]["bytes"])
        plan["bytesTotal"] += b
        src, dst = olds[sid]["owner"], news[sid]["owner"]
        if src != dst:
            plan["moves"].append(
                {"shard": sid, "from": src, "to": dst, "bytes": b})
            plan["shardsMoved"] += 1
            plan["bytesMoved"] += b
    return plan


class MemoryCheckpointStore:
    """Deterministic stand-in for the orbax CheckpointManager: finalized
    saves are durable, a ``partial=True`` save models a crash mid-write
    (enumerates like a real torn step directory, fails restore), and
    restore falls back past partial steps exactly like
    ``TrainCheckpointer.restore`` does.

    A save may carry a sharded ``layout`` (build_layout): the step then
    holds per-host shards plus a manifest, and the manifest IS the
    finalize-rename commit point — a ``partial`` sharded save models a
    crash mid-shard-handoff (shards written, manifest never renamed in),
    so ``manifest()`` returns None for it and restore falls back exactly
    like the blob path."""

    def __init__(self, max_to_keep: int = 3):
        self.max_to_keep = max_to_keep
        self._steps: Dict[int, dict] = {}

    def save(self, step: int, payload: Any = None,
             partial: bool = False, layout: Optional[dict] = None) -> None:
        step = int(step)
        if partial and step in self._steps \
                and not self._steps[step]["partial"]:
            # finalize-rename atomicity: a torn write can never replace
            # an already-finalized step directory (blob or manifest)
            return
        rec = {"partial": bool(partial), "payload": payload,
               "layout": None, "shards": None}
        if layout is not None:
            rec["layout"] = layout
            rec["shards"] = {
                sid: {"owner": meta["owner"],
                      "bytes": int(meta["bytes"]),
                      "payload": payload}
                for sid, meta in layout["shards"].items()}
        self._steps[step] = rec
        finalized = sorted(s for s, rec in self._steps.items()
                           if not rec["partial"])
        for stale in finalized[:-self.max_to_keep]:
            del self._steps[stale]

    def manifest(self, step: int) -> Optional[dict]:
        """The finalized layout manifest of ``step``, or None — for a
        blob step, a torn sharded step (manifest never renamed in), or
        an unknown step."""
        rec = self._steps.get(int(step))
        if rec is None or rec["partial"]:
            return None
        return rec["layout"]

    def restore_shards(self, step: int,
                       shard_ids: List[str]) -> Tuple[Any, int]:
        """Fetch ONLY the named shards of a finalized sharded step —
        the direct-handoff read path. Returns (payload, bytes_fetched);
        raises FileNotFoundError when the step has no finalized
        manifest (torn or blob-only), the full-restore fallback."""
        rec = self._steps.get(int(step))
        if rec is None or rec["partial"] or not rec["shards"]:
            raise FileNotFoundError(
                f"step {step} has no finalized sharded manifest")
        fetched = 0
        for sid in shard_ids:
            if sid not in rec["shards"]:
                raise FileNotFoundError(
                    f"step {step} has no shard {sid!r}")
            fetched += rec["shards"][sid]["bytes"]
        return rec["payload"], fetched

    def all_steps(self) -> list:
        return sorted(self._steps)

    def latest_step(self) -> Optional[int]:
        finalized = [s for s, rec in self._steps.items()
                     if not rec["partial"]]
        return max(finalized) if finalized else None

    def restore(self) -> Tuple[int, Any]:
        """(step, payload) of the newest restorable checkpoint, skipping
        partial steps with the same fallback accounting as the orbax
        path. Raises FileNotFoundError when nothing restorable exists."""
        for step in sorted(self._steps, reverse=True):
            rec = self._steps[step]
            if rec["partial"]:
                OPERATOR_METRICS.checkpoint_restore_fallbacks.inc()
                log.warning("skipping partial checkpoint step %s", step)
                continue
            return step, rec["payload"]
        raise FileNotFoundError("no restorable checkpoint")


class OrbaxCheckpointStore:
    """The same store interface over a real ``TrainCheckpointer``:
    ``state_fn`` yields the live train state to persist, ``state_like_fn``
    the freshly-initialized template restore reshards into (which is what
    makes resume-on-a-new-topology work).

    With the sharded gate on, a save that carries a ``layout`` also
    persists the layout manifest next to the step via the
    checkpointer's atomic tmp+rename write — orbax already stores
    per-shard files, so the manifest is the only artifact this layer
    adds, and its rename stays the commit point for the handoff
    planner."""

    def __init__(self, checkpointer, state_fn: Callable[[], Any],
                 state_like_fn: Callable[[], Any]):
        self._ckpt = checkpointer
        self._state_fn = state_fn
        self._state_like_fn = state_like_fn

    def save(self, step: int, payload: Any = None,
             partial: bool = False, layout: Optional[dict] = None) -> None:
        self._ckpt.save(self._state_fn(), int(step), wait=not partial)
        if layout is not None and not partial \
                and hasattr(self._ckpt, "save_manifest"):
            # manifest AFTER the finalized save: a crash in between
            # leaves a restorable step that simply planless-falls-back
            self._ckpt.save_manifest(int(step), layout)

    def manifest(self, step: int) -> Optional[dict]:
        if hasattr(self._ckpt, "read_manifest"):
            return self._ckpt.read_manifest(int(step))
        return None

    def latest_step(self) -> Optional[int]:
        return self._ckpt.latest_step()

    def restore(self) -> Tuple[int, Any]:
        state = self._ckpt.restore(self._state_like_fn())
        step = None
        if isinstance(state, dict):
            step = state.get("step")
        step = int(step) if step is not None else int(
            self._ckpt.latest_step() or 0)
        return step, state


class ElasticWorkload:
    """One training job speaking the slice-intent protocol for one
    SliceRequest. ``tick()`` is one scheduling quantum: the chaos runner
    (and the migration bench) call it once per virtual step, a real
    deployment would call it from the training loop's step callback.

    All cluster interaction goes through the request's status/annotations
    — the shim holds no protocol state a restart could lose; its only
    private state (the in-memory step counter) is exactly the work a
    crash is ALLOWED to lose, back to the last durable checkpoint.
    """

    def __init__(self, client, name: str, namespace: str = "default",
                 clock: Callable[[], float] = None,
                 store: Optional[MemoryCheckpointStore] = None,
                 checkpoint_every: int = 6, steps_per_tick: int = 3,
                 state_bytes: int = 1 << 20,
                 restore_bandwidth: int = 0,
                 sharded: Optional[bool] = None):
        import time

        self.client = client
        self.name = name
        self.namespace = namespace
        self.clock = clock or time.time
        self.store = store if store is not None else MemoryCheckpointStore()
        self.checkpoint_every = checkpoint_every
        self.steps_per_tick = steps_per_tick
        # synthetic state size for the shard layout's byte accounting,
        # and the restore-cost model: with restore_bandwidth > 0
        # (bytes per quantum), a restore stalls extra quanta
        # proportional to the bytes it fetched — which is what makes
        # the direct handoff's smaller byte bill measurable on the
        # virtual clock. 0 bandwidth = instant restores (legacy).
        self.state_bytes = int(state_bytes)
        self.restore_bandwidth = int(restore_bandwidth)
        self._sharded_override = sharded
        self.step = 0
        self.max_acked = -1
        self.last_reshard: Optional[dict] = None
        self._last_saved: Optional[int] = None
        self._last_save_at: Optional[float] = None
        self._nodes_seen: Optional[tuple] = None
        self._crashed = False
        self._layout: Optional[dict] = None
        self._layout_version = LAYOUT_VERSION
        self._reshard_crash_armed = False
        self._pause_ticks = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def sharded(self) -> bool:
        if self._sharded_override is not None:
            return bool(self._sharded_override)
        return SHARDED_CKPT_GATE.enabled

    def crash(self, partial: bool = True) -> None:
        """Chaos hook: the job dies mid-step. ``partial`` leaves a torn
        checkpoint at the current (never-acked) step, the artifact a
        crash during an async save produces."""
        if partial:
            self.store.save(self.step, payload={"step": self.step},
                            partial=True)
        self._crashed = True

    def arm_reshard_crash(self) -> None:
        """Chaos hook: die mid-shard-handoff. The next direct-handoff
        restore writes part of the re-shard (an unfinalized manifest —
        it can never shadow the finalized acked step) and crashes."""
        self._reshard_crash_armed = True

    def force_layout_mismatch(self) -> None:
        """Chaos hook: the job's next checkpoints publish an
        incompatible layout version, forcing every subsequent resize
        onto the full-checkpoint fallback path."""
        self._layout_version = LAYOUT_VERSION + 1
        self._layout = None

    def _restore(self) -> int:
        try:
            step, _ = self.store.restore()
        except FileNotFoundError:
            step = 0
        return int(step)

    def _current_layout(self, nodes) -> dict:
        """The layout for a save on ``nodes``: kept when ownership
        already matches, minimally rebalanced when the host set moved,
        built fresh otherwise — always deterministic from (previous
        layout, sorted hosts)."""
        hosts = sorted(nodes)
        if self._layout is not None \
                and int(self._layout.get("version", -1)) \
                == self._layout_version:
            owners = sorted({s["owner"]
                             for s in self._layout["shards"].values()})
            if owners == hosts:
                return self._layout
            return rebalance_layout(self._layout, hosts)
        return build_layout(hosts, self.state_bytes,
                            version=self._layout_version)

    def _save(self, step: int) -> None:
        layout = None
        if self.sharded and self._nodes_seen:
            layout = self._current_layout(self._nodes_seen)
        self.store.save(step, payload={"step": step}, layout=layout)
        self._layout = layout
        self._last_saved = step
        self._last_save_at = self.clock()

    def _pause_for(self, fetched_bytes: int) -> None:
        if self.restore_bandwidth > 0 and fetched_bytes > 0:
            # the restore's own quantum covers the first bandwidth unit
            self._pause_ticks = max(
                0, -(-int(fetched_bytes) // self.restore_bandwidth) - 1)

    def _reshard_restore(self, nodes) -> Optional[Tuple[int, int]]:
        """Direct shard handoff: restore the acked step by fetching ONLY
        the shards whose owner changed onto this binding, then commit
        the re-shard under a fresh finalized manifest. Returns
        (step, bytes_fetched); None on ANY mismatch — the caller falls
        back to the full restore. Raises nothing: a mid-handoff crash
        (armed by chaos) leaves a torn manifest and sets the crashed
        flag instead."""
        step = self.store.latest_step()
        if step is None or not hasattr(self.store, "manifest"):
            return None
        manifest = self.store.manifest(step)
        if manifest is None \
                or int(manifest.get("version", -1)) != self._layout_version:
            return None
        new_layout = rebalance_layout(manifest, sorted(nodes))
        plan = plan_reshard(manifest, new_layout)
        if not plan["compatible"]:
            return None
        if self._reshard_crash_armed:
            # die mid-handoff: some shards of the re-shard land, the
            # manifest rename never happens — the torn save can never
            # shadow the finalized acked step, so the restart below
            # restores it and no acked work is lost
            self.store.save(step, payload={"step": step}, partial=True,
                            layout=new_layout)
            self._reshard_crash_armed = False
            self._crashed = True
            return None
        try:
            _, fetched = self.store.restore_shards(
                step, [m["shard"] for m in plan["moves"]])
        except FileNotFoundError:
            return None
        # commit the re-shard: the new ownership map becomes the
        # finalized manifest the NEXT resize plans against
        self.store.save(step, payload={"step": step}, layout=new_layout)
        self._layout = new_layout
        self.last_reshard = {"bytesMoved": plan["bytesMoved"],
                             "shardsMoved": plan["shardsMoved"],
                             "bytesTotal": plan["bytesTotal"]}
        return int(step), int(fetched)

    def tick(self) -> None:
        live = self.client.get_or_none(
            V1ALPHA1, KIND_SLICE_REQUEST, self.name, self.namespace)
        if live is None:
            return
        cr = thaw_obj(live)
        nodes = tuple(get_nested(cr, "status", "nodes", default=[]) or [])
        mig = dict(get_nested(cr, "status", "migration",
                              default={}) or {})
        phase = mig.get("phase", "")
        if not nodes:
            return  # not placed (or mid-eviction): nothing is running
        if self._pause_ticks > 0:
            # still fetching checkpoint bytes onto the new binding: the
            # restore's re-warm stalls training for this quantum
            self._pause_ticks -= 1
            return
        if (self._crashed or phase in (MIG_REBOUND, MIG_RESHARDING)
                or (self._nodes_seen is not None
                    and nodes != self._nodes_seen)):
            # restart/reshard: restore the newest durable checkpoint on
            # the (possibly new) topology, losing only un-acked steps.
            # A Resharding rebind takes the direct handoff — surviving
            # hosts keep their shards, only reassigned shards are
            # fetched; any mismatch (torn manifest, version skew,
            # crashed peer) degrades to the full restore.
            restored = fetched = None
            if (phase == MIG_RESHARDING and not self._crashed
                    and self.sharded):
                out = self._reshard_restore(nodes)
                if self._crashed:
                    return  # the dying handoff consumed this quantum
                if out is not None:
                    restored, fetched = out
                    mig["bytesMoved"] = self.last_reshard["bytesMoved"]
                    mig["shardsMoved"] = self.last_reshard["shardsMoved"]
            if restored is None:
                restored = self._restore()
                manifest = (self.store.manifest(restored)
                            if hasattr(self.store, "manifest") else None)
                fetched = (sum(int(s["bytes"])
                               for s in manifest["shards"].values())
                           if manifest else self.state_bytes)
            self.step = restored
            mig["restoredStep"] = restored
            if phase in (MIG_REBOUND, MIG_RESHARDING):
                mig["phase"] = MIG_RESUMED
            set_nested(cr, mig, "status", "migration")
            update_status_with_retry(self.client, cr, live=live)
            if TIMELINE.enabled and phase in (MIG_REBOUND,
                                              MIG_RESHARDING):
                TIMELINE.record("SliceRequest", self.key,
                                "migration:" + MIG_RESUMED,
                                {"restoredStep": restored,
                                 "nodes": len(nodes)})
            log.info("workload %s restored step %d on %d node(s)",
                     self.key, restored, len(nodes))
            self._nodes_seen = nodes
            self._crashed = False
            self._pause_for(fetched or 0)
            return  # the restore consumed this quantum
        self._nodes_seen = nodes

        # one quantum of training, then the periodic checkpoint cadence
        self.step += self.steps_per_tick
        saved = False
        if self.step - (self._last_saved or 0) >= self.checkpoint_every:
            self._save(self.step)
            # goodput progress: the durably-checkpointed step is the
            # acked-work counter the fleet telemetry plane rates against
            # the generation-ideal step rate (metrics/fleet.py) — kept
            # outside status.migration so it advances between handshakes
            set_nested(cr, self._last_saved,
                       "status", "progress", "checkpointedStep")
            saved = True

        anns = annotations_of(cr)
        intent = anns.get(L.SLICE_INTENT)
        deadline = anns.get(L.SLICE_INTENT_DEADLINE)
        if intent and phase == MIG_MIGRATING:
            try:
                expired = (deadline is not None
                           and self.clock() > float(deadline))
            except (TypeError, ValueError):
                expired = False
            if not expired:
                # checkpoint at this step boundary and ack it durably;
                # save BEFORE ack — the ack is the operator's license to
                # tear the old binding down
                self._save(self.step)
                set_nested(cr, self._last_saved,
                           "status", "progress", "checkpointedStep")
                self.max_acked = max(self.max_acked, self.step)
                self.client.patch(
                    V1ALPHA1, KIND_SLICE_REQUEST, self.name,
                    {"metadata": {"annotations": {
                        L.SLICE_INTENT_ACK: str(self.step)}}},
                    namespace=self.namespace)
                mig["phase"] = MIG_CHECKPOINTED
                mig["ackedStep"] = max(
                    int(mig.get("ackedStep", -1) or -1), self.step)
                if self.sharded and self._layout is not None:
                    # the acked checkpoint's shard map: the operator's
                    # input to the same-domain handoff planner
                    mig["layout"] = self._layout
                set_nested(cr, mig, "status", "migration")
                update_status_with_retry(self.client, cr, live=live)
                saved = False  # the handshake write carried progress too
                if TIMELINE.enabled:
                    TIMELINE.record("SliceRequest", self.key,
                                    "migration:" + MIG_CHECKPOINTED,
                                    {"intent": intent,
                                     "ackedStep": self.step})
                log.info("workload %s acked %s at step %d",
                         self.key, intent, self.step)
        if saved:
            update_status_with_retry(self.client, cr, live=live)
        if self._last_save_at is not None:
            OPERATOR_METRICS.slice_checkpoint_age.labels(
                request=self.key).set(self.clock() - self._last_save_at)
