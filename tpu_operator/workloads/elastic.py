"""Workload half of the elastic-slice protocol (Tenplex-style reshard).

The operator (upgrade FSM migrate stage, placement resize path) posts a
``tpu.graft.dev/slice-intent`` annotation on the SliceRequest; this
module is the training job's side of the handshake:

    intent seen -> checkpoint at the next step boundary -> ack the
    durable step (annotation + ``status.migration`` Checkpointed) ->
    ... operator rebinds (Rebound) ... -> restore the acked step on the
    new topology and report Resumed + ``restoredStep``.

The ONLY thing a step may be acked on is a *finalized* checkpoint —
orbax's finalize-rename atomicity means a crash mid-save leaves a
partial step that was never acked, so restoring an older retained step
(TrainCheckpointer's corrupt-latest fallback) can never violate the
no-acked-work-lost invariant.

Two bindings of the same state machine live here:

- ``MemoryCheckpointStore`` + ``ElasticWorkload``: deterministic
  in-process store + shim used by the chaos runner and the migration
  bench — no jax, all time through an injectable clock, so seeded runs
  produce byte-identical verdicts.
- ``OrbaxCheckpointStore``: the same store interface over
  ``TrainCheckpointer`` for real multi-host jobs (jax imported lazily).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional, Tuple

from ..api import labels as L
from ..api.conditions import update_status_with_retry
from ..api.slicerequest import (
    KIND_SLICE_REQUEST,
    MIG_CHECKPOINTED,
    MIG_MIGRATING,
    MIG_REBOUND,
    MIG_RESUMED,
    V1ALPHA1,
)
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime.objects import (
    annotations_of,
    get_nested,
    name_of,
    namespace_of,
    set_nested,
    thaw_obj,
)
from ..runtime.timeline import TIMELINE

log = logging.getLogger("tpu_operator.elastic")


class MemoryCheckpointStore:
    """Deterministic stand-in for the orbax CheckpointManager: finalized
    saves are durable, a ``partial=True`` save models a crash mid-write
    (enumerates like a real torn step directory, fails restore), and
    restore falls back past partial steps exactly like
    ``TrainCheckpointer.restore`` does."""

    def __init__(self, max_to_keep: int = 3):
        self.max_to_keep = max_to_keep
        self._steps: Dict[int, dict] = {}

    def save(self, step: int, payload: Any = None,
             partial: bool = False) -> None:
        step = int(step)
        if partial and step in self._steps \
                and not self._steps[step]["partial"]:
            # finalize-rename atomicity: a torn write can never replace
            # an already-finalized step directory
            return
        self._steps[step] = {"partial": bool(partial),
                             "payload": payload}
        finalized = sorted(s for s, rec in self._steps.items()
                           if not rec["partial"])
        for stale in finalized[:-self.max_to_keep]:
            del self._steps[stale]

    def all_steps(self) -> list:
        return sorted(self._steps)

    def latest_step(self) -> Optional[int]:
        finalized = [s for s, rec in self._steps.items()
                     if not rec["partial"]]
        return max(finalized) if finalized else None

    def restore(self) -> Tuple[int, Any]:
        """(step, payload) of the newest restorable checkpoint, skipping
        partial steps with the same fallback accounting as the orbax
        path. Raises FileNotFoundError when nothing restorable exists."""
        for step in sorted(self._steps, reverse=True):
            rec = self._steps[step]
            if rec["partial"]:
                OPERATOR_METRICS.checkpoint_restore_fallbacks.inc()
                log.warning("skipping partial checkpoint step %s", step)
                continue
            return step, rec["payload"]
        raise FileNotFoundError("no restorable checkpoint")


class OrbaxCheckpointStore:
    """The same store interface over a real ``TrainCheckpointer``:
    ``state_fn`` yields the live train state to persist, ``state_like_fn``
    the freshly-initialized template restore reshards into (which is what
    makes resume-on-a-new-topology work)."""

    def __init__(self, checkpointer, state_fn: Callable[[], Any],
                 state_like_fn: Callable[[], Any]):
        self._ckpt = checkpointer
        self._state_fn = state_fn
        self._state_like_fn = state_like_fn

    def save(self, step: int, payload: Any = None,
             partial: bool = False) -> None:
        self._ckpt.save(self._state_fn(), int(step), wait=not partial)

    def latest_step(self) -> Optional[int]:
        return self._ckpt.latest_step()

    def restore(self) -> Tuple[int, Any]:
        state = self._ckpt.restore(self._state_like_fn())
        step = None
        if isinstance(state, dict):
            step = state.get("step")
        step = int(step) if step is not None else int(
            self._ckpt.latest_step() or 0)
        return step, state


class ElasticWorkload:
    """One training job speaking the slice-intent protocol for one
    SliceRequest. ``tick()`` is one scheduling quantum: the chaos runner
    (and the migration bench) call it once per virtual step, a real
    deployment would call it from the training loop's step callback.

    All cluster interaction goes through the request's status/annotations
    — the shim holds no protocol state a restart could lose; its only
    private state (the in-memory step counter) is exactly the work a
    crash is ALLOWED to lose, back to the last durable checkpoint.
    """

    def __init__(self, client, name: str, namespace: str = "default",
                 clock: Callable[[], float] = None,
                 store: Optional[MemoryCheckpointStore] = None,
                 checkpoint_every: int = 6, steps_per_tick: int = 3):
        import time

        self.client = client
        self.name = name
        self.namespace = namespace
        self.clock = clock or time.time
        self.store = store if store is not None else MemoryCheckpointStore()
        self.checkpoint_every = checkpoint_every
        self.steps_per_tick = steps_per_tick
        self.step = 0
        self.max_acked = -1
        self._last_saved: Optional[int] = None
        self._last_save_at: Optional[float] = None
        self._nodes_seen: Optional[tuple] = None
        self._crashed = False

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def crash(self, partial: bool = True) -> None:
        """Chaos hook: the job dies mid-step. ``partial`` leaves a torn
        checkpoint at the current (never-acked) step, the artifact a
        crash during an async save produces."""
        if partial:
            self.store.save(self.step, payload={"step": self.step},
                            partial=True)
        self._crashed = True

    def _restore(self) -> int:
        try:
            step, _ = self.store.restore()
        except FileNotFoundError:
            step = 0
        return int(step)

    def _save(self, step: int) -> None:
        self.store.save(step, payload={"step": step})
        self._last_saved = step
        self._last_save_at = self.clock()

    def tick(self) -> None:
        live = self.client.get_or_none(
            V1ALPHA1, KIND_SLICE_REQUEST, self.name, self.namespace)
        if live is None:
            return
        cr = thaw_obj(live)
        nodes = tuple(get_nested(cr, "status", "nodes", default=[]) or [])
        mig = dict(get_nested(cr, "status", "migration",
                              default={}) or {})
        phase = mig.get("phase", "")
        if not nodes:
            return  # not placed (or mid-eviction): nothing is running
        if (self._crashed or phase == MIG_REBOUND
                or (self._nodes_seen is not None
                    and nodes != self._nodes_seen)):
            # restart/reshard: restore the newest durable checkpoint on
            # the (possibly new) topology, losing only un-acked steps
            restored = self._restore()
            self.step = restored
            mig["restoredStep"] = restored
            if phase == MIG_REBOUND:
                mig["phase"] = MIG_RESUMED
            set_nested(cr, mig, "status", "migration")
            update_status_with_retry(self.client, cr, live=live)
            if TIMELINE.enabled and phase == MIG_REBOUND:
                TIMELINE.record("SliceRequest", self.key,
                                "migration:" + MIG_RESUMED,
                                {"restoredStep": restored,
                                 "nodes": len(nodes)})
            log.info("workload %s restored step %d on %d node(s)",
                     self.key, restored, len(nodes))
            self._nodes_seen = nodes
            self._crashed = False
            return  # the restore consumed this quantum
        self._nodes_seen = nodes

        # one quantum of training, then the periodic checkpoint cadence
        self.step += self.steps_per_tick
        saved = False
        if self.step - (self._last_saved or 0) >= self.checkpoint_every:
            self._save(self.step)
            # goodput progress: the durably-checkpointed step is the
            # acked-work counter the fleet telemetry plane rates against
            # the generation-ideal step rate (metrics/fleet.py) — kept
            # outside status.migration so it advances between handshakes
            set_nested(cr, self._last_saved,
                       "status", "progress", "checkpointedStep")
            saved = True

        anns = annotations_of(cr)
        intent = anns.get(L.SLICE_INTENT)
        deadline = anns.get(L.SLICE_INTENT_DEADLINE)
        if intent and phase == MIG_MIGRATING:
            try:
                expired = (deadline is not None
                           and self.clock() > float(deadline))
            except (TypeError, ValueError):
                expired = False
            if not expired:
                # checkpoint at this step boundary and ack it durably;
                # save BEFORE ack — the ack is the operator's license to
                # tear the old binding down
                self._save(self.step)
                set_nested(cr, self._last_saved,
                           "status", "progress", "checkpointedStep")
                self.max_acked = max(self.max_acked, self.step)
                self.client.patch(
                    V1ALPHA1, KIND_SLICE_REQUEST, self.name,
                    {"metadata": {"annotations": {
                        L.SLICE_INTENT_ACK: str(self.step)}}},
                    namespace=self.namespace)
                mig["phase"] = MIG_CHECKPOINTED
                mig["ackedStep"] = max(
                    int(mig.get("ackedStep", -1) or -1), self.step)
                set_nested(cr, mig, "status", "migration")
                update_status_with_retry(self.client, cr, live=live)
                saved = False  # the handshake write carried progress too
                if TIMELINE.enabled:
                    TIMELINE.record("SliceRequest", self.key,
                                    "migration:" + MIG_CHECKPOINTED,
                                    {"intent": intent,
                                     "ackedStep": self.step})
                log.info("workload %s acked %s at step %d",
                         self.key, intent, self.step)
        if saved:
            update_status_with_retry(self.client, cr, live=live)
        if self._last_save_at is not None:
            OPERATOR_METRICS.slice_checkpoint_age.labels(
                request=self.key).set(self.clock() - self._last_save_at)
