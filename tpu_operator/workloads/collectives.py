"""ICI collective-bandwidth proof: psum ring allreduce over the mesh.

The BASELINE.md north star: the validator's allreduce must achieve >=80%
of ICI link bandwidth. The measurement follows the standard ring-allreduce
accounting: for N chips each reducing S bytes, every chip moves
2*(N-1)/N * S bytes over its ICI links, so

    algo_bw  = S / t                      (allreduce "algorithmic" GB/s)
    bus_bw   = 2*(N-1)/N * S / t          (per-chip ICI traffic GB/s)

``bus_bw`` is compared against the chip's published aggregate ICI GB/s.
Written with shard_map + lax.psum so XLA lowers straight to the ICI
all-reduce; no host round-trips inside the timed loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ring_mesh, shard_map
from .hardware import chip_spec_for


@dataclass
class AllReduceResult:
    devices: int
    bytes_per_device: int
    seconds: float
    algo_bw_gbps: float
    bus_bw_gbps: float
    peak_ici_gbps: Optional[float]
    fraction_of_peak: Optional[float]
    device_kind: str
    correct: bool


def run(size_mb: float = 256.0, iters: int = 10, repeats: int = 5,
        devices=None) -> AllReduceResult:
    mesh = ring_mesh(devices)
    n = mesh.devices.size
    elems = int(size_mb * 1e6 / 4)
    x = jnp.ones((n, elems), dtype=jnp.float32)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("ring", None),
             out_specs=P("ring", None))
    def allreduce_chain(shard):
        def step(carry, _):
            s = lax.psum(carry, "ring")
            # keep values bounded and dependent across iterations; the
            # cast back to "varying" restores the scan-carry type (psum
            # output is replicated across the ring)
            s = s * (1.0 / n)
            if hasattr(lax, "pcast"):
                s = lax.pcast(s, "ring", to="varying")
            else:  # pragma: no cover - older jax
                s = lax.pvary(s, "ring")
            return s, ()

        out, _ = lax.scan(step, shard, None, length=iters)
        return out

    import numpy as np

    out = allreduce_chain(x)  # compile + warmup
    np.asarray(out[:1, :1])   # full sync (remote-runtime safe)

    calls = 4
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = x
        for _ in range(calls):
            out = allreduce_chain(out)  # data-dependent chaining
        np.asarray(out[:1, :1])         # single end-of-chain sync
        best = min(best, time.perf_counter() - t0)

    per_iter = best / (iters * calls)
    nbytes = elems * 4
    algo = nbytes / per_iter / 1e9
    bus = (2.0 * (n - 1) / n) * nbytes / per_iter / 1e9
    kind = getattr(mesh.devices.flat[0], "device_kind", "cpu")
    spec = chip_spec_for(kind)
    # psum of ones, renormalized by 1/n each iter -> stays ones
    correct = bool(jnp.allclose(out[0, :8], 1.0, rtol=1e-3).item())
    return AllReduceResult(
        devices=n, bytes_per_device=nbytes, seconds=best,
        algo_bw_gbps=algo, bus_bw_gbps=bus,
        peak_ici_gbps=spec.ici_bw_gbps if spec else None,
        fraction_of_peak=(bus / spec.ici_bw_gbps) if spec else None,
        device_kind=kind, correct=correct)


def main() -> int:
    import json

    res = run()
    print(json.dumps(res.__dict__))
    return 0 if res.correct else 1


if __name__ == "__main__":
    raise SystemExit(main())
