"""ICI collective-bandwidth proof: psum ring allreduce over the mesh.

The BASELINE.md north star: the validator's allreduce must achieve >=80%
of ICI link bandwidth. The measurement follows the standard ring-allreduce
accounting: for N chips each reducing S bytes, every chip moves
2*(N-1)/N * S bytes over its ICI links, so

    algo_bw  = S / t                      (allreduce "algorithmic" GB/s)
    bus_bw   = 2*(N-1)/N * S / t          (per-chip ICI traffic GB/s)

``bus_bw`` is compared against the chip's published aggregate ICI GB/s.
Written with shard_map + lax.psum so XLA lowers straight to the ICI
all-reduce; no host round-trips inside the timed loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ring_mesh, shard_map
from .backend import pins_platform
from .hardware import chip_spec_for


@pins_platform
def run(size_mb: float = 256.0, iters: int = 10, repeats: int = 5,
        devices=None) -> "CollectiveResult":
    """The gating psum measurement — one timing harness and one result
    type for the whole suite (run_collective)."""
    return run_collective("all_reduce", size_mb=size_mb, iters=iters,
                          repeats=repeats, devices=devices)


# ---------------------------------------------------------------------------
# full collective suite (the NCCL-tests slot: one number per primitive)
# ---------------------------------------------------------------------------

# per-chip ICI bytes moved per byte of PER-DEVICE INPUT, ring algorithms
# (NCCL-tests busbw accounting, restated for our input convention — NCCL
# normalizes all_gather by the total gathered size; here every op is
# normalized by what one device feeds in):
#   all_reduce       2*(n-1)/n   (reduce-scatter + all-gather phases)
#   all_gather        n-1        (each chip RECEIVES the other n-1 full
#                                 shards, each the size of its own input)
#   reduce_scatter    (n-1)/n    (each chip receives n-1 blocks of 1/n)
#   all_to_all        (n-1)/n    (keeps its own block local)
#   ppermute          1          (whole buffer crosses one hop)
_BUS_FACTOR = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: float(n - 1),
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
}


@dataclass
class CollectiveResult:
    devices: int
    bytes_per_device: int
    seconds: float
    algo_bw_gbps: float
    bus_bw_gbps: float
    peak_ici_gbps: Optional[float]
    fraction_of_peak: Optional[float]
    device_kind: str
    correct: bool
    op: str = "all_reduce"


# the historical name the validator/bench consume for the psum gate
AllReduceResult = CollectiveResult


def _step_fn(op: str, n: int):
    """The one-shot, shape-stable body of a collective — shared by the
    timed scan chain and the correctness oracle so the two can never
    drift apart. Shape-stable means the op output feeds the next input
    directly (no HBM rebuild inside the timed loop — except
    reduce_scatter, whose output is 1/n of its input and is re-expanded
    by an all_gather, so that chain times the RS+AG pair and its per-op
    figure is conservative)."""

    def norm(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, "ring", to="varying")
        return lax.pvary(x, "ring")  # pragma: no cover - older jax

    if op == "all_reduce":
        def one(c):
            return norm(lax.psum(c, "ring") * (1.0 / n))
    elif op == "all_gather":
        def one(c):
            g = lax.all_gather(c, "ring", axis=0, tiled=True)  # (n*k,)
            # slice a REMOTE block (the next device's): the local block
            # never crossed the wire, so checking it would prove nothing
            # about the fabric
            i = (lax.axis_index("ring") + 1) % n
            k = c.shape[0]
            return lax.dynamic_slice_in_dim(g, i * k, k, axis=0)
    elif op == "reduce_scatter":
        def one(c):
            s = lax.psum_scatter(c, "ring", scatter_dimension=0,
                                 tiled=True) * (1.0 / n)      # (k/n,)
            return lax.all_gather(s, "ring", axis=0, tiled=True)
    elif op == "all_to_all":
        def one(c):
            y = lax.all_to_all(c.reshape(n, -1), "ring", split_axis=0,
                               concat_axis=0, tiled=False)
            return y.reshape(c.shape)
    elif op == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]

        def one(c):
            return lax.ppermute(c, "ring", perm=perm)
    else:
        raise ValueError(f"unknown collective {op!r}")
    return one


def _chain_fn(op: str, mesh, n: int, iters: int):
    """A jitted scan of ``iters`` executions of the collective with a
    data dependence between steps."""
    one = _step_fn(op, n)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("ring"), out_specs=P("ring"))
    def chain(shard):
        out, _ = lax.scan(lambda c, _: (one(c), ()), shard, None,
                          length=iters)
        return out

    return chain


def _oracle_ok(op: str, mesh, n: int) -> bool:
    """Tiny-shape correctness check of the SAME step the timed chain
    runs, against a numpy oracle (the timed loop's inputs are constant
    ones, which would mask routing errors)."""
    import numpy as np

    k = 8 * n
    x = jnp.arange(n * k, dtype=jnp.float32).reshape(n, k)
    xs = np.asarray(x)
    one = _step_fn(op, n)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("ring", None),
             out_specs=P("ring", None))
    def apply_once(shard):
        return one(shard.reshape(-1)).reshape(1, k)

    got = np.asarray(apply_once(x))
    if op == "all_reduce":
        want = np.tile(xs.sum(axis=0) / n, (n, 1))
    elif op == "all_gather":
        # device i returns device (i+1)%n's shard
        want = np.roll(xs, -1, axis=0)
    elif op == "reduce_scatter":
        # RS averages blocks of the concatenated shards; AG re-gathers:
        # every device ends with the blockwise means, identical everywhere
        want = np.tile(xs.reshape(n, n, k // n).sum(axis=0).reshape(k) / n,
                       (n, 1))
    elif op == "all_to_all":
        want = xs.reshape(n, n, k // n).swapaxes(0, 1).reshape(n, k)
    else:  # ppermute: shard i lands on device i+1
        want = np.roll(xs, 1, axis=0)
    return bool(np.allclose(got, want, rtol=1e-4))


@pins_platform
def run_collective(op: str, size_mb: float = 64.0, iters: int = 10,
                   repeats: int = 5, devices=None) -> CollectiveResult:
    """Measure one collective primitive over the ICI ring (NCCL-tests
    slot). ``size_mb`` is the per-device buffer size."""
    import numpy as np

    mesh = ring_mesh(devices)
    n = mesh.devices.size
    # per-device k elements, divisible by n*n so all_to_all/RS tile evenly
    k = max(1, int(size_mb * 1e6 / 4) // (n * n)) * n * n
    x = jnp.ones((n, k), dtype=jnp.float32)

    chain = _chain_fn(op, mesh, n, iters)
    xf = x.reshape(n * k)  # shard_map over P("ring"): k per device
    out = chain(xf)
    np.asarray(out[:1])  # compile + sync

    calls = 4
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        o = xf
        for _ in range(calls):
            o = chain(o)
        np.asarray(o[:1])
        best = min(best, time.perf_counter() - t0)

    per_iter = best / (iters * calls)
    nbytes = k * 4
    algo = nbytes / per_iter / 1e9
    bus = _BUS_FACTOR[op](n) * nbytes / per_iter / 1e9
    kind = getattr(mesh.devices.flat[0], "device_kind", "cpu")
    spec = chip_spec_for(kind)
    return CollectiveResult(
        op=op, devices=n, bytes_per_device=nbytes, seconds=best,
        algo_bw_gbps=algo, bus_bw_gbps=bus,
        peak_ici_gbps=spec.ici_bw_gbps if spec else None,
        fraction_of_peak=(bus / spec.ici_bw_gbps) if spec else None,
        device_kind=kind, correct=_oracle_ok(op, mesh, n))


def run_suite(size_mb: float = 64.0, iters: int = 10, repeats: int = 3,
              devices=None, ops=None) -> dict:
    """One CollectiveResult per primitive — the full fabric picture the
    reference leaves to NCCL-tests inside user workloads."""
    return {op: run_collective(op, size_mb=size_mb, iters=iters,
                               repeats=repeats, devices=devices)
            for op in (ops or list(_BUS_FACTOR))}


def main() -> int:
    import json

    res = run()
    print(json.dumps(res.__dict__))
    return 0 if res.correct else 1


if __name__ == "__main__":
    raise SystemExit(main())
