"""Context-parallel attention for long sequences: ring + all-to-all.

Long-context workloads shard the *sequence* axis across chips; attention
then needs cross-chip communication because every query attends to every
(earlier) key. Two standard TPU-native strategies, both SPMD under
``shard_map`` so XLA lowers the communication onto ICI:

- **Ring attention** (``ring_attention``): K/V blocks rotate around a 1D
  ring of devices via ``jax.lax.ppermute`` while each device's Q stays
  put; partial results merge with an online-softmax (running max +
  normalizer) so the result is exact, not approximate. Communication is
  neighbor-to-neighbor only — the pattern ICI tori are built for — and
  each hop's transfer overlaps the next block's compute.
- **Ulysses / all-to-all** (``ulysses_attention``): ``lax.all_to_all``
  re-shards [B, S/n, H, D] -> [B, S, H/n, D], runs plain local attention
  over the *full* sequence with a head subset, then re-shards back.
  Cheaper at moderate sequence lengths (2 collectives instead of n-1
  hops), but requires n_heads % n_devices == 0.

The reference operator has no analog (its parallelism surface is fabric
*enablement*, SURVEY.md section 2.5); this module is part of the
framework's long-context story alongside the sharded burn-in step
(workloads/burnin.py). The single-device reference implementation doubles
as the correctness oracle in tests and in ``run()``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import shard_map, shard_map_unchecked

from .backend import pins_platform

NEG_INF = -1e30


def reference_attention(q, k, v, causal: bool = True):
    """Plain single-device attention, the correctness oracle.
    q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _block_attend(q, k, v, q_offset, k_offset, causal: bool):
    """One (Q-block, KV-block) tile: returns (out, lse-max m, normalizer l)
    with scores kept in f32 for the online-softmax merge.
    q: [B, Sq, H, D]; k,v: [B, Sk, H, D]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                      # [B, H, Sq]
    p = jnp.exp(scores - m[..., None])
    # fully-masked rows: m == NEG_INF, p == 1 from exp(0) — zero them
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                           # [B, H, Sq]
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(jnp.float32), m, l


def _block_attend_flash(q, k, v, q_offset, k_offset, causal: bool):
    """Same contract as _block_attend, but the tile runs as the Pallas
    flash kernel (workloads/flashattention.py): scores never leave VMEM
    and the kernel's (m, l) statistics feed the ring merge directly.
    Forward-only (the kernel defines no VJP); the einsum path remains
    the default for training."""
    from .flashattention import flash_attention_blocks

    B, S, H, D = q.shape
    sk = k.shape[1]
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(B * H, -1, D)
    out, m, l = flash_attention_blocks(fold(q), fold(k), fold(v),
                                       q_offset, k_offset, causal=causal)
    unnorm = out.astype(jnp.float32) * l[..., None]
    unnorm = unnorm.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    return unnorm, m.reshape(B, H, S), l.reshape(B, H, S)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          use_flash: bool = False):
    """Per-device body (runs inside shard_map). q,k,v: [B, S_local, H, D]
    sharded on S. K/V travel the ring; the online softmax merges each
    incoming block into (o, l, m) running state."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = my_idx * s_local

    # accumulators derive from q so they carry q's device-varying type —
    # a plain jnp.zeros would be "replicated" and trip shard_map's
    # varying-manual-axes check once the loop body mixes in ppermuted data
    zero_q = jnp.zeros_like(q, jnp.float32)
    o0 = zero_q
    l0 = zero_q[..., 0].transpose(0, 2, 1)            # [B, H, S_local]
    m0 = l0 + NEG_INF
    perm = [(j, (j + 1) % n) for j in range(n)]

    def merge(o, l, m, bo, bm, bl):
        m_new = jnp.maximum(m, bm)
        # rescale both accumulators onto the new max
        alpha = jnp.exp(m - m_new)          # old-state scale
        beta = jnp.exp(bm - m_new)          # block scale
        alpha = jnp.where(m_new <= NEG_INF / 2, 0.0, alpha)
        beta = jnp.where(m_new <= NEG_INF / 2, 0.0, beta)
        l = l * alpha + bl * beta
        o = o * alpha.transpose(0, 2, 1)[..., None] \
            + bo * beta.transpose(0, 2, 1)[..., None]
        return o, l, m_new

    block_attend = _block_attend_flash if use_flash else _block_attend

    def attend(i, o, l, m, k_blk, v_blk):
        # after i hops, the resident K/V block originated on device
        # (my_idx - i) mod n
        k_offset = ((my_idx - i) % n) * s_local
        bo, bm, bl = block_attend(q, k_blk, v_blk, q_offset, k_offset,
                                  causal)
        return merge(o, l, m, bo, bm, bl)

    def body(i, carry):
        o, l, m, k_blk, v_blk = carry
        o, l, m = attend(i, o, l, m, k_blk, v_blk)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return o, l, m, k_blk, v_blk

    # n-1 hops: the loop permutes after each of the first n-1 blocks; the
    # final resident block is attended outside so its K/V are never
    # shipped a pointless extra hop around the ring
    o, l, m, k_blk, v_blk = lax.fori_loop(0, n - 1, body, (o0, l0, m0, k, v))
    o, l, _ = attend(n - 1, o, l, m, k_blk, v_blk)
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows output zeros
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True, use_flash: bool = False):
    """Exact attention with the sequence axis sharded over ``axis_name``.
    q,k,v: [B, S, H, D] with S divisible by the axis size.

    ``use_flash`` runs each hop's local tile as the Pallas flash kernel
    (forward/inference path); the default einsum tile is differentiable
    and is what the training step uses."""
    spec = P(None, axis_name, None, None)
    # pallas_call's out_shape structs carry no varying-mesh-axes
    # annotation, which trips shard_map's vma check — the fused path
    # disables it (correctness is oracle-proven in tests)
    smap = shard_map_unchecked if use_flash else shard_map
    fn = smap(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    """Per-device body: all-to-all heads<->sequence, local full-sequence
    attention, all-to-all back. q,k,v: [B, S_local, H, D]."""
    a2a = lambda t: lax.all_to_all(t, axis_name, split_axis=2,
                                   concat_axis=1, tiled=True)
    q, k, v = a2a(q), a2a(k), a2a(v)          # [B, S, H_local, D]
    out = reference_attention(q, k, v, causal=causal)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                      causal: bool = True):
    """All-to-all sequence parallelism (Ulysses): needs
    n_heads % axis_size == 0."""
    axis_size = mesh.shape[axis_name]
    if q.shape[2] % axis_size:
        raise ValueError(f"n_heads={q.shape[2]} not divisible by "
                         f"axis size {axis_size}")
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


@dataclass
class ContextParallelResult:
    strategy: str
    devices: int
    seq_len: int
    max_abs_err: float
    seconds: float
    correct: bool


@pins_platform
def run(seq_len: int = 2048, n_heads: int = 8, head_dim: int = 64,
        batch: int = 1, causal: bool = True,
        strategy: str = "ring",
        mesh: Optional[Mesh] = None) -> ContextParallelResult:
    """Run context-parallel attention over all devices and check it
    against the single-device oracle."""
    import time

    devices = jax.devices()
    if mesh is None:
        from ..parallel.mesh import ring_mesh

        mesh = ring_mesh(devices, axis_name="sp")
    n = mesh.shape["sp"]
    if seq_len % n:
        raise ValueError(f"seq_len={seq_len} not divisible by {n} devices")
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (batch, seq_len, n_heads, head_dim)
    dtype = jnp.float32 if devices[0].platform == "cpu" else jnp.bfloat16
    q = jax.random.normal(kq, shape, dtype)
    k = jax.random.normal(kk, shape, dtype)
    v = jax.random.normal(kv, shape, dtype)

    fn = ring_attention if strategy == "ring" else ulysses_attention
    sharded = jax.jit(functools.partial(fn, mesh=mesh, causal=causal))
    out = sharded(q, k, v)
    out.block_until_ready()
    t0 = time.perf_counter()
    out = sharded(q, k, v)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    ref = jax.jit(functools.partial(reference_attention, causal=causal))(
        q, k, v)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    tol = 1e-4 if dtype == jnp.float32 else 2e-2
    return ContextParallelResult(strategy=strategy, devices=n,
                                 seq_len=seq_len, max_abs_err=err,
                                 seconds=dt, correct=err < tol)


def main() -> int:
    import json

    results = [run(strategy=s).__dict__ for s in ("ring", "ulysses")]
    print(json.dumps(results))
    return 0 if all(r["correct"] for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
