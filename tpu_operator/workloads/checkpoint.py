"""Checkpoint/resume for the sharded training workloads (orbax-backed).

The reference operator is stateless (SURVEY.md section 5: restart =
re-list + re-reconcile), so on the control-plane side checkpoint/resume
is N/A by design. The *workload* side is where the capability belongs on
TPU: long multi-host burn-ins and validation runs must survive
preemption (TPU pools are routinely preempted/defragmented), which means
saving the sharded train state to durable storage and restoring it with
the SAME shardings on a possibly different incarnation of the slice.

Orbax handles the heavy lifting (async multi-host writes, atomicity via
finalize-rename, per-shard files); this module pins down the framework
contract: save(state, step), latest_step(), restore(state_like) with
sharding-preserving restore driven by the live state's shardings.
"""

from __future__ import annotations

import logging
import pathlib
from typing import Any, Optional

import jax

log = logging.getLogger("tpu_operator.checkpoint")


class TrainCheckpointer:
    """Thin, typed wrapper over orbax's CheckpointManager for the burn-in
    train state (params/opt/step pytree)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = pathlib.Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, state: Any, step: int, wait: bool = True) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        return sorted(self._mgr.all_steps())

    def save_manifest(self, step: int, manifest: dict) -> None:
        """Persist the shard-layout manifest for a FINALIZED step.

        Orbax already owns blob atomicity (finalize-rename of the step
        directory); the manifest rides the same discipline — written to
        a tmp name and os.replace'd into place, so a crash mid-write
        never leaves a readable half-manifest. Only ever called after
        save() returned (the step is durable), which keeps the ordering
        invariant: a manifest's existence implies its step is complete.
        """
        import json
        import os

        path = self._dir / f"manifest-{int(step)}.json"
        tmp = self._dir / f".manifest-{int(step)}.json.tmp"
        tmp.write_text(json.dumps(manifest, sort_keys=True))
        os.replace(tmp, path)

    def read_manifest(self, step: int) -> Optional[dict]:
        """Shard-layout manifest for ``step``, or None when the step was
        saved pre-sharding (legacy blob) or the manifest is unreadable —
        callers treat None as 'full restore only'."""
        import json

        path = self._dir / f"manifest-{int(step)}.json"
        try:
            return json.loads(path.read_text())
        except (OSError, ValueError):
            return None

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of ``state_like`` (the freshly
        initialized state): each leaf comes back placed exactly where the
        live mesh wants it, so resume works even when the host set (and
        hence device ordering) changed across the preemption.

        With no explicit ``step``, an unreadable latest checkpoint (a
        crash can leave a torn step directory that still enumerates) falls
        back to the previous retained step instead of failing the job —
        each skip is logged and counted
        (tpu_operator_checkpoint_restore_fallbacks_total). An explicit
        ``step`` still raises: the caller asked for that step, not "the
        newest restorable one"."""
        import orbax.checkpoint as ocp

        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            state_like)
        if step is not None:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(target))
        candidates = sorted(self._mgr.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        last_err: Optional[Exception] = None
        for i, s in enumerate(candidates):
            try:
                return self._mgr.restore(
                    s, args=ocp.args.StandardRestore(target))
            except Exception as e:  # noqa: BLE001 — any unreadable step
                last_err = e
                if i + 1 < len(candidates):
                    from ..metrics.operator_metrics import OPERATOR_METRICS

                    OPERATOR_METRICS.checkpoint_restore_fallbacks.inc()
                    log.warning(
                        "checkpoint step %s under %s is partial/corrupt "
                        "(%s); falling back to step %s",
                        s, self._dir, e, candidates[i + 1])
        raise FileNotFoundError(
            f"no restorable checkpoint under {self._dir}") from last_err

    def close(self) -> None:
        self._mgr.close()
