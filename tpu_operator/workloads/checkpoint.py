"""Checkpoint/resume for the sharded training workloads (orbax-backed).

The reference operator is stateless (SURVEY.md section 5: restart =
re-list + re-reconcile), so on the control-plane side checkpoint/resume
is N/A by design. The *workload* side is where the capability belongs on
TPU: long multi-host burn-ins and validation runs must survive
preemption (TPU pools are routinely preempted/defragmented), which means
saving the sharded train state to durable storage and restoring it with
the SAME shardings on a possibly different incarnation of the slice.

Orbax handles the heavy lifting (async multi-host writes, atomicity via
finalize-rename, per-shard files); this module pins down the framework
contract: save(state, step), latest_step(), restore(state_like) with
sharding-preserving restore driven by the live state's shardings.
"""

from __future__ import annotations

import logging
import pathlib
from typing import Any, Optional

import jax

log = logging.getLogger("tpu_operator.checkpoint")


class TrainCheckpointer:
    """Thin, typed wrapper over orbax's CheckpointManager for the burn-in
    train state (params/opt/step pytree)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = pathlib.Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, state: Any, step: int, wait: bool = True) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of ``state_like`` (the freshly
        initialized state): each leaf comes back placed exactly where the
        live mesh wants it, so resume works even when the host set (and
        hence device ordering) changed across the preemption."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            state_like)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(target))

    def close(self) -> None:
        self._mgr.close()
