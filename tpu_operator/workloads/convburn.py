"""Conv burn-in: the vision/conv model family of the fleet-exercise set.

The transformer burn-in (burnin.py) exercises the MXU through matmuls;
this workload exercises the OTHER MXU FLOP family — convolutions — which
hit different XLA lowering paths (conv_general_dilated tiling, im2col /
spatial partitioning) and different HBM access patterns (activation
feature maps instead of attention caches). A fleet that only ever ran
matmuls can still have a chip that faults on convs; the reference's
burn-in slot (the CUDA workload pod, validator/cuda-workload-validation.yaml,
and dcgmproftester practice) covers both; so does this pair.

TPU-first choices:
- NHWC activations with HWIO filters — the layout XLA's TPU conv
  emitter is native in (no transposes in the lowered HLO);
- bf16 compute, fp32 loss/norm statistics;
- channel tensor parallelism via GSPMD: each residual block's first
  conv is output-channel sharded (column-parallel), the second is
  input-channel sharded (row-parallel), so XLA inserts exactly one
  psum per block — the Megatron pattern applied to HWIO filters;
- data parallelism over batch; the same [data, model] mesh contract as
  the transformer burn-in, so it runs unchanged on multi-slice meshes
  through parallel.multihost.training_mesh.

Correctness oracle: loss must fall over a few steps (grads flowed
through every shard), same contract as burnin.run.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ConvBurninConfig:
    image_size: int = 32
    in_channels: int = 3
    width: int = 32          # channel width; divisible by the model axis
    n_blocks: int = 2
    n_classes: int = 16
    batch: int = 8
    learning_rate: float = 1e-3
    dtype: Any = jnp.bfloat16


# --- parameters + shardings ------------------------------------------------


def init_params(cfg: ConvBurninConfig, key) -> Dict:
    k = iter(jax.random.split(key, 2 + 2 * cfg.n_blocks))

    def he(shape):  # Kaiming init over the conv fan-in (H*W*I)
        fan_in = shape[0] * shape[1] * shape[2]
        return jax.random.normal(next(k), shape) * jnp.sqrt(2.0 / fan_in)

    p: Dict[str, Any] = {
        # stem: 3x3, in_channels -> width
        "stem": he((3, 3, cfg.in_channels, cfg.width)),
        "head": jax.random.normal(next(k), (cfg.width, cfg.n_classes))
        * (1.0 / jnp.sqrt(cfg.width)),
        "blocks": [],
    }
    for _ in range(cfg.n_blocks):
        p["blocks"].append({
            "conv1": he((3, 3, cfg.width, cfg.width)),
            "conv2": he((3, 3, cfg.width, cfg.width)),
            "scale1": jnp.ones((cfg.width,)),
            "scale2": jnp.ones((cfg.width,)),
        })
    return p


def param_specs(cfg: ConvBurninConfig) -> Dict:
    """Column-parallel conv1 (output channels on `model`), row-parallel
    conv2 (input channels on `model`): one psum per block, inserted by
    the SPMD partitioner."""
    block = {
        "conv1": P(None, None, None, "model"),   # HWIO: O sharded
        "conv2": P(None, None, "model", None),   # HWIO: I sharded
        "scale1": P("model"),                     # follows conv1 output
        "scale2": P(None),
    }
    return {
        "stem": P(),
        "head": P(None, "model"),                 # column-parallel head
        "blocks": [dict(block) for _ in range(cfg.n_blocks)],
    }


def shard_params(params: Dict, mesh: Mesh, cfg: ConvBurninConfig) -> Dict:
    # tree.map flattens by the FIRST tree (params); each PartitionSpec in
    # the specs tree is taken whole at the matching leaf position
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, param_specs(cfg))


# --- model -----------------------------------------------------------------


def _conv(x, w, stride: int = 1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _norm(x, scale):
    """Channel RMS norm with fp32 statistics (batch-size independent,
    no running stats to shard)."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(1, 2),
                   keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale


def forward(params: Dict, images: jnp.ndarray, cfg: ConvBurninConfig,
            mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """images [B, H, W, C_in] -> logits [B, n_classes]. With a mesh,
    activation constraints pin the dp/channel-tp layout; without one the
    same code is the single-chip proof path (burnin.forward contract)."""
    if mesh is not None:
        csc = lambda t, spec: jax.lax.with_sharding_constraint(
            t, NamedSharding(mesh, spec))
    else:
        csc = lambda t, spec: t
    x = _conv(images.astype(cfg.dtype), params["stem"].astype(cfg.dtype))
    x = csc(x, P("data", None, None, None))
    for bp in params["blocks"]:
        h = _conv(x, bp["conv1"].astype(cfg.dtype))
        h = csc(h, P("data", None, None, "model"))  # column-parallel out
        h = jax.nn.relu(_norm(h, bp["scale1"].astype(cfg.dtype)))
        h = _conv(h, bp["conv2"].astype(cfg.dtype))
        h = csc(h, P("data", None, None, None))     # psum happened here
        x = jax.nn.relu(x + _norm(h, bp["scale2"].astype(cfg.dtype)))
    pooled = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # [B, width]
    logits = pooled @ params["head"].astype(jnp.float32)
    return csc(logits, P("data", None))


def loss_fn(params: Dict, batch: Dict, cfg: ConvBurninConfig,
            mesh: Optional[Mesh] = None) -> jnp.ndarray:
    logits = forward(params, batch["images"], cfg, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


# --- training step ---------------------------------------------------------


def make_train_step(mesh: Mesh, cfg: ConvBurninConfig, optimizer=None):
    optimizer = optimizer or optax.adamw(cfg.learning_rate)

    def init_state(key):
        params = shard_params(init_params(cfg, key), mesh, cfg)
        return {"params": params, "opt": optimizer.init(params),
                "step": jnp.zeros((), jnp.int32)}

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch,
                                                  cfg, mesh)
        updates, new_opt = optimizer.update(grads, state["opt"],
                                            state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt,
                "step": state["step"] + 1}, loss

    return jax.jit(train_step, donate_argnums=0), init_state


def make_batch(cfg: ConvBurninConfig, mesh: Mesh, key) -> Dict:
    k1, k2 = jax.random.split(key)
    images = jax.random.normal(
        k1, (cfg.batch, cfg.image_size, cfg.image_size, cfg.in_channels))
    labels = jax.random.randint(k2, (cfg.batch,), 0, cfg.n_classes)
    return {
        "images": jax.device_put(
            images, NamedSharding(mesh, P("data", None, None, None))),
        "labels": jax.device_put(labels, NamedSharding(mesh, P("data"))),
    }


def run(cfg: Optional[ConvBurninConfig] = None, steps: int = 5,
        model_parallel: Optional[int] = None) -> Tuple[float, float]:
    """Run the conv burn-in; returns (first_loss, last_loss); loss must
    fall (the grads-flowed-through-every-shard proof)."""
    from ..parallel.multihost import initialize, training_mesh

    cfg = cfg or ConvBurninConfig()
    initialize()
    mesh = training_mesh(model_parallel=model_parallel)
    step, init_state = make_train_step(mesh, cfg)
    key = jax.random.PRNGKey(0)
    state = init_state(key)
    first = last = None
    for i in range(steps):
        batch = make_batch(cfg, mesh, jax.random.fold_in(key, i))
        state, loss = step(state, batch)
        last = float(loss)
        first = last if first is None else first
    return first, last


def main() -> int:
    import json

    first, last = run()
    ok = last < first
    print(json.dumps({"workload": "convburn", "first_loss": first,
                      "last_loss": last, "loss_fell": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
