"""Pipeline parallelism (GPipe-style) over a 1D device mesh.

The pp slot of the dp/tp/pp/sp/ep strategy set: transformer stages are
sharded one-per-device along a ``pipe`` mesh axis; microbatches stream
through the pipeline with activations handed stage-to-stage by
``jax.lax.ppermute`` inside a ``lax.scan`` schedule (M + S - 1 ticks for
M microbatches over S stages), so XLA lowers the handoffs onto ICI
neighbor links — the wiring pipeline parallelism exists to exploit.
Like every workload here (SURVEY.md §2.5), it doubles as a proof: the
pipelined forward must match the sequential single-device oracle
bit-for-bit within tolerance, making it a validator-grade check that
stage handoffs over the interconnect do not corrupt activations.

No reference analog (the GPU operator contains no parallelism
implementations, SURVEY.md §2.5); the design follows the public GPipe
schedule, written shard_map-first.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import make_varying, shard_map

from .backend import pins_platform


def init_stage_params(key, n_stages: int, d_model: int, d_ff: int) -> dict:
    """Stacked per-stage FFN-block weights, leading axis = stage."""
    ks = jax.random.split(key, 2)
    scale1 = 1.0 / np.sqrt(d_model)
    scale2 = 1.0 / np.sqrt(d_ff)
    return {
        "w1": jax.random.normal(ks[0], (n_stages, d_model, d_ff),
                                jnp.float32) * scale1,
        "b1": jnp.zeros((n_stages, d_ff), jnp.float32),
        "w2": jax.random.normal(ks[1], (n_stages, d_ff, d_model),
                                jnp.float32) * scale2,
        "b2": jnp.zeros((n_stages, d_model), jnp.float32),
    }


def stage_fn(p: dict, x: jax.Array) -> jax.Array:
    """One pipeline stage: pre-norm FFN block with residual."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    h = (x - mu) * lax.rsqrt(var + 1e-6)
    h = jax.nn.gelu(h @ p["w1"] + p["b1"])
    return x + h @ p["w2"] + p["b2"]


def reference_forward(params: dict, x: jax.Array) -> jax.Array:
    """Sequential oracle: apply every stage on one device."""
    n_stages = params["w1"].shape[0]
    for s in range(n_stages):
        x = stage_fn(jax.tree_util.tree_map(lambda a: a[s], params), x)
    return x


def _pipeline_local(params, x_micro, axis_name: str):
    """Per-device body (inside shard_map). params: this stage's weights
    (leading stage axis of size 1); x_micro: [M, b, T, D] microbatches
    (replicated). GPipe schedule: M + S - 1 ticks."""
    stage = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)
    p_local = jax.tree_util.tree_map(lambda a: a[0], params)
    n_micro = x_micro.shape[0]

    # activations travel stage -> stage+1 each tick
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    # the carries must be device-varying from tick 0 (plain zeros are
    # "replicated" and trip shard_map's varying-manual-axes check once
    # the body mixes in ppermuted data — same constraint as
    # ringattention's accumulators)
    act0 = make_varying(jnp.zeros_like(x_micro[0]), axis_name)
    outbuf0 = make_varying(jnp.zeros_like(x_micro), axis_name)

    def tick(carry, t):
        act, outbuf = carry
        # stage 0 injects microbatch t (clipped; injections past M are
        # pipeline-drain garbage that never reaches the output window)
        inject = x_micro[jnp.clip(t, 0, n_micro - 1)]
        my_in = jnp.where(stage == 0, inject, act)
        my_out = stage_fn(p_local, my_in)
        # the last stage completes microbatch t - (S - 1) at tick t
        idx = t - (n_stages - 1)
        write = (stage == n_stages - 1) & (idx >= 0) & (idx < n_micro)
        updated = outbuf.at[jnp.clip(idx, 0, n_micro - 1)].set(my_out)
        outbuf = jnp.where(write, updated, outbuf)
        act_next = lax.ppermute(my_out, axis_name, perm)
        return (act_next, outbuf), None

    (_, outbuf), _ = lax.scan(tick, (act0, outbuf0),
                              jnp.arange(n_micro + n_stages - 1))
    # results live on the last stage; psum of the masked buffer
    # replicates them everywhere
    mine = jnp.where(stage == n_stages - 1, outbuf,
                     jnp.zeros_like(outbuf))
    return lax.psum(mine, axis_name)


def pipeline_forward(params: dict, x: jax.Array, mesh: Mesh,
                     axis_name: str = "pipe",
                     n_microbatches: int = 4) -> jax.Array:
    """x: [B, T, D] with B divisible by n_microbatches. Stage weights are
    sharded one-per-device along ``axis_name``; the output is replicated."""
    batch, seq, d_model = x.shape
    assert batch % n_microbatches == 0, (batch, n_microbatches)
    x_micro = x.reshape(n_microbatches, batch // n_microbatches, seq,
                        d_model)
    fn = shard_map(
        partial(_pipeline_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
    )
    out = fn(params, x_micro)
    return out.reshape(batch, seq, d_model)


@dataclass
class PipelineResult:
    stages: int
    microbatches: int
    batch: int
    seq_len: int
    d_model: int
    max_abs_err: float
    correct: bool
    device_kind: str


@pins_platform
def run(mesh: Mesh = None, axis_name: str = "pipe", batch: int = 8,
        seq_len: int = 16, d_model: int = 32, d_ff: int = 64,
        n_microbatches: int = 4, seed: int = 0) -> PipelineResult:
    """Build an S-stage pipeline over the mesh, stream microbatches
    through it, and diff against the sequential oracle."""
    from ..parallel.mesh import ring_mesh

    if mesh is None:
        mesh = ring_mesh(axis_name=axis_name)
    n_stages = int(np.prod(list(mesh.shape.values())))
    key = jax.random.PRNGKey(seed)
    kp, kx = jax.random.split(key)
    params = init_stage_params(kp, n_stages, d_model, d_ff)
    x = jax.random.normal(kx, (batch, seq_len, d_model), jnp.float32)

    piped = jax.jit(partial(pipeline_forward, mesh=mesh,
                            axis_name=axis_name,
                            n_microbatches=n_microbatches))(
        jax.device_put(params, NamedSharding(mesh, P(axis_name))), x)
    oracle = reference_forward(params, x)
    err = float(jnp.max(jnp.abs(piped - oracle)))
    dev = jax.devices()[0]
    return PipelineResult(
        stages=n_stages, microbatches=n_microbatches, batch=batch,
        seq_len=seq_len, d_model=d_model, max_abs_err=err,
        correct=bool(err < 1e-4),
        device_kind=getattr(dev, "device_kind", dev.platform))


def main() -> int:  # pragma: no cover - manual entry
    res = run()
    print(res)
    return 0 if res.correct else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
