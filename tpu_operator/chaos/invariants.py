"""Continuous cluster-invariant checking for chaos runs.

The checker reads the cluster through the UNWRAPPED inner client (its
reads must never consume an armed fault or perturb the run) and asserts
the properties the control plane promises to hold *at every observation
point*, not just at convergence:

- ``rv-regress``: resourceVersions never move backwards on the policy
  CR, Nodes, or operand DaemonSets. The fake apiserver's RV counter is
  globally monotonic, so a regression means a write path resurrected a
  stale snapshot — a lost status update.
- ``fsm-monotonic``: per upgrade *unit* (all hosts of a multi-host
  slice, the upgrade controller's own grouping), the aggregate FSM state
  only walks forward through ``_STAGE_ORDER``, with exactly the legal
  resets: anything may fail; ``failed`` retries to ``upgrade-required``;
  ``done`` may re-enter ``upgrade-required`` on a new rollout. A unit
  observed moving backward mid-flight (drain back to cordon) lost a
  member's transition.
- ``upgrade-budget``: units concurrently in ``IN_PROGRESS_STATES`` never
  exceed ``upgradePolicy.maxParallelUpgrades``.
- ``gauge-consistency`` (settled runs only): the slice gauges and the
  CR's ``status.slices[]`` rows agree with a fresh
  :func:`~tpu_operator.controllers.slices.slice_status` computation.
  Checked only once faults stop — mid-storm a reconcile legally sets
  gauges and then loses its status write to an injected 409.
- ``cache-staleness`` (when the controllers read through a
  :class:`~tpu_operator.runtime.cache.CachedClient`): continuously, no
  cached object may be *ahead* of the authoritative store — a cached
  resourceVersion above the apiserver's means the cache invented state
  (being behind mid-storm is legal; that's what healing is for). Once
  settled, the cache must agree exactly: same keys, same
  resourceVersions, for every kind it caches — a dropped watch that
  resumed must leave no stale or phantom entries behind.
- ``dag-order`` (when the runner hands over the state manager's
  :class:`~tpu_operator.state.scheduler.SyncJournal`): within every sync
  pass, no operand state may *start* syncing before every state in its
  ``requires()`` has *finished* — the dependency contract the DAG
  scheduler exists to uphold, checked against the journal's sequence
  numbers rather than trusted. Journal entries accumulate per pass
  across drains, so a pass split over two observation points cannot
  false-positive.
- ``placement-sound``: no node is ever claimed by two Placed
  SliceRequests at once, and a bound node never violates the request's
  accelerator pin. Once settled, every bound node must also exist and
  carry the matching ``tpu.graft.dev/placed-by`` lease, and no node may
  carry an orphan lease (mid-storm a NODE_REMOVE legally breaks a
  binding until the eviction path catches up). Checked in every
  scenario — a run with no SliceRequests is a clean no-op.
- ``placement-stable``: a Placed request's node set never changes
  without ``status.evictions`` OR ``status.migrations`` incrementing —
  the controller's promise that placements only move through an
  explicit drain event or an acknowledged elastic migration, never a
  silent re-pack.
- ``no-lost-work``: the elastic-slice durability promise. A workload's
  acked step (``status.migration.ackedStep`` / the
  ``tpu.graft.dev/slice-intent-ack`` annotation) is a receipt for a
  finalized checkpoint, so per request the acked high-water mark never
  regresses, and every restore (``status.migration.restoredStep``
  changing) lands at or above it — acknowledged training work must
  survive any migrate/resize/crash interleaving the storm produces.
- ``index-coherence``: the incremental placement index
  (:class:`~tpu_operator.topology.index.FleetIndex`), fed O(delta) from
  the node-list diffs between observation points exactly as the
  placement controller's resync path feeds it, must rank
  candidate-for-candidate identically to a from-scratch ``FleetState``
  — same ``sort_key`` order, same ``unschedulable_reason`` — for a
  panel of probe request shapes at every settle point. A divergence
  means the O(delta) maintenance lost or invented structure the full
  rebuild sees.
- ``telemetry-no-flap-evict``: a telemetry-driven eviction
  (``status.lastEvictionReason`` naming a node "condemned by
  telemetry") is legal only for a node whose own digest stream — folded
  independently here, seq by seq, through the same hysteresis rule the
  scorer uses — actually sustained ``CONDEMN_AFTER`` consecutive FAIL
  publishes, and at most once per (request, node) pair. A flapping chip
  must cause zero evictions and a condemned one must never ping-pong
  the same slice off the same node twice. Checked in every scenario — a
  run that publishes no digests is a clean no-op.
- ``no-starvation`` (when the runner hands over the scenario's quota
  tree): a quota class with work queued and usage below its
  min-guarantee floor (``min(minChips, usage + queued)`` — the same
  floor the admission watchdog clocks) may stay starved for at most its
  ``starvationBoundSeconds`` of virtual time before the deficit-driven
  escalation and budgeted preemption must have rescued it. Folded
  independently from CR phases and spec sizes, never from the
  controller's own deficit clocks. Checked in every scenario — with no
  quota tree the fold is a strict no-op, so legacy verdicts stay
  byte-identical.
- ``preemption-budget`` (same gating): preemptions are bounded AND
  non-lethal. Each posted preempt intent (a ``status.migration`` with
  ``preemptedFor`` and a fresh ``startedAt``) counts against the
  victim's class inside a sliding ``preemptWindowSeconds`` window; more
  events than the class's ``preemptTokens`` is a violation. And a
  preemption must route through the elastic checkpoint->rebind
  handshake — a ``status.evictions`` increment whose reason names a
  preemption means a slice was killed for quota, the one thing the
  budgeted path exists to prevent.
- ``lane-priority`` (recorded by the runner): no health-lane event may
  be dequeued having waited behind more than the runner's
  ``LANE_PRIORITY_BUDGET`` bulk reconciles — the workload-aware
  queueing promise the priority lanes exist for, audited from the
  controllers' lane journals at verdict time.
- ``convergence``: recorded by the runner when the cluster fails to
  reach all-Ready within the soak budget after faults stop.

Every violation also increments
``tpu_operator_chaos_invariant_violations_total{invariant=...}``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api import labels as L
from ..api.clusterpolicy import KIND_CLUSTER_POLICY, V1
from ..controllers.upgrade_controller import (
    IN_PROGRESS_STATES,
    STATE_DONE,
    STATE_FAILED,
    STATE_UPGRADE_REQUIRED,
    _STAGE_ORDER,
)
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..metrics.registry import REGISTRY
from ..runtime.client import Client, ListOptions
from ..runtime.objects import get_nested, labels_of, name_of


@dataclass(frozen=True)
class Violation:
    invariant: str
    step: int
    detail: str

    def to_dict(self) -> dict:
        return {"invariant": self.invariant, "step": self.step,
                "detail": self.detail}


class InvariantChecker:
    def __init__(self, client: Client, namespace: str = "tpu-operator",
                 cache=None, journal=None, quota=None,
                 step_dt: float = 1.0):
        self.client = client
        self.namespace = namespace
        self.cache = cache  # CachedClient under test, or None
        self.journal = journal  # state manager's SyncJournal, or None
        # the scenario's QuotaTree (admission invariants), or None —
        # with None the admission fold is a strict no-op, so every
        # pre-quota scenario's verdict stays byte-identical
        self.quota = quota
        self.step_dt = step_dt  # virtual seconds per observation step
        self.violations: List[Violation] = []
        self._last_rv: Dict[Tuple[str, str, str], int] = {}
        self._unit_states: Dict[Tuple[str, ...], Optional[str]] = {}
        # pass_id -> {state: done_seq}, accumulated across journal drains
        self._dag_done: Dict[int, Dict[str, int]] = {}
        # request key -> (sorted bound-node tuple, evictions, migrations)
        # at the last observation the request was Placed
        # (placement-stable history)
        self._placements: Dict[str, Tuple[Tuple[str, ...], int, int]] = {}
        # request key -> (acked high-water step, last restoredStep seen)
        # for the no-lost-work audit
        self._work: Dict[str, Tuple[Optional[int], Optional[int]]] = {}
        # long-lived FleetIndex fed by node-list diffs across the whole
        # run (index-coherence); built lazily on the first observation
        self._fleet_index = None
        # telemetry-no-flap-evict: an independent digest fold (last seq,
        # fail streak, ever-legitimately-condemned set) plus the
        # telemetry-eviction ledger per (request key, node)
        self._tel_seq: Dict[str, object] = {}
        self._tel_fail: Dict[str, int] = {}
        self._tel_ever: set = set()
        self._tel_evicted: Dict[Tuple[str, str], int] = {}
        self._tel_evictions: Dict[str, int] = {}
        # admission fold: class -> step its starvation began; request
        # key -> last migration startedAt counted as a preempt event;
        # class -> event steps inside the sliding window; request key ->
        # last evictions count (preemptions must never surface here)
        self._starve_start: Dict[str, int] = {}
        self._preempt_seen: Dict[str, object] = {}
        self._preempt_events: Dict[str, List[int]] = {}
        self._adm_evictions: Dict[str, int] = {}

    def on_operator_restart(self, step: int, cache=None,
                            journal=None) -> None:
        """The operator process died and a successor took over: audit
        and release the dead process's sync journal, then follow the
        successor's cache and journal. Cluster-state history (RVs,
        placements, acked work, FSM units) survives untouched — the
        cluster didn't restart, the operator did."""
        self._check_dag(step)
        # the successor's journal restarts pass ids and sequence
        # numbers; stale done-seqs would false-positive dag-order
        self._dag_done.clear()
        self.cache = cache
        self.journal = journal

    def record(self, invariant: str, step: int, detail: str) -> None:
        self.violations.append(Violation(invariant, step, detail))
        OPERATOR_METRICS.chaos_invariant_violations.labels(
            invariant=invariant).inc()

    def to_list(self) -> List[dict]:
        return [v.to_dict() for v in self.violations]

    # -- periodic observation ----------------------------------------------

    def observe(self, step: int) -> None:
        nodes = {name_of(n): n for n in self.client.list("v1", "Node")}
        self._check_rv(step, nodes)
        self._check_fsm(step, nodes)
        self._check_budget(step, nodes)
        self._check_cache(step, settled=False)
        self._check_dag(step)
        self._check_placement(step, nodes, settled=False)
        self._check_work(step)
        self._check_telemetry(step, nodes)
        self._check_admission(step)
        self._feed_index(nodes)

    # -- fair-share admission ------------------------------------------------

    def _check_admission(self, step: int) -> None:
        """no-starvation + preemption-budget (see module docstring).
        The fold is the checker's OWN: per-class usage and queue depth
        come straight from CR phases and spec sizes, the starvation
        floor is recomputed from the tree — a watchdog whose deficit
        clocks drift is caught rather than trusted."""
        if self.quota is None:
            return
        from ..api.slicerequest import (
            KIND_SLICE_REQUEST,
            PHASE_PLACED,
            V1ALPHA1,
            SliceRequestSpec,
        )
        from ..controllers.slices import migration_of

        dt = max(self.step_dt, 1e-9)
        usage: Dict[str, int] = {}
        queued: Dict[str, int] = {}
        for req in sorted(self.client.list(V1ALPHA1, KIND_SLICE_REQUEST),
                          key=lambda r: (namespace_key(r), name_of(r))):
            key = f"{namespace_key(req) or 'default'}/{name_of(req)}"
            cls = self.quota.class_of(req)
            if get_nested(req, "status", "phase") == PHASE_PLACED:
                usage[cls] = usage.get(cls, 0) + int(
                    get_nested(req, "status", "chips", default=0) or 0)
            else:
                queued[cls] = queued.get(cls, 0) + int(
                    SliceRequestSpec.from_obj(req).chips_needed() or 0)
            mig = migration_of(req)
            started = mig.get("startedAt")
            if mig.get("preemptedFor") and started is not None \
                    and self._preempt_seen.get(key) != started:
                # one event per posted preempt intent, charged to the
                # VICTIM's class (the budget bounds what a class suffers)
                self._preempt_seen[key] = started
                self._preempt_events.setdefault(cls, []).append(step)
            evictions = int(get_nested(req, "status", "evictions",
                                       default=0) or 0)
            prev = self._adm_evictions.get(key, 0)
            self._adm_evictions[key] = evictions
            if evictions > prev:
                reason = str(get_nested(req, "status",
                                        "lastEvictionReason",
                                        default="") or "")
                if reason.startswith("preempted"):
                    self.record(
                        "preemption-budget", step,
                        f"{key}: hard-evicted for a preemption "
                        f"({reason!r}) — quota reclaim must migrate "
                        f"through the checkpoint handshake, never kill")
        for name in self.quota.leaf_names():
            qc = self.quota.get(name)
            events = self._preempt_events.get(name)
            if events:
                horizon = step - qc.preempt_window_s / dt
                events[:] = [s for s in events if s > horizon]
                if len(events) > qc.preempt_tokens:
                    self.record(
                        "preemption-budget", step,
                        f"class {name}: {len(events)} preemptions inside "
                        f"one {qc.preempt_window_s:.0f}s window, budget "
                        f"is {qc.preempt_tokens}")
                    events.clear()  # one report per overrun, not per step
            use = usage.get(name, 0)
            q = queued.get(name, 0)
            floor = min(qc.min_chips, use + q)
            if not (q > 0 and use < floor):
                self._starve_start.pop(name, None)
                continue
            start = self._starve_start.setdefault(name, step)
            waited = (step - start) * dt
            if waited > qc.starvation_bound_s:
                self.record(
                    "no-starvation", step,
                    f"class {name}: {use}/{floor} min-guarantee chips "
                    f"with {q} queued for {waited:.0f} virtual s — past "
                    f"the {qc.starvation_bound_s:.0f}s starvation bound")
                self._starve_start[name] = step  # re-arm, don't spam

    # -- telemetry eviction legality -----------------------------------------

    def _check_telemetry(self, step: int, nodes: Dict[str, dict]) -> None:
        """telemetry-no-flap-evict (see module docstring). The fold here
        is the checker's OWN: same hysteresis rule as the production
        scorer (consecutive FAIL publishes by digest seq, any other
        status resets the streak) but fed straight from the node
        annotations, so a scorer that miscounts is caught rather than
        trusted. Runs at most one publish behind — the runner applies at
        most one digest per node per step, and this observes every step."""
        from ..api.slicerequest import KIND_SLICE_REQUEST, V1ALPHA1
        from ..metrics.fleet import CONDEMN_AFTER
        from ..metrics.health_engine import parse_digest

        for name in sorted(nodes):
            digest = parse_digest((get_nested(
                nodes[name], "metadata", "annotations", default={})
                or {}).get(L.HEALTH_DIGEST))
            if digest is None or digest.get("seq") == self._tel_seq.get(
                    name):
                continue
            self._tel_seq[name] = digest.get("seq")
            if str(digest.get("status", "")) == "fail":
                self._tel_fail[name] = self._tel_fail.get(name, 0) + 1
                if self._tel_fail[name] >= CONDEMN_AFTER:
                    self._tel_ever.add(name)
            else:
                self._tel_fail.pop(name, None)
        requests = self.client.list(V1ALPHA1, KIND_SLICE_REQUEST)
        if not requests and not self._tel_evictions:
            return
        live = set()
        for req in sorted(requests, key=name_of):
            key = f"{namespace_key(req) or 'default'}/{name_of(req)}"
            live.add(key)
            evictions = int(get_nested(req, "status", "evictions",
                                       default=0) or 0)
            prev = self._tel_evictions.get(key, 0)
            self._tel_evictions[key] = evictions
            if evictions <= prev:
                continue
            reason = str(get_nested(req, "status", "lastEvictionReason",
                                    default="") or "")
            if not (reason.startswith("node ")
                    and reason.endswith(" condemned by telemetry")):
                continue
            node_name = reason[len("node "):-len(
                " condemned by telemetry")]
            if node_name not in self._tel_ever:
                self.record(
                    "telemetry-no-flap-evict", step,
                    f"{key}: evicted off {node_name}, whose digest "
                    f"stream never sustained {CONDEMN_AFTER} consecutive "
                    f"FAIL publishes (streak now "
                    f"{self._tel_fail.get(node_name, 0)}) — a flapping "
                    f"chip caused an eviction")
            pair = (key, node_name)
            self._tel_evicted[pair] = self._tel_evicted.get(pair, 0) + 1
            if self._tel_evicted[pair] > 1:
                self.record(
                    "telemetry-no-flap-evict", step,
                    f"{key}: evicted off {node_name} by telemetry "
                    f"{self._tel_evicted[pair]} times — condemn/absolve "
                    f"ping-pong")
        for key in [k for k in self._tel_evictions if k not in live]:
            del self._tel_evictions[key]

    # -- incremental-index coherence ----------------------------------------

    def _feed_index(self, nodes: Dict[str, dict]) -> None:
        from ..topology.index import FleetIndex

        if self._fleet_index is None:
            self._fleet_index = FleetIndex(list(nodes.values()))
        else:
            # the same O(delta) diff feed the controller uses when the
            # client has no delta hook — so the index under audit has
            # lived through every churn step, never a fresh rebuild
            self._fleet_index.resync(list(nodes.values()))

    def _check_index(self, step: int, nodes: Dict[str, dict]) -> None:
        """index-coherence (see module docstring): candidate-for-candidate
        equality between the run-long incrementally-fed FleetIndex and a
        from-scratch FleetState, across probe shapes covering plain,
        pinned, preferred-generation, and infeasible requests."""
        from ..api.slicerequest import SliceRequestSpec
        from ..topology.placement import (
            FleetState,
            rank_candidates,
            unschedulable_reason,
        )

        self._feed_index(nodes)
        idx = self._fleet_index
        scratch = FleetState(list(nodes.values()))
        probes = [SliceRequestSpec(chips=c) for c in (4, 8, 16, 32)]
        probes += [SliceRequestSpec(chips=8,
                                    accelerator="tpu-v5p-slice"),
                   SliceRequestSpec(chips=8,
                                    preferred_generations=("v5p",))]
        for spec in probes:
            want = [c.sort_key() for c in rank_candidates(spec, scratch)]
            got = [c.sort_key() for c in idx.rank(spec)]
            if got != want:
                self.record(
                    "index-coherence", step,
                    f"spec chips={spec.chips_needed()} "
                    f"acc={spec.accelerator!r}: index ranked "
                    f"{len(got)} candidates (top {got[:1]}), rescan "
                    f"ranked {len(want)} (top {want[:1]})")
            best = idx.best(spec)
            top = (best.sort_key() if best is not None else None)
            if top != (want[0] if want else None):
                self.record(
                    "index-coherence", step,
                    f"spec chips={spec.chips_needed()}: index best() "
                    f"{top} != rescan top "
                    f"{want[0] if want else None}")
        impossible = SliceRequestSpec(chips=10 ** 6)
        want_reason = unschedulable_reason(impossible, scratch)
        got_reason = idx.unschedulable_reason(impossible)
        if got_reason != want_reason:
            self.record(
                "index-coherence", step,
                f"unschedulable_reason diverged: index {got_reason!r} "
                f"!= rescan {want_reason!r}")

    # -- slice placement ----------------------------------------------------

    def _check_placement(self, step: int, nodes: Dict[str, dict],
                         settled: bool) -> None:
        """placement-sound + placement-stable (see module docstring).
        Listing an unknown kind returns [] on the fake apiserver, so in
        every scenario that creates no SliceRequests this is a no-op."""
        from ..api.slicerequest import (
            KIND_SLICE_REQUEST,
            PHASE_PLACED,
            V1ALPHA1,
            SliceRequestSpec,
        )

        requests = sorted(self.client.list(V1ALPHA1, KIND_SLICE_REQUEST),
                          key=lambda r: (namespace_key(r), name_of(r)))
        if not requests and not self._placements:
            return
        owner_by_node: Dict[str, str] = {}
        live_keys = set()
        for req in requests:
            key = f"{namespace_key(req) or 'default'}/{name_of(req)}"
            live_keys.add(key)
            if get_nested(req, "status", "phase") != PHASE_PLACED:
                continue
            spec = SliceRequestSpec.from_obj(req)
            bound = tuple(sorted(
                get_nested(req, "status", "nodes", default=[]) or []))
            evictions = int(get_nested(req, "status", "evictions",
                                       default=0) or 0)
            for node_name in bound:
                prior = owner_by_node.get(node_name)
                if prior is not None:
                    self.record(
                        "placement-sound", step,
                        f"node {node_name} double-booked by {prior} "
                        f"and {key}")
                owner_by_node[node_name] = key
                node = nodes.get(node_name)
                if node is None:
                    # legal mid-storm (NODE_REMOVE outruns the eviction
                    # path); a hole after settling is a lost drain
                    if settled:
                        self.record(
                            "placement-sound", step,
                            f"{key}: bound node {node_name} does not "
                            f"exist after settling")
                    continue
                if spec.accelerator and labels_of(node).get(
                        L.GKE_TPU_ACCELERATOR) != spec.accelerator:
                    self.record(
                        "placement-sound", step,
                        f"{key}: node {node_name} violates accelerator "
                        f"pin {spec.accelerator!r}")
                if settled:
                    lease = (get_nested(node, "metadata", "annotations",
                                        default={}) or {}).get(L.PLACED_BY)
                    if lease != key:
                        self.record(
                            "placement-sound", step,
                            f"{key}: node {node_name} lease is {lease!r} "
                            f"after settling, want {key!r}")
            migrations = int(get_nested(req, "status", "migrations",
                                        default=0) or 0)
            prev = self._placements.get(key)
            if prev is not None and bound != prev[0] \
                    and evictions <= prev[1] and migrations <= prev[2]:
                self.record(
                    "placement-stable", step,
                    f"{key}: bound nodes {list(prev[0])} -> {list(bound)} "
                    f"without status.evictions "
                    f"({prev[1]} -> {evictions}) or status.migrations "
                    f"({prev[2]} -> {migrations}) incrementing")
            self._placements[key] = (bound, evictions, migrations)
        if settled:
            for node_name in sorted(nodes):
                lease = (get_nested(nodes[node_name], "metadata",
                                    "annotations", default={})
                         or {}).get(L.PLACED_BY)
                if lease and owner_by_node.get(node_name) != lease:
                    self.record(
                        "placement-sound", step,
                        f"node {node_name}: orphan placement lease "
                        f"{lease!r} after settling")
        # deleted requests stop being tracked (their leases were audited
        # above while they lived); a namesake re-create starts fresh
        for key in [k for k in self._placements if k not in live_keys]:
            del self._placements[key]

    # -- elastic no-lost-work ----------------------------------------------

    def _check_work(self, step: int) -> None:
        """no-lost-work (see module docstring). An ack is written only
        after the checkpoint it names is finalized, and retention never
        prunes past the newest finalized step, so a regression here means
        acknowledged training work genuinely evaporated."""
        from ..api.slicerequest import KIND_SLICE_REQUEST, V1ALPHA1

        requests = self.client.list(V1ALPHA1, KIND_SLICE_REQUEST)
        if not requests and not self._work:
            return
        live = set()
        for req in sorted(requests, key=name_of):
            key = f"{namespace_key(req) or 'default'}/{name_of(req)}"
            live.add(key)
            mig = get_nested(req, "status", "migration", default={}) or {}
            anns = get_nested(req, "metadata", "annotations",
                              default={}) or {}
            acks = []
            for raw in (mig.get("ackedStep"),
                        anns.get(L.SLICE_INTENT_ACK)):
                try:
                    if raw is not None:
                        acks.append(int(raw))
                except (TypeError, ValueError):
                    pass
            high, prev_restored = self._work.get(key, (None, None))
            if acks and high is not None and max(acks) < high:
                self.record(
                    "no-lost-work", step,
                    f"{key}: acked step regressed {high} -> {max(acks)}")
            raw_restored = mig.get("restoredStep")
            try:
                restored = (int(raw_restored)
                            if raw_restored is not None else None)
            except (TypeError, ValueError):
                restored = None
            if restored is not None and restored != prev_restored \
                    and high is not None and restored < high:
                self.record(
                    "no-lost-work", step,
                    f"{key}: restored step {restored} below the acked "
                    f"high-water mark {high}")
            candidates = acks if high is None else acks + [high]
            self._work[key] = (max(candidates) if candidates else None,
                               restored)
        # deleted requests stop being tracked; their durability promise
        # died with them (a namesake re-create starts at step 0 legally)
        for key in [k for k in self._work if k not in live]:
            del self._work[key]

    # -- DAG dependency order ----------------------------------------------

    def _check_dag(self, step: int) -> None:
        """No state starts before its requires() finished, per pass.

        Journal entries are recorded at state *completion* (the scheduler
        joins each wave before the next draws start sequences), so by the
        time a dependent's entry exists, every prerequisite's entry from
        the same pass exists too — a missing or later-finishing
        prerequisite is a genuine ordering violation, not a drain
        artifact."""
        if self.journal is None:
            return
        entries = self.journal.drain()
        for e in entries:
            self._dag_done.setdefault(e.pass_id, {})[e.state] = e.done_seq
        for e in entries:
            done = self._dag_done.get(e.pass_id, {})
            for req in e.requires:
                done_seq = done.get(req)
                if done_seq is None or done_seq > e.start_seq:
                    self.record(
                        "dag-order", step,
                        f"pass {e.pass_id}: {e.state} started (seq "
                        f"{e.start_seq}) before required state {req} "
                        f"finished (seq {done_seq})")
        # old passes can never gain new entries; keep the map bounded
        if entries:
            newest = max(e.pass_id for e in entries)
            for pid in [p for p in self._dag_done if p < newest - 4]:
                del self._dag_done[pid]

    # -- cache coherence ----------------------------------------------------

    def _authoritative_rvs(self, api_version: str,
                           kind: str) -> Dict[tuple, str]:
        return {(namespace_key(obj), name_of(obj)):
                get_nested(obj, "metadata", "resourceVersion")
                for obj in self.client.list(api_version, kind)}

    def _check_cache(self, step: int, settled: bool) -> None:
        if self.cache is None:
            return
        for api_version, kind in self.cache.cached_kinds():
            cached = self.cache.store_snapshot(api_version, kind)
            auth = self._authoritative_rvs(api_version, kind)
            for key, rv in sorted(cached.items()):
                want = auth.get(key)
                if want is not None:
                    try:
                        ahead = int(rv) > int(want)
                    except (TypeError, ValueError):
                        ahead = False
                    if ahead:
                        self.record(
                            "cache-staleness", step,
                            f"{kind} {key[0]}/{key[1]}: cache rv {rv} is "
                            f"AHEAD of apiserver rv {want}")
                    elif settled and rv != want:
                        self.record(
                            "cache-staleness", step,
                            f"{kind} {key[0]}/{key[1]}: settled cache rv "
                            f"{rv} != apiserver rv {want}")
                elif settled:
                    self.record(
                        "cache-staleness", step,
                        f"{kind} {key[0]}/{key[1]}: phantom cache entry "
                        f"(rv {rv}) for an object the apiserver deleted")
            if settled:
                for key in sorted(set(auth) - set(cached)):
                    self.record(
                        "cache-staleness", step,
                        f"{kind} {key[0]}/{key[1]}: missing from cache "
                        f"after settling (apiserver rv {auth[key]})")

    def _check_rv(self, step: int, nodes: Dict[str, dict]) -> None:
        tracked = list(self.client.list(V1, KIND_CLUSTER_POLICY))
        tracked += list(nodes.values())
        tracked += self.client.list(
            "apps/v1", "DaemonSet", ListOptions(namespace=self.namespace))
        seen = set()
        for obj in tracked:
            key = (obj.get("kind", ""), namespace_key(obj), name_of(obj))
            seen.add(key)
            try:
                rv = int(get_nested(obj, "metadata", "resourceVersion"))
            except (TypeError, ValueError):
                continue
            last = self._last_rv.get(key)
            if last is not None and rv < last:
                self.record("rv-regress", step,
                            f"{key[0]} {key[2]}: resourceVersion went "
                            f"{last} -> {rv}")
            self._last_rv[key] = rv
        # deleted objects stop being tracked; a re-created namesake gets a
        # fresh (higher, globally monotonic) RV anyway
        for key in [k for k in self._last_rv if k not in seen]:
            del self._last_rv[key]

    # -- upgrade FSM monotonicity ------------------------------------------

    @staticmethod
    def _units(nodes: Dict[str, dict]) -> List[List[str]]:
        """The upgrade controller's own unit partition (multi-host slices
        move as one unit; everything else is a singleton) — recomputed
        here so the invariant judges the controller by its own grouping."""
        from ..state.nodepool import get_node_pools, slices_of

        units: List[List[str]] = []
        grouped = set()
        for pool in get_node_pools(list(nodes.values())):
            if pool.multi_host:
                for _, members in sorted(slices_of(pool, nodes).items()):
                    units.append(sorted(members))
            else:
                for node_name in pool.nodes:
                    units.append([node_name])
            grouped.update(pool.nodes)
        for name in sorted(set(nodes) - grouped):
            units.append([name])
        units.sort(key=lambda u: u[0])
        return units

    @staticmethod
    def _unit_state(members: List[str],
                    nodes: Dict[str, dict]) -> Optional[str]:
        states = [labels_of(nodes[m]).get(L.UPGRADE_STATE) for m in members]
        if any(s == STATE_FAILED for s in states):
            return STATE_FAILED
        present = [s for s in states if s in _STAGE_ORDER]
        if not present:
            return None
        return min(present, key=_STAGE_ORDER.index)

    @staticmethod
    def _legal_transition(prev: Optional[str], new: Optional[str]) -> bool:
        if prev is None or new is None or prev == new:
            return True
        if new == STATE_FAILED:
            return True  # any stage may fail
        # backoff retry (from failed) and fresh rollout (from done) both
        # re-enter at upgrade-required, but the controller advances
        # multiple safe stages per pass while this checker samples once
        # per step — any stage downstream of the re-entry point can be
        # the first one observed (e.g. failed -> validation-required
        # when the retried unit's drain is instantly clean)
        if prev in (STATE_FAILED, STATE_DONE):
            return new in _STAGE_ORDER
        if prev in _STAGE_ORDER and new in _STAGE_ORDER:
            return _STAGE_ORDER.index(new) >= _STAGE_ORDER.index(prev)
        return True  # unknown label value: not this invariant's problem

    def _check_fsm(self, step: int, nodes: Dict[str, dict]) -> None:
        seen = set()
        for members in self._units(nodes):
            key = tuple(members)
            seen.add(key)
            new = self._unit_state(members, nodes)
            prev = self._unit_states.get(key)
            if not self._legal_transition(prev, new):
                self.record("fsm-monotonic", step,
                            f"unit [{members[0]}+{len(members) - 1}]: "
                            f"{prev} -> {new}")
            self._unit_states[key] = new
        # churned units (membership changed) restart with no history —
        # a different member set is a different unit, not a regression
        for key in [k for k in self._unit_states if k not in seen]:
            del self._unit_states[key]

    def _check_budget(self, step: int, nodes: Dict[str, dict]) -> None:
        crs = self.client.list(V1, KIND_CLUSTER_POLICY)
        if not crs:
            return
        crs.sort(key=lambda c: (
            get_nested(c, "metadata", "creationTimestamp", default=""),
            name_of(c)))
        raw = get_nested(crs[0], "spec", "upgradePolicy",
                         "maxParallelUpgrades")
        budget = max(1, raw or 1)  # the controller's own default
        in_progress = sum(
            1 for members in self._units(nodes)
            if self._unit_state(members, nodes) in IN_PROGRESS_STATES)
        if in_progress > budget:
            self.record("upgrade-budget", step,
                        f"{in_progress} upgrade units in progress, "
                        f"budget is {budget}")

    # -- settled-only checks ------------------------------------------------

    def check_settled(self, step: int) -> None:
        """Gauge/status consistency, valid only once faults have stopped
        and the cluster has had time to settle: mid-storm a reconcile can
        legally set the gauges and then lose the CR status write to an
        injected 409."""
        from ..controllers.slices import MAX_ROWS, slice_status

        rows = slice_status(self.client, self.namespace)
        total = REGISTRY.get_sample_value("tpu_operator_slices_total")
        validated = REGISTRY.get_sample_value("tpu_operator_slices_validated")
        want_total = float(len(rows))
        want_validated = float(sum(1 for r in rows if r["validated"]))
        if total != want_total or validated != want_validated:
            self.record("gauge-consistency", step,
                        f"slice gauges ({total}, {validated}) != "
                        f"recomputed ({want_total}, {want_validated})")
        crs = self.client.list(V1, KIND_CLUSTER_POLICY)
        for cr in crs:
            if get_nested(cr, "status", "state") != "ready":
                continue
            cr_rows = get_nested(cr, "status", "slices", default=[]) or []
            if cr_rows != rows[:MAX_ROWS]:
                self.record("gauge-consistency", step,
                            f"policy {name_of(cr)}: status.slices[] "
                            f"({len(cr_rows)} rows) disagrees with a fresh "
                            f"slice_status ({len(rows)} rows)")
        self._check_cache(step, settled=True)
        if self.cache is not None and getattr(self.cache, "degraded",
                                              False):
            # a healed apiserver must let the breaker close again —
            # settling while still serving stale reads is a stuck exit
            self.record("cache-staleness", step,
                        "cache still in degraded mode after settling "
                        f"(staleness {self.cache.staleness_s():.1f}s)")
        self._check_dag(step)
        nodes = {name_of(n): n for n in self.client.list("v1", "Node")}
        self._check_placement(step, nodes, settled=True)
        self._check_work(step)
        self._check_telemetry(step, nodes)
        self._check_admission(step)
        self._check_index(step, nodes)


class CrossCellWorkChecker:
    """Federation invariants over N cells (chaos/federation.py):

    - **no-lost-work-cross-cell**: per request key, the acked
      checkpoint high-water (max of ``status.migration.ackedStep``,
      ``status.progress.checkpointedStep`` and the intent-ack
      annotation, observed across EVERY cell) must never regress, and
      any observed ``restoredStep`` must be at or above it — a restore
      below the high-water after a hop means acked work evaporated in
      transit between clusters.
    - **single-binding**: a request Placed in more than one cell at the
      same observation, excluding the source copy of an in-flight
      outbound handoff (it carries ``migration.toCell``; the window
      between the destination's bind and the source's retirement is the
      handshake working as designed, not a double-spend).
    - **no-route-to-open** is recorded by the runner via :meth:`record`
      — only the decision site knows the breaker state at decision
      time.

    Observes each cell's RAW client (never the chaos-wrapped one): the
    auditor sees ground truth even while the global plane is
    partitioned away from it.
    """

    def __init__(self, namespace: str = "default"):
        self.namespace = namespace
        self.violations: List[Violation] = []
        self._high: Dict[str, int] = {}
        # last restoredStep judged per key (judge each restore once)
        self._judged: Dict[str, int] = {}

    def record(self, invariant: str, step: int, detail: str) -> None:
        self.violations.append(Violation(invariant, step, detail))
        OPERATOR_METRICS.chaos_invariant_violations.labels(
            invariant=invariant).inc()

    def to_list(self) -> List[dict]:
        return [v.to_dict() for v in self.violations]

    @property
    def acked_high_water(self) -> Dict[str, int]:
        return dict(self._high)

    def observe(self, step: int, cells: Dict[str, Client]) -> None:
        from ..api import labels as L
        from ..api.slicerequest import KIND_SLICE_REQUEST, V1ALPHA1

        placed_in: Dict[str, List[str]] = {}
        for cell_name in sorted(cells):
            client = cells[cell_name]
            for cr in client.list(V1ALPHA1, KIND_SLICE_REQUEST,
                                  ListOptions(namespace=self.namespace)):
                ns = get_nested(cr, "metadata", "namespace") or "default"
                key = f"{ns}/{name_of(cr)}"
                mig = get_nested(cr, "status", "migration",
                                 default={}) or {}
                # the high-water is built from ACK points only — the
                # steps a workload declared durably checkpointed for a
                # handoff. The live checkpointedStep is deliberately
                # excluded: a resumed twin trains past its restore
                # point immediately, and holding yesterday's
                # restoredStep against today's progress would flag the
                # recovery working as designed.
                acked = [mig.get("ackedStep"),
                         (get_nested(cr, "metadata", "annotations",
                                     default={}) or {}).get(
                             L.SLICE_INTENT_ACK)]
                for val in acked:
                    try:
                        val = int(val)
                    except (TypeError, ValueError):
                        continue
                    if val > self._high.get(key, -1):
                        self._high[key] = val
                restored = mig.get("restoredStep")
                try:
                    restored = int(restored)
                except (TypeError, ValueError):
                    restored = None
                # judge each restore once, when it appears (or moves):
                # the marker is historical and must not be re-tried
                # against high-waters acked after it
                if restored is not None \
                        and self._judged.get(key) != restored:
                    self._judged[key] = restored
                    if restored < self._high.get(key, -1):
                        self.record(
                            "no-lost-work-cross-cell", step,
                            f"{key} restored at step {restored} in "
                            f"{cell_name}, below the acked high-water "
                            f"{self._high[key]}")
                if get_nested(cr, "status", "phase") == "Placed" \
                        and not mig.get("toCell"):
                    placed_in.setdefault(key, []).append(cell_name)
        for key, where in sorted(placed_in.items()):
            if len(where) > 1:
                self.record(
                    "single-binding", step,
                    f"{key} Placed in {len(where)} cells at once: "
                    f"{sorted(where)}")


def namespace_key(obj: dict) -> str:
    return get_nested(obj, "metadata", "namespace", default="") or ""


def canonical_settled_state(client: Client, namespace: str) -> dict:
    """The restart-coherent invariant's comparison object: a canonical,
    clock-free projection of everything the operator owes the user at
    settle — which requests run, at what size, with sound leases, on a
    converged fleet. A crashed-and-restored run must produce this dict
    byte-for-byte equal (via its sorted-JSON digest) to a never-crashed
    run of the same seed.

    Deliberately excluded: resourceVersions and write counts (a restart
    legally re-writes), eviction/migration tallies and exact node
    assignments (a crash may legally shift WHICH equivalent nodes serve
    a slice — placement-sound and no-lost-work hold those paths to
    account), and requeue/backoff bookkeeping."""
    from ..api.slicerequest import (
        KIND_SLICE_REQUEST,
        MIG_TERMINAL,
        V1ALPHA1,
        SliceRequestSpec,
    )

    nodes = {name_of(n): n for n in client.list("v1", "Node")}
    requests = sorted(client.list(V1ALPHA1, KIND_SLICE_REQUEST),
                      key=lambda r: (namespace_key(r), name_of(r)))
    rows = []
    owners = set()
    for req in requests:
        key = f"{namespace_key(req) or 'default'}/{name_of(req)}"
        owners.add(key)
        bound = sorted(get_nested(req, "status", "nodes",
                                  default=[]) or [])
        sound = True
        for node_name in bound:
            node = nodes.get(node_name)
            lease = (get_nested(node, "metadata", "annotations",
                                default={}) or {}).get(L.PLACED_BY) \
                if node is not None else None
            if lease != key:
                sound = False
        rows.append({
            "name": f"{namespace_key(req)}/{name_of(req)}",
            "phase": get_nested(req, "status", "phase") or "",
            "chips": SliceRequestSpec.from_obj(req).chips_needed(),
            "nodes_bound": len(bound),
            "leases_sound": sound,
            "migration_terminal":
                (get_nested(req, "status", "migration", "phase") or "")
                in MIG_TERMINAL,
        })
    orphan_leases = 0
    tpu_nodes = ready = rolled = 0
    for node_name in sorted(nodes):
        node = nodes[node_name]
        lease = (get_nested(node, "metadata", "annotations",
                            default={}) or {}).get(L.PLACED_BY)
        if lease and lease not in owners:
            orphan_leases += 1
        if not labels_of(node).get(L.GKE_TPU_ACCELERATOR):
            continue
        tpu_nodes += 1
        conds = get_nested(node, "status", "conditions",
                           default=[]) or []
        if any(c.get("type") == "Ready" and c.get("status") == "True"
               for c in conds):
            ready += 1
        if labels_of(node).get(L.UPGRADE_STATE) in (None, STATE_DONE):
            rolled += 1
    crs = client.list(V1, KIND_CLUSTER_POLICY)
    return {
        "requests": rows,
        "fleet": {"tpu_nodes": tpu_nodes, "ready": ready,
                  "rolled": rolled, "orphan_leases": orphan_leases},
        "policy_ready": bool(crs) and all(
            get_nested(cr, "status", "state") == "ready" for cr in crs),
    }


def settled_state_digest(state: dict) -> str:
    """sha256 over the canonical sorted-JSON serialization — the byte
    identity the restart-coherent invariant compares."""
    import hashlib
    import json

    blob = json.dumps(state, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()
