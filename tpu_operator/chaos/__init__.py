"""Chaos plane: deterministic fault injection + cluster invariant
checking for the control plane.

Three pieces (ISSUE 1 tentpole):

- ``faults``: a seeded :class:`FaultPlan` (RNG -> reproducible fault
  schedule) and a :class:`ChaosClient` wrapper that injects apiserver
  faults (409 storms, 429 Retry-After, transient 5xx, latency, dropped
  watch streams) into any :class:`~tpu_operator.runtime.client.Client`.
- ``invariants``: an :class:`InvariantChecker` asserted continuously
  while the controllers run under fire.
- ``runner``: named scenarios against the mock cluster, emitting a
  deterministic JSON verdict (the ``tpuop-chaos`` CLI front-end).
"""

from .faults import ChaosClient, Fault, FaultPlan, VirtualClock  # noqa: F401
from .invariants import InvariantChecker, Violation  # noqa: F401
from .runner import SCENARIOS, run_scenario  # noqa: F401
