"""Federation chaos: N cells, one router, seeded partitions.

Runs the federation plane (federation/ + runtime/multicell.py) the way
chaos/runner.py runs a single cell: a deterministic synchronous loop
over a virtual clock, a seeded :class:`FaultPlan`, invariant checkers
folding every observation, and a JSON verdict that is byte-identical
per seed. Three scenarios:

- ``cell-partition`` — one cell drops off the global plane; the breaker
  must open (no request ever routed to an Open cell), bound slices ride
  out the window untouched, and past the condemnation horizon they
  migrate cross-cell with no acked work lost. A router crash lands
  mid-window and the rebuilt-from-snapshot router must carry on
  (restart-coherent: the crash-stripped rerun settles byte-identically).
- ``stale-digest`` — a cell stays reachable but its digest publisher
  wedges; the router must age-discount the frozen digest instead of
  trusting its last words.
- ``split-brain-router`` — a shadow router forked from the primary's
  snapshot receives the same digests in seeded-permuted order; every
  decision is compared, and any divergence is a violation (arrival-
  order independence, run as chaos).

Each cell's own control plane (placement reconciler, workload shims)
talks to its apiserver directly — a partition cuts the GLOBAL plane off
from the cell, not the cell off from itself. Only the harness's view
(:class:`_PartitionGate`) fails, which is exactly the asymmetry that
makes "partition is not death" worth testing.
"""

from __future__ import annotations

import json
import logging
import random
from dataclasses import asdict
from typing import Dict, List, Optional

from ..api import labels as L
from ..api.slicerequest import (
    KIND_SLICE_REQUEST,
    MIG_TERMINAL,
    PHASE_PLACED,
    PHASE_UNSCHEDULABLE,
    V1ALPHA1,
)
from ..benchmarks.controlplane import build_cluster
from ..controllers.placement_controller import PlacementReconciler
from ..controllers.slices import migration_of, request_key
from ..federation.digest import cell_digest
from ..federation.router import CELL_OPEN, GlobalRouter
from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime import Request
from ..runtime.client import (
    ApiError,
    Client,
    ListOptions,
    ServerUnavailableError,
)
from ..runtime.fake import simulate_kubelet
from ..runtime.multicell import Cell, MultiCellHarness
from ..runtime.objects import get_nested, name_of, namespace_of
from ..runtime.timeline import TIMELINE
from ..workloads.elastic import ElasticWorkload
from .faults import (
    CELL_PARTITION_END,
    CELL_PARTITION_START,
    DIGEST_STALE_END,
    DIGEST_STALE_START,
    ROUTER_CRASH,
    ROUTER_SPLIT,
    SLICE_REQUEST,
    FaultPlan,
    VirtualClock,
)
from .invariants import CrossCellWorkChecker, settled_state_digest

logger = logging.getLogger("tpu_operator.chaos.federation")

NAMESPACE = "default"
N_CELLS = 4
STEP_DT = 20.0
DEFAULT_STEPS = 12
SOAK_PASS_BUDGET = 80

#: Router tuning for the chaos timescale (STEP_DT-second ticks): two
#: failed contacts open a breaker, the condemnation horizon is three
#: ticks, and the first backoff probe lands well after the horizon — so
#: a partition window reliably walks a cell through Suspect → Open →
#: condemned → (heal) → probed-Healthy inside one run.
ROUTER_TUNING = dict(
    failure_threshold=2,
    probe_base_s=6 * STEP_DT,
    probe_cap_s=30 * STEP_DT,
    digest_half_life_s=2 * STEP_DT,
    condemnation_horizon_s=3 * STEP_DT,
)


class _PartitionGate(Client):
    """The global plane's view of one cell's apiserver: a pass-through
    that raises 503 on every verb while the cell is partitioned. The
    cell's own reconciler and shims hold the raw client — only the
    federation harness looks through this gate."""

    def __init__(self, inner: Client):
        self.inner = inner
        self.blocked = False

    def _gate(self) -> None:
        if self.blocked:
            raise ServerUnavailableError(
                "cell partitioned from the global plane")

    def get(self, api_version, kind, name, namespace=None):
        self._gate()
        return self.inner.get(api_version, kind, name, namespace)

    def list(self, api_version, kind, opts=None):
        self._gate()
        return self.inner.list(api_version, kind, opts)

    def create(self, obj):
        self._gate()
        return self.inner.create(obj)

    def update(self, obj):
        self._gate()
        return self.inner.update(obj)

    def update_status(self, obj):
        self._gate()
        return self.inner.update_status(obj)

    def patch(self, api_version, kind, name, patch, namespace=None):
        self._gate()
        return self.inner.patch(api_version, kind, name, patch, namespace)

    def delete(self, api_version, kind, name, namespace=None):
        self._gate()
        return self.inner.delete(api_version, kind, name, namespace)

    def watch(self, api_version, kind, handler, since_rv=None):
        self._gate()
        return self.inner.watch(api_version, kind, handler, since_rv)


class _RouterAudit:
    """Wraps the primary router (and the split-brain shadow, when one is
    forked) so every decision is audited at the decision site: a route
    onto an Open cell or a primary/shadow divergence is recorded as a
    violation the moment it happens, with the breaker state in hand."""

    def __init__(self, primary: GlobalRouter,
                 checker: CrossCellWorkChecker):
        self.primary = primary
        self.shadow: Optional[GlobalRouter] = None
        self.checker = checker
        self.step = 0

    def route(self, chips, generation=None, locality=None):
        decision = self.primary.route(chips, generation=generation,
                                      locality=locality)
        if self.shadow is not None:
            mirror = self.shadow.route(chips, generation=generation,
                                       locality=locality)
            if mirror != decision:
                self.checker.record(
                    "split-brain-router", self.step,
                    f"primary decided {decision}, shadow (permuted "
                    f"digest order) decided {mirror}")
        if decision is not None and self.primary.cells[
                decision["cell"]].state == CELL_OPEN:
            self.checker.record(
                "no-route-to-open", self.step,
                f"routed {chips} chips to Open cell "
                f"{decision['cell']}")
        return decision

    def record_failure(self, cell: str) -> None:
        self.primary.record_failure(cell)
        if self.shadow is not None:
            self.shadow.record_failure(cell)

    def record_success(self, cell: str) -> None:
        self.primary.record_success(cell)
        if self.shadow is not None:
            self.shadow.record_success(cell)

    def __getattr__(self, name):
        return getattr(self.primary, name)


def _record(injected: Dict[str, int], kind: str) -> None:
    injected[kind] = injected.get(kind, 0) + 1
    OPERATOR_METRICS.chaos_faults_injected.labels(kind=kind).inc()


def _settled_state(fakes: Dict[str, Client], pending: list) -> dict:
    """The restart-coherent comparison object: where every request
    ended up, at what size, in which phase — and nothing volatile
    (no step counters, no timestamps, no resourceVersions)."""
    cells: dict = {}
    for cell_name in sorted(fakes):
        rows = {}
        for cr in fakes[cell_name].list(
                V1ALPHA1, KIND_SLICE_REQUEST,
                ListOptions(namespace=NAMESPACE)):
            mig = migration_of(cr)
            rows[request_key(cr)] = {
                "phase": get_nested(cr, "status", "phase") or "Pending",
                "chips": get_nested(cr, "status", "chips", default=0)
                or 0,
                "nodes": sorted(get_nested(cr, "status", "nodes",
                                           default=[]) or []),
                "migration": mig.get("phase") or "",
                "from": mig.get("from") or "",
            }
        cells[cell_name] = rows
    return {"cells": cells,
            "unrouted": sorted(
                f"{namespace_of(cr) or 'default'}/{name_of(cr)}"
                for cr in pending)}


def run_federation_scenario(scenario: str, nodes: int = 100,
                            seed: int = 0,
                            steps: Optional[int] = None) -> dict:
    """Run one federation scenario and return its JSON verdict. Same
    contract as ``chaos.runner.run_scenario``: deterministic per
    (scenario, seed, nodes, steps), ``ok`` = converged with zero
    invariant violations."""
    from ..runtime.tracing import TRACER

    steps = int(steps or DEFAULT_STEPS)
    root = logging.getLogger("tpu_operator")
    prev_level = root.level
    root.setLevel(logging.CRITICAL)
    clock = VirtualClock()
    prev_tr = (TRACER.clock, TRACER.enabled)
    TRACER.reset(clock=clock, enabled=False)
    prev_tl = (TIMELINE.clock, TIMELINE.enabled)
    TIMELINE.reset(clock=clock, enabled=True)
    try:
        out = _run_impl(scenario, nodes, seed, steps, clock)
    finally:
        TRACER.reset(clock=prev_tr[0], enabled=prev_tr[1])
        TIMELINE.reset(clock=prev_tl[0], enabled=prev_tl[1])
        root.setLevel(prev_level)
    if scenario == "cell-partition":
        # restart-coherent: the same seed with ONLY the router crash
        # stripped must settle byte-identically — a crash changing
        # which cell any slice ended up in is the bug class this pins
        clock2 = VirtualClock()
        TRACER.reset(clock=clock2, enabled=False)
        TIMELINE.reset(clock=clock2, enabled=True)
        root.setLevel(logging.CRITICAL)
        try:
            base = _run_impl(scenario, nodes, seed, steps, clock2,
                             strip_crashes=True)
        finally:
            TRACER.reset(clock=prev_tr[0], enabled=prev_tr[1])
            TIMELINE.reset(clock=prev_tl[0], enabled=prev_tl[1])
            root.setLevel(prev_level)
        coherent = (base["converged"]
                    and base["settled_digest"] == out["settled_digest"])
        out["restart_coherent"] = {
            "ok": bool(out["converged"] and coherent),
            "digest": out["settled_digest"],
            "baseline_digest": base["settled_digest"],
            "baseline_converged": base["converged"],
        }
        if not (out["converged"] and coherent):
            out["violations"].append({
                "invariant": "restart-coherent", "step": steps,
                "detail": "crash-stripped rerun settled differently"})
            out["ok"] = False
    return out


def _run_impl(scenario: str, nodes: int, seed: int, steps: int,
              clock: VirtualClock, strip_crashes: bool = False) -> dict:
    per_cell = max(8, nodes // N_CELLS)
    cell_names = [f"cell-{i}" for i in range(N_CELLS)]
    fakes: Dict[str, Client] = {}
    gates: Dict[str, _PartitionGate] = {}
    cells: Dict[str, Cell] = {}
    recons: Dict[str, PlacementReconciler] = {}
    for name in cell_names:
        fake = build_cluster(n_tpu=per_cell)
        fakes[name] = fake
        gates[name] = _PartitionGate(fake)
        recons[name] = PlacementReconciler(
            fake, namespace=NAMESPACE, preemption=False, now=clock,
            cell=name)
        cells[name] = Cell(name, gates[name], reconciler=recons[name],
                           namespace=NAMESPACE)

    checker = CrossCellWorkChecker(namespace=NAMESPACE)
    audit = _RouterAudit(
        GlobalRouter(cell_names, now=clock, **ROUTER_TUNING), checker)
    harness = MultiCellHarness(
        audit, cells, now=clock,
        shim_factory=lambda cell, name, ns, store: ElasticWorkload(
            fakes[cell.name], name, ns, clock=clock, store=store))

    plan = FaultPlan.build(scenario, seed, cell_names, steps)
    injected: Dict[str, int] = {}
    stale: set = set()
    shadow_rng = random.Random(f"split:{scenario}:{seed}")
    last_snap: Optional[dict] = None
    router_crashes = 0

    def contact_pass() -> None:
        tick_digests: List[dict] = []
        for name in audit.primary.cells_to_contact():
            gate = gates[name]
            try:
                # the list IS the probe: a partitioned cell fails here
                gate.list("v1", "Node")
            except ApiError:
                audit.record_failure(name)
                continue
            audit.record_success(name)
            if name in stale:
                digest = harness._last_digest.get(name)  # frozen
            else:
                harness._seq[name] += 1
                digest = cell_digest(cells[name].fleet_index(), name,
                                     harness._seq[name], clock())
                harness._last_digest[name] = digest
            if digest is not None:
                audit.primary.observe_digest(digest)
                tick_digests.append(digest)
        if audit.shadow is not None:
            # the split-brain half: same digest SET, permuted arrival
            permuted = list(tick_digests)
            shadow_rng.shuffle(permuted)
            for digest in permuted:
                audit.shadow.observe_digest(digest)
        audit.primary.export_metrics()

    harness._last_digest = {}

    def cell_pass() -> None:
        for name in sorted(cells):
            fake, recon, cell = fakes[name], recons[name], cells[name]
            for cr in sorted(fake.list(V1ALPHA1, KIND_SLICE_REQUEST,
                                       ListOptions(namespace=NAMESPACE)),
                             key=request_key):
                recon.reconcile(Request(name=name_of(cr),
                                        namespace=namespace_of(cr)))
            simulate_kubelet(fake, ready=True)
            # adopt shims for freshly placed elastic requests (unless
            # the key's shim already lives somewhere — a mid-migration
            # twin must wait for the store to be carried over)
            owned = {k for c in cells.values() for k in c.shims}
            for cr in sorted(fake.list(V1ALPHA1, KIND_SLICE_REQUEST,
                                       ListOptions(namespace=NAMESPACE)),
                             key=request_key):
                key = request_key(cr)
                if (get_nested(cr, "status", "phase") == PHASE_PLACED
                        and name_of(cr).startswith("freq-")
                        and key not in owned):
                    cell.shims[key] = ElasticWorkload(
                        fake, name_of(cr), NAMESPACE, clock=clock)
                    owned.add(key)
        for name in sorted(cells):
            for key in sorted(cells[name].shims):
                cells[name].shims[key].tick()

    def apply_fault(fault) -> None:
        nonlocal last_snap, router_crashes
        if fault.kind == SLICE_REQUEST:
            req_name, _, affinity = fault.arg.partition("@")
            body = {
                "apiVersion": V1ALPHA1, "kind": KIND_SLICE_REQUEST,
                "metadata": {"name": req_name, "namespace": NAMESPACE},
                "spec": {"chips": int(fault.count)},
            }
            if affinity:
                body["metadata"]["annotations"] = {
                    L.CELL_AFFINITY: affinity}
            harness.submit(body)
            _record(injected, fault.kind)
        elif fault.kind == CELL_PARTITION_START:
            gates[fault.arg].blocked = True
            _record(injected, fault.kind)
        elif fault.kind == CELL_PARTITION_END:
            gates[fault.arg].blocked = False
            _record(injected, fault.kind)
        elif fault.kind == DIGEST_STALE_START:
            stale.add(fault.arg)
            _record(injected, fault.kind)
        elif fault.kind == DIGEST_STALE_END:
            stale.discard(fault.arg)
            _record(injected, fault.kind)
        elif fault.kind == ROUTER_CRASH:
            if strip_crashes:
                return
            router_crashes += 1
            _record(injected, fault.kind)
            # the router process dies; its successor warm-restores
            # breaker ledgers + digests from the durable snapshot and
            # re-derives in-flight migrations from the requests' own
            # status — nothing else survives
            audit.primary = GlobalRouter.restore(
                last_snap or {}, cell_names, now=clock, **ROUTER_TUNING)
            harness.recover_migrations()
        elif fault.kind == ROUTER_SPLIT:
            _record(injected, fault.kind)
            audit.shadow = GlobalRouter.restore(
                json.loads(json.dumps(audit.primary.snapshot())),
                cell_names, now=clock, **ROUTER_TUNING)

    def tick(step: int) -> None:
        audit.step = step
        contact_pass()
        harness.route_pass()
        cell_pass()
        harness.migration_pass()
        checker.observe(step, fakes)

    for step in range(steps):
        for fault in plan.for_step(step):
            apply_fault(fault)
        tick(step)
        # the durable router snapshot rides the end of every tick —
        # JSON-roundtripped so a crash restore sees exactly what a
        # process restart would read off disk
        last_snap = json.loads(json.dumps(audit.primary.snapshot(),
                                          sort_keys=True))
        clock.advance(STEP_DT)

    def converged() -> bool:
        if harness.pending or harness.migrations:
            return False
        for cell_name in sorted(fakes):
            for cr in fakes[cell_name].list(
                    V1ALPHA1, KIND_SLICE_REQUEST,
                    ListOptions(namespace=NAMESPACE)):
                if get_nested(cr, "status", "phase") not in (
                        PHASE_PLACED, PHASE_UNSCHEDULABLE):
                    return False
                if migration_of(cr).get("phase", "") not in MIG_TERMINAL:
                    return False
        return True

    soak = 0
    while not converged() and soak < SOAK_PASS_BUDGET:
        soak += 1
        tick(steps + soak - 1)
        last_snap = json.loads(json.dumps(audit.primary.snapshot(),
                                          sort_keys=True))
        clock.advance(STEP_DT)

    settled = _settled_state(fakes, harness.pending)
    is_converged = converged()
    cells_block = {}
    for name in sorted(cells):
        rows = [cr for cr in fakes[name].list(
            V1ALPHA1, KIND_SLICE_REQUEST,
            ListOptions(namespace=NAMESPACE))]
        cells_block[name] = {
            "nodes": per_cell,
            "requests": len(rows),
            "placed": sum(1 for cr in rows if get_nested(
                cr, "status", "phase") == PHASE_PLACED),
            "state": audit.primary.cells[name].state,
        }
    migrated_keys = sorted(
        k for cell in fakes.values()
        for cr in cell.list(V1ALPHA1, KIND_SLICE_REQUEST,
                            ListOptions(namespace=NAMESPACE))
        if str(migration_of(cr).get("from") or "").startswith("cell/")
        for k in (request_key(cr),))
    out = {
        "scenario": scenario,
        "seed": seed,
        "nodes": nodes,
        "steps": steps,
        "cells": cells_block,
        "schedule": [asdict(f) for f in plan.faults],
        "faults_injected": dict(sorted(injected.items())),
        "converged": is_converged,
        "soak_passes": soak,
        "convergence_virtual_s": clock.t,
        "router": audit.primary.report(),
        "router_crashes": router_crashes,
        "cross_cell_migrated": migrated_keys,
        "timelines": {k: TIMELINE.timeline("SliceRequest", k)
                      for k in migrated_keys},
        "violations": checker.to_list(),
        "settled_state": settled,
        "settled_digest": settled_state_digest(settled),
    }
    out["ok"] = bool(is_converged and not out["violations"])
    return out
