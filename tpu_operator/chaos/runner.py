"""Named chaos scenarios against the mock cluster.

The runner is deliberately single-threaded: the production Manager's
worker threads would make fault-consumption order depend on the
scheduler, and a chaos verdict that can't be reproduced from (scenario,
seed) is a bug report nobody can act on. :class:`_SyncController`
re-uses the real ``setup_controller`` wiring — watches, predicates,
mappers — so the event plumbing under test is the production code, only
the thread is gone. Time is a :class:`~.faults.VirtualClock`: requeue
delays, FSM deadlines and injected latency all advance it, never the
wall clock, so a 100-node scenario runs in seconds and two runs with the
same seed emit byte-identical JSON.

Each step: apply the step's faults (apiserver faults arm the
ChaosClient; object faults mutate the world through the unwrapped fake),
drain both controllers, tick the fake kubelet, drain again, advance the
clock, then let the invariant checker observe. After the plan runs out,
the cluster must converge to all-Ready within the soak budget —
"eventual convergence once faults stop" is itself an invariant.
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Callable, Dict, Iterable, List, Optional

from ..api import labels as L
from ..api.clusterpolicy import KIND_CLUSTER_POLICY, V1, new_cluster_policy
from ..api.slicerequest import (
    KIND_SLICE_REQUEST,
    MIG_TERMINAL,
    PHASE_PLACED,
    PHASE_UNSCHEDULABLE,
    V1ALPHA1,
    SliceRequestSpec,
    new_slice_request,
)
from ..benchmarks.controlplane import build_cluster
from ..controllers.clusterpolicy_controller import ClusterPolicyReconciler
from ..controllers.placement_controller import PlacementReconciler
from ..controllers.telemetry_controller import TelemetryReconciler
from ..metrics.fleet import FleetTelemetry
from ..controllers.upgrade_controller import (
    STATE_DONE,
    UpgradeReconciler,
    desired_revision,
)
from ..runtime import (
    LANE_BULK,
    LANE_HEALTH,
    LANES,
    CachedClient,
    FakeClient,
    Request,
)
from ..runtime.client import (
    ApiError,
    ConflictError,
    ListOptions,
    NotFoundError,
)
from ..runtime.fake import simulate_kubelet
from ..runtime.manager import any_event, enqueue_object, shard_of
from ..runtime.timeline import TIMELINE
from ..runtime.tracing import TRACER
from ..runtime.workqueue import MAX_CAUSES, Cause
from ..runtime.objects import (
    annotations_of,
    get_nested,
    labels_of,
    name_of,
    namespace_of,
    set_nested,
    thaw_obj,
)
from ..workloads.elastic import ElasticWorkload
from ..runtime import snapshot as snapshot_mod
from .faults import (
    ANNOTATION_CLEAR,
    API_CONFLICT,
    API_LATENCY,
    API_THROTTLE,
    API_UNAVAILABLE,
    BROWNOUT_END,
    BROWNOUT_START,
    CHIP_LOSS,
    CHIP_RESTORE,
    DIGEST_DEGRADE,
    DIGEST_HEAL,
    DIGEST_SEED,
    MUTATE_POLICY,
    NODE_ADD,
    NODE_FLAP,
    NODE_HEAL,
    NODE_REMOVE,
    OPERAND_DRIFT,
    OPERATOR_CRASH,
    POD_CRASH,
    RESHARD_CRASH,
    SHARD_KILL,
    SLICE_REQUEST,
    SLICE_RESIZE,
    TRIGGER_ROLLOUT,
    WATCH_DROP,
    WORKLOAD_CRASH,
    ChaosClient,
    Fault,
    FaultPlan,
    VirtualClock,
)
from .invariants import (
    InvariantChecker,
    canonical_settled_state,
    settled_state_digest,
)

# federation scenarios run N cells under the global router; they have
# their own runner (chaos/federation.py) — run_scenario dispatches there
FEDERATION_SCENARIOS = ("cell-partition", "stale-digest",
                        "split-brain-router")

SCENARIOS = ("conflict-storm", "watch-flap", "node-churn",
             "upgrade-under-fire", "chip-loss", "operand-drift",
             "dag-race", "placement-contention", "placement-storm",
             "slice-migrate", "shard-failover", "operator-crash",
             "apiserver-brownout", "chip-degrade", "saturation-storm"
             ) + FEDERATION_SCENARIOS

# scenarios that run the placement controller (they create SliceRequests)
PLACEMENT_SCENARIOS = ("placement-contention", "placement-storm",
                       "slice-migrate", "operator-crash", "chip-degrade",
                       "saturation-storm")
# scenarios whose elastic requests get workload shims (the training
# jobs' half of the slice-intent protocol)
SHIM_SCENARIOS = ("slice-migrate", "operator-crash", "chip-degrade",
                  "saturation-storm")
# scenarios that crash the operator AND must reach the byte-identical
# canonical settled state as a never-crashed run of the same seed
RESTART_COHERENT_SCENARIOS = ("operator-crash", "saturation-storm")

# virtual deadlines for the slice-migrate scenario, sized in runner steps
# (STEP_DT each): long enough for the elastic handshake (~3 passes),
# short enough that a rigid request demonstrably times out into the
# hard-drain degradation inside the soak budget
MIGRATION_TIMEOUT_S = 60.0
RESIZE_TIMEOUT_VIRTUAL_S = 60.0

NAMESPACE = "tpu-operator"
POLICY = "tpu-cluster-policy"
STEP_DT = 20.0           # virtual seconds per runner step
DEFAULT_STEPS = 12
SETUP_PASS_BUDGET = 30   # fault-free passes to reach the baseline Ready
SOAK_PASS_BUDGET = 150   # post-fault passes before convergence fails
DRAIN_BUDGET = 500       # reconciles per drain — a backstop, not a knob
# reconciles each controller gets before an OPERATOR_CRASH fires: the
# process dies mid-pass with queues half-drained, not at a tick boundary
CRASH_PARTIAL_DRAIN = 6
RETRY_DELAY_S = 1.0      # virtual requeue delay after an injected failure
MAX_PARALLEL_UPGRADES = 8
FAILOVER_SHARDS = 4      # shard count for the shard-failover scenario
# the lane-priority invariant: no health-lane item may be dequeued having
# waited behind more than this many bulk reconciles
LANE_PRIORITY_BUDGET = 8


def _saturation_quota(n_nodes: int) -> dict:
    """The saturation-storm scenario's quota config, scaled to fleet
    size. ``prod`` carries the min-guarantee (the floor self-caps at
    live demand, so a generous value just means "rescue all of prod")
    and zero preempt tokens — the guaranteed class is itself
    preemption-exempt. The opportunists carry token budgets sized so
    the whole rescue fits without exhausting a window: budget
    EXHAUSTION mid-rescue would make the crashed run's outcome hinge
    on one lost tick of token accounting, and the restart-coherent
    check demands tick-for-tick-identical settled state."""
    return {"classes": [
        {"name": "prod", "weight": 6.0, "minChips": 2 * n_nodes,
         "starvationBoundSeconds": 240},
        {"name": "batch", "weight": 3.0, "preemptTokens": 16,
         "preemptWindowSeconds": 600},
        {"name": "research", "weight": 1.0,
         "maxChips": max(32, 2 * n_nodes), "preemptTokens": 16,
         "preemptWindowSeconds": 600},
    ]}


class _SyncController:
    """Single-threaded Controller stand-in: same watch/predicate/mapper
    registration surface, but reconciles run inline from :meth:`drain`
    and delayed requeues key off the virtual clock.

    Models the production Controller's fleet-scale queueing exactly:
    requests route to ``shards`` rendezvous-hashed queues (the same
    ``shard_of`` the Manager uses, so a kill moves only the dead shard's
    keys) and each shard holds per-lane FIFOs popped health > placement >
    bulk. ``shards=1`` is the default — scenarios that predate sharding
    keep one queue. The lane journal (``max_health_behind_bulk``) feeds
    the lane-priority invariant: how many bulk reconciles ran while the
    worst-served health item waited."""

    def __init__(self, reconciler, client, clock: VirtualClock,
                 shards: int = 1, name: str = ""):
        self.reconciler = reconciler
        self.client = client
        self.clock = clock
        self.name = name
        self.timeline_kind = getattr(reconciler, "primary_kind", None)
        self.shards = max(1, shards)
        self._live: List[int] = list(range(self.shards))
        self._queues: List[Dict[str, List[Request]]] = [
            {lane: [] for lane in LANES} for _ in range(self.shards)]
        self._lane_of: Dict[Request, str] = {}
        # cause provenance per queued key, same bounded-merge discipline
        # as the production WorkQueue — popped with the key at drain
        self._causes: Dict[Request, tuple] = {}
        self._delayed: Dict[Request, float] = {}
        self._last_seen: Dict[tuple, dict] = {}
        self.reconcile_errors = 0
        # lane-priority accounting: bulk reconciles completed while each
        # queued health item waited, and the worst case seen
        self._bulk_pops = 0
        self._health_marks: Dict[Request, int] = {}
        self.max_health_behind_bulk = 0
        self.keys_moved_on_failover = 0
        self._cancels: List[Callable] = []

    def watch(self, api_version: str, kind: str,
              predicate: Callable = any_event,
              mapper: Callable = enqueue_object,
              lane: Optional[str] = None) -> None:
        def handler(event):
            key = (api_version, kind, namespace_of(event.obj),
                   name_of(event.obj))
            old = self._last_seen.get(key)
            if event.type == "DELETED":
                self._last_seen.pop(key, None)
            else:
                self._last_seen[key] = event.obj
            try:
                if not predicate(event, old):
                    return
                cause = None
                if TRACER.enabled:
                    # watch delivery is synchronous from the writer, so
                    # the trace open on this thread IS the reconcile
                    # whose write fired the event — the causal link
                    tr = TRACER.current_trace()
                    cause = Cause(
                        reason=f"watch:{event.type}",
                        origin=f"{kind}/{name_of(event.obj)}",
                        trace_id=tr.seq if tr is not None else -1)
                for req in mapper(event):
                    self.add(req, lane=lane, cause=cause)
            except ApiError:
                # the mapper's LIST ate an armed fault; the per-tick
                # resync (and any relist) re-enqueues what this loses
                pass

        self._cancels.append(self.client.watch(api_version, kind, handler))

    def stop(self) -> None:
        """The process dies: watch subscriptions are torn down (the
        OPERATOR_CRASH teardown — queued keys, delayed requeues and lane
        state simply stop existing with this object)."""
        for cancel in self._cancels:
            cancel()
        self._cancels.clear()

    def _shard_for(self, request: Request) -> int:
        return shard_of(str(request), self._live)

    def _stamp_cause(self, request: Request, cause) -> None:
        if cause is None:
            return
        causes = (cause,) if isinstance(cause, Cause) else tuple(cause)
        cur = self._causes.get(request, ())
        for c in causes:
            if len(cur) >= MAX_CAUSES:
                break
            if c not in cur:
                cur = cur + (c,)
        if cur:
            self._causes[request] = cur

    def add(self, request: Request, lane: Optional[str] = None,
            cause=None) -> None:
        lane = lane if lane in LANES else LANE_BULK
        self._stamp_cause(request, cause)
        if (cause is not None and self.timeline_kind is not None
                and TIMELINE.enabled
                and self._lane_of.get(request) is None):
            # same per-object enqueue attribution the production
            # Controller.enqueue records: caused FRESH adds only — the
            # per-tick resync and coalesced duplicates would be noise
            TIMELINE.record(self.timeline_kind, str(request), "enqueue",
                            {"controller": self.name, "lane": lane},
                            causes=(cause,) if isinstance(cause, Cause)
                            else tuple(cause))
        cur = self._lane_of.get(request)
        if cur is not None:
            # already queued: promote to the higher-priority lane only
            if LANES.index(lane) < LANES.index(cur):
                shard = self._shard_for(request)
                self._queues[shard][cur].remove(request)
                self._queues[shard][lane].append(request)
                self._lane_of[request] = lane
                if lane == LANE_HEALTH:
                    self._health_marks.setdefault(request, self._bulk_pops)
            return
        self._queues[self._shard_for(request)][lane].append(request)
        self._lane_of[request] = lane
        if lane == LANE_HEALTH:
            self._health_marks.setdefault(request, self._bulk_pops)

    def kill_busiest(self, preferred: int) -> Optional[tuple]:
        """Kill the killable shard currently holding the most queued
        keys (ties: ``preferred`` if killable, else lowest id) — the
        adversary aims where it hurts. Deterministic given the queue
        state. Returns ``(shard, keys_moved)`` or None when no shard can
        die (single-shard controller)."""
        killable = self._live[1:]  # the first live shard always survives
        if not killable:
            return None
        depth = {s: sum(len(self._queues[s][lane]) for lane in LANES)
                 for s in killable}
        top = max(depth.values())
        candidates = sorted(s for s, d in depth.items() if d == top)
        victim = (preferred if preferred in candidates and top == 0
                  else candidates[0])
        return victim, self.kill_shard(victim) or 0

    def kill_shard(self, shard: int) -> Optional[int]:
        """Kill one shard's (virtual) worker group: remove it from the
        live set and rehash its queued keys onto the survivors, lanes
        preserved. Returns keys moved, or None when the kill is a no-op
        (unknown/dead shard, or it would take the last shard down)."""
        if shard not in self._live or len(self._live) == 1:
            return None
        self._live.remove(shard)
        dead = self._queues[shard]
        self._queues[shard] = {lane: [] for lane in LANES}
        moved = 0
        for lane in LANES:
            for req in dead[lane]:
                del self._lane_of[req]
                self.add(req, lane=lane,
                         cause=Cause(reason="failover-transfer",
                                     origin=f"{self.name}:shard{shard}"))
                moved += 1
        self.keys_moved_on_failover += moved
        return moved

    def _pop(self) -> Optional[Request]:
        # strict lane priority, shards visited in live order within a
        # lane — deterministic, and with shards=1 exactly the old FIFO
        # per lane
        for lane in LANES:
            for shard in self._live:
                queue = self._queues[shard][lane]
                if queue:
                    req = queue.pop(0)
                    del self._lane_of[req]
                    if lane == LANE_BULK:
                        self._bulk_pops += 1
                    elif lane == LANE_HEALTH:
                        behind = self._bulk_pops - self._health_marks.pop(
                            req, self._bulk_pops)
                        if behind > self.max_health_behind_bulk:
                            self.max_health_behind_bulk = behind
                    return req
        return None

    def _schedule(self, request: Request, due: float, cause=None) -> None:
        self._stamp_cause(request, cause)
        prev = self._delayed.get(request)
        self._delayed[request] = due if prev is None else min(prev, due)

    def _promote(self) -> None:
        for req in [r for r, t in self._delayed.items()
                    if t <= self.clock()]:
            del self._delayed[req]
            self.add(req)

    def drain(self, budget: int = DRAIN_BUDGET) -> int:
        done = 0
        self._promote()
        while done < budget:
            req = self._pop()
            if req is None:
                break
            done += 1
            causes = self._causes.pop(req, ())
            tr = None
            try:
                # open the root here (the reconciler's own wrapper nests
                # as a passthrough) so the popped causes ride the trace —
                # same dual-path treatment the production _worker gives
                with TRACER.trace(self.reconciler.name, str(req),
                                  causes=causes) as t:
                    tr = t
                    result = self.reconciler.reconcile(req)
            except ApiError:
                # an injected 409/429/5xx escaped the reconcile: retry
                # with a (virtual) delay, like the workqueue rate limiter
                self.reconcile_errors += 1
                self._schedule(req, self.clock() + RETRY_DELAY_S,
                               cause=Cause(
                                   reason="retry-backoff", origin=self.name,
                                   trace_id=tr.seq if tr else -1))
                continue
            if result and result.requeue_after > 0:
                self._schedule(req, self.clock() + result.requeue_after,
                               cause=Cause(
                                   reason="requeue-after", origin=self.name,
                                   trace_id=tr.seq if tr else -1))
            elif result and result.requeue:
                self.add(req, cause=Cause(
                    reason="requeue", origin=self.name,
                    trace_id=tr.seq if tr else -1))
            self._promote()
        return done


# -- object-level faults (adversary moves through the unwrapped fake) -------


def _mutate_cr(fake: FakeClient, mutate: Callable[[dict], None]) -> None:
    for _ in range(10):
        cr = fake.get_or_none(V1, KIND_CLUSTER_POLICY, POLICY)
        if cr is None:
            return
        cr = thaw_obj(cr)  # reads are frozen store snapshots
        mutate(cr)
        try:
            fake.update(cr)
            return
        except ConflictError:
            continue


def _set_node_ready(fake: FakeClient, name: str, ready: bool) -> bool:
    node = fake.get_or_none("v1", "Node", name)
    if node is None:
        return False
    node = thaw_obj(node)
    set_nested(node, [{"type": "Ready",
                       "status": "True" if ready else "False"}],
               "status", "conditions")
    fake.update_status(node)
    return True


def _digest_target(arg: str, fake: FakeClient,
                   state: dict) -> Optional[str]:
    """Resolve a digest fault's target node. A literal node name passes
    through; the ``@placed:N`` sentinel resolves to the N-th (sorted)
    TPU node carrying a placement lease at FIRST resolution and is then
    pinned in ``state`` — the whole FAIL ramp stays aimed at one node
    even after the eviction it provokes moves the lease elsewhere.
    Distinct sentinels pin distinct nodes, so the flap target can never
    accidentally heal the ramp target's streak."""
    if not arg.startswith("@placed:"):
        return arg
    targets = state.setdefault("digest_targets", {})
    if arg in targets:
        return targets[arg]
    leased = sorted(
        name_of(n) for n in fake.list("v1", "Node")
        if labels_of(n).get(L.GKE_TPU_ACCELERATOR)
        and annotations_of(n).get(L.PLACED_BY))
    if not leased:
        # nothing bound (all requests unschedulable this seed): fall
        # back to any TPU node so the scorer is still exercised
        leased = sorted(
            name_of(n) for n in fake.list("v1", "Node")
            if labels_of(n).get(L.GKE_TPU_ACCELERATOR))
    pool = [n for n in leased if n not in set(targets.values())] or leased
    if not pool:
        return None
    name = pool[int(arg.split(":", 1)[1]) % len(pool)]
    targets[arg] = name
    return name


def _publish_digest(fake: FakeClient, node_name: str, state: dict,
                    status: str, temp_c: float) -> bool:
    """One digest publish onto a node's annotation — the chaos analog of
    the on-node engine's jittered publish loop. ``seq`` counts publishes
    per node, so the scorer's per-seq dedupe sees each write as exactly
    one new sample no matter how many watch echoes deliver it."""
    from ..metrics.health_engine import (
        DIGEST_SCHEMA_VERSION,
        digest_annotation,
    )

    node = fake.get_or_none("v1", "Node", node_name)
    if node is None:
        return False
    seqs = state.setdefault("digest_seq", {})
    seqs[node_name] = seqs.get(node_name, 0) + 1
    nl = labels_of(node)
    gen = L.accelerator_generation(nl.get(L.GKE_TPU_ACCELERATOR, "")) or ""
    try:
        chips = int(nl.get(L.GKE_ACCELERATOR_COUNT) or "4")
    except ValueError:
        chips = 4
    # a FAIL digest is one overheating chip, not a dead board — exactly
    # the single-chip degradation the hysteresis scorer arbitrates
    grades = {f"chip{i}": "ok" for i in range(chips)}
    if status == "fail" and grades:
        grades["chip0"] = "fail"
    digest = {"v": DIGEST_SCHEMA_VERSION, "status": status,
              "grades": grades,
              "duty_pct": 95.0 if status == "ok" else 35.0,
              "hbm_free_frac": 0.4 if status == "ok" else 0.05,
              "temp_max_c": float(temp_c), "gen": gen,
              "seq": seqs[node_name]}
    node = thaw_obj(node)
    node.setdefault("metadata", {}).setdefault("annotations", {})[
        L.HEALTH_DIGEST] = digest_annotation(digest)
    try:
        fake.update(node)
    except ConflictError:
        return False
    return True


def _apply_fault(fault: Fault, fake: FakeClient, chaos: ChaosClient,
                 state: dict) -> None:
    kind = fault.kind
    if kind in (API_CONFLICT, API_THROTTLE, API_UNAVAILABLE, API_LATENCY):
        chaos.arm(fault)
        return
    applied = False
    if kind in (NODE_FLAP, NODE_HEAL):
        applied = _set_node_ready(fake, fault.arg, ready=kind == NODE_HEAL)
    elif kind == NODE_ADD:
        fake.add_node(fault.arg, labels={
            L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
            L.GKE_TPU_TOPOLOGY: "2x2x1",
            L.GKE_ACCELERATOR_COUNT: "4"},
            allocatable={L.TPU_RESOURCE: "4"})
        applied = True
    elif kind == NODE_REMOVE:
        if fake.get_or_none("v1", "Node", fault.arg) is not None:
            # the VM is gone: its pods go with it (no graceful drain)
            for pod in fake.list("v1", "Pod"):
                if get_nested(pod, "spec", "nodeName") == fault.arg:
                    try:
                        fake.delete("v1", "Pod", name_of(pod),
                                    namespace_of(pod) or None)
                    except NotFoundError:
                        pass
            try:
                fake.delete("v1", "Node", fault.arg)
                applied = True
            except NotFoundError:
                pass
    elif kind == CHIP_LOSS:
        node = fake.get_or_none("v1", "Node", fault.arg)
        if node is not None:
            node = thaw_obj(node)
            alloc = get_nested(node, "status", "allocatable",
                               default={}) or {}
            state["chips"].setdefault(fault.arg,
                                      alloc.get(L.TPU_RESOURCE, "0"))
            for field in ("allocatable", "capacity"):
                cur = dict(get_nested(node, "status", field,
                                      default={}) or {})
                cur[L.TPU_RESOURCE] = "0"
                set_nested(node, cur, "status", field)
            fake.update_status(node)
            applied = True
    elif kind == CHIP_RESTORE:
        saved = state["chips"].pop(fault.arg, None)
        node = fake.get_or_none("v1", "Node", fault.arg)
        if saved is not None and node is not None:
            node = thaw_obj(node)
            for field in ("allocatable", "capacity"):
                cur = dict(get_nested(node, "status", field,
                                      default={}) or {})
                cur[L.TPU_RESOURCE] = saved
                set_nested(node, cur, "status", field)
            fake.update_status(node)
            applied = True
    elif kind == POD_CRASH:
        pods = sorted(
            (p for p in fake.list("v1", "Pod",
                                  ListOptions(namespace=NAMESPACE))
             if get_nested(p, "spec", "nodeName") == fault.arg
             and not get_nested(p, "metadata", "deletionTimestamp")),
            key=name_of)
        if pods:  # deterministic victim: first by name
            victim = thaw_obj(pods[0])
            set_nested(victim, "Pending", "status", "phase")
            set_nested(victim, [{"type": "Ready", "status": "False"}],
                       "status", "conditions")
            fake.update_status(victim)
            applied = True
    elif kind == MUTATE_POLICY:
        def set_marker(cr: dict) -> None:
            cr.setdefault("spec", {}).setdefault("devicePlugin", {})[
                "env"] = [{"name": "CHAOS_MARKER", "value": fault.arg}]

        _mutate_cr(fake, set_marker)
        state["marker"] = fault.arg
        applied = True
    elif kind == TRIGGER_ROLLOUT:
        _mutate_cr(fake, lambda cr: cr.setdefault("spec", {}).__setitem__(
            "libtpu", {"installDir": fault.arg}))
        state["rollout"] = True
        applied = True
    elif kind == OPERAND_DRIFT:
        # out-of-band spec edit that leaves the spec-hash annotation
        # INTACT — the blind spot of an annotation-only skip. The image
        # is a field every desired container carries, so the operator's
        # live-vs-desired check must see the mismatch and rewrite.
        dss = sorted(fake.list("apps/v1", "DaemonSet",
                               ListOptions(namespace=NAMESPACE)),
                     key=name_of)
        if dss:
            victim = thaw_obj(dss[fault.count % len(dss)])
            ctrs = get_nested(victim, "spec", "template", "spec",
                              "containers", default=[]) or []
            if ctrs:
                ctrs[0]["image"] = f"chaos-drift/{fault.arg}"
                try:
                    fake.update(victim)
                    state["drift"] = True
                    applied = True
                except ConflictError:
                    pass
    elif kind == SLICE_REQUEST:
        # demand arrives: a user submits a SliceRequest. Chip count rides
        # in ``count``, priority in ``seconds`` (the plan's only free
        # numeric slots), and an optional quota class suffixed onto the
        # name as ``name@class`` (the saturation scenario's classed
        # demand); the placement controller picks it up from the ADDED
        # watch event like any other client would.
        req_name, _, qclass = fault.arg.partition("@")
        if fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST, req_name,
                            NAMESPACE) is None:
            obj = new_slice_request(
                req_name,
                spec=SliceRequestSpec(chips=fault.count,
                                      priority=int(fault.seconds)).to_obj(),
                namespace=NAMESPACE)
            if qclass:
                obj.setdefault("metadata", {}).setdefault(
                    "annotations", {})[L.QUOTA_CLASS] = qclass
            fake.create(obj)
            if chaos.clock is not None:
                # birth time on the virtual clock: the denominator of
                # the verdict's deterministic per-slice goodput rate
                state.setdefault("req_created", {})[req_name] = \
                    chaos.clock.t
            applied = True
    elif kind == SLICE_RESIZE:
        # the user edits spec.chips on a live request (kubectl apply of a
        # bigger/smaller topology). The fake bumps metadata.generation on
        # the spec change, so the placement controller's watch fires and
        # the elastic shrink/grow handshake starts from the intent post.
        live = fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST, fault.arg,
                                NAMESPACE)
        if live is not None:
            victim = thaw_obj(live)
            if get_nested(victim, "spec", "chips") != fault.count:
                set_nested(victim, fault.count, "spec", "chips")
                try:
                    fake.update(victim)
                    applied = True
                except ConflictError:
                    pass
    elif kind == SHARD_KILL:
        # kill the busiest killable shard's worker group on every
        # controller: queued keys rehash onto the survivors (lanes
        # preserved); the no-op guard (never the last shard) mirrors
        # Controller.kill_shard
        kills = []
        for ctrl in state.get("ctrls") or []:
            out = ctrl.kill_busiest(fault.count)
            if out is not None:
                kills.append({"controller": ctrl.name, "shard": out[0],
                              "keys_moved": out[1]})
        if kills:
            state.setdefault("shard_kills", []).extend(kills)
            applied = True
    elif kind == WORKLOAD_CRASH:
        # the training job dies mid-step, leaving a torn (never-acked)
        # checkpoint behind — the restart must restore the newest durable
        # step, and the no-lost-work invariant holds it to the acked one
        wl = (state.get("shims") or {}).get(fault.arg)
        if wl is not None:
            wl.crash(partial=True)
            applied = True
    elif kind == RESHARD_CRASH:
        # arm a kill landing mid-shard-handoff: the shim's next direct
        # handoff writes a torn (unfinalized) re-shard manifest and
        # dies — restore must roll back to the finalized step. The
        # "@mismatch" mode instead bumps the shim's layout version so
        # its next resize is ineligible for the fast path and exercises
        # the full-checkpoint fallback arc
        name, _, mode = str(fault.arg or "").partition("@")
        wl = (state.get("shims") or {}).get(name)
        if wl is not None:
            if mode == "mismatch":
                wl.force_layout_mismatch()
            else:
                wl.arm_reshard_crash()
            applied = True
    elif kind == ANNOTATION_CLEAR:
        # strip the hash annotations entirely (a `kubectl annotate ...-`
        # adversary): the skip must fail closed and restore them
        dss = sorted(fake.list("apps/v1", "DaemonSet",
                               ListOptions(namespace=NAMESPACE)),
                     key=name_of)
        if dss:
            victim = thaw_obj(dss[fault.count % len(dss)])
            anns = victim.setdefault("metadata", {}).get("annotations") or {}
            cleared = bool(anns.pop(L.SPEC_HASH, None)) \
                | bool(anns.pop(L.LAST_APPLIED_HASH, None))
            if cleared:
                try:
                    fake.update(victim)
                    state["drift"] = True
                    applied = True
                except ConflictError:
                    pass
    elif kind == DIGEST_SEED:
        # t=0 of the telemetry plane: every TPU node starts publishing
        # healthy digests, so silence is never mistaken for health
        for nm in sorted(
                name_of(n) for n in fake.list("v1", "Node")
                if labels_of(n).get(L.GKE_TPU_ACCELERATOR)):
            applied = _publish_digest(fake, nm, state, "ok", 55.0) \
                or applied
    elif kind in (DIGEST_DEGRADE, DIGEST_HEAL):
        target = _digest_target(fault.arg, fake, state)
        if target is not None:
            if kind == DIGEST_DEGRADE:
                # the builder rides the chip temperature in ``seconds``
                applied = _publish_digest(fake, target, state, "fail",
                                          fault.seconds or 90.0)
            else:
                applied = _publish_digest(fake, target, state, "ok", 55.0)
    if applied:
        chaos.record(kind)


# -- convergence ------------------------------------------------------------


def _marker_landed(fake: FakeClient, marker: str) -> bool:
    for ds in fake.list("apps/v1", "DaemonSet",
                        ListOptions(namespace=NAMESPACE)):
        for ctr in get_nested(ds, "spec", "template", "spec", "containers",
                              default=[]) or []:
            for var in ctr.get("env") or []:
                if var.get("name") == "CHAOS_MARKER" \
                        and var.get("value") == marker:
                    return True
    return False


def _fleet_rolled(fake: FakeClient) -> bool:
    """Every driver pod runs its DaemonSet's current template revision —
    the controller's own canonical definition (desired_revision), same as
    the rollout bench's fleet check."""
    sel = ListOptions(namespace=NAMESPACE,
                      label_selector={"tpu.graft.dev/component":
                                      "libtpu-driver"})
    wants = {name_of(ds): desired_revision(fake, ds)
             for ds in fake.list("apps/v1", "DaemonSet", sel)}
    if not wants:
        return False
    pods = fake.list("v1", "Pod", sel)
    for pod in pods:
        ds_name = next(
            (o.get("name") for o in get_nested(
                pod, "metadata", "ownerReferences", default=[]) or []
             if o.get("kind") == "DaemonSet"), None)
        want = wants.get(ds_name)
        if want is not None and get_nested(
                pod, "metadata", "labels",
                "controller-revision-hash") != want:
            return False
    return bool(pods)


def _converged(fake: FakeClient, state: dict) -> bool:
    cr = fake.get_or_none(V1, KIND_CLUSTER_POLICY, POLICY)
    if cr is None or get_nested(cr, "status", "state") != "ready":
        return False
    for node in fake.list("v1", "Node"):
        if not labels_of(node).get(L.GKE_TPU_ACCELERATOR):
            continue
        if get_nested(node, "spec", "unschedulable", default=False):
            return False
        conds = get_nested(node, "status", "conditions", default=[]) or []
        if not any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in conds):
            return False
        if labels_of(node).get(L.UPGRADE_STATE) not in (None, STATE_DONE):
            return False
    if state["marker"] is not None \
            and not _marker_landed(fake, state["marker"]):
        return False
    if state["rollout"] and not _fleet_rolled(fake):
        return False
    if state.get("drift"):
        # drift must be healed: every operand carries the spec-hash
        # annotation again and no container still runs a drifted image
        for ds in fake.list("apps/v1", "DaemonSet",
                            ListOptions(namespace=NAMESPACE)):
            anns = get_nested(ds, "metadata", "annotations",
                              default={}) or {}
            if L.SPEC_HASH not in anns:
                return False
            for ctr in get_nested(ds, "spec", "template", "spec",
                                  "containers", default=[]) or []:
                if str(ctr.get("image", "")).startswith("chaos-drift/"):
                    return False
    # every SliceRequest must sit in a terminal phase with a consistent
    # lease trail — a request still Pending (or Placed onto a vanished or
    # re-leased node) means the placement loop hasn't finished healing
    for req in fake.list(V1ALPHA1, KIND_SLICE_REQUEST):
        phase = get_nested(req, "status", "phase")
        if phase not in (PHASE_PLACED, PHASE_UNSCHEDULABLE):
            return False
        # an elastic handshake still in flight (Migrating/Checkpointed/
        # Rebound) means a controller or workload still owes a move
        if (get_nested(req, "status", "migration", "phase") or "") \
                not in MIG_TERMINAL:
            return False
        if phase != PHASE_PLACED:
            continue
        key = f"{namespace_of(req) or 'default'}/{name_of(req)}"
        for node_name in get_nested(req, "status", "nodes",
                                    default=[]) or []:
            node = fake.get_or_none("v1", "Node", node_name)
            if node is None or annotations_of(node).get(L.PLACED_BY) != key:
                return False
    from ..controllers.slices import slice_status

    return all(r["validated"] for r in slice_status(fake, NAMESPACE))


def _placement_summary(fake: FakeClient) -> dict:
    """Deterministic placement outcome block for the verdict: phase
    counts, total evictions survived, and the chip inventory the gauges
    export — all read from the settled store, no clocks involved."""
    from ..topology.placement import FleetState

    reqs = fake.list(V1ALPHA1, KIND_SLICE_REQUEST)
    phases: Dict[str, int] = {}
    evictions = 0
    for req in reqs:
        phase = get_nested(req, "status", "phase") or "Pending"
        phases[phase] = phases.get(phase, 0) + 1
        evictions += int(get_nested(req, "status", "evictions",
                                    default=0) or 0)
    totals = FleetState(fake.list("v1", "Node")).chip_totals()
    free = sum(b["free"] for b in totals.values())
    placed = sum(b["placed"] for b in totals.values())
    return {
        "requests": len(reqs),
        "phases": {k: phases[k] for k in sorted(phases)},
        "evictions": evictions,
        "chips_placed": placed,
        "chips_free": free,
        "utilization": (round(placed / (placed + free), 4)
                        if placed + free else 0.0),
    }


def _migration_summary(fake: FakeClient) -> dict:
    """Deterministic elastic-protocol outcome block for the verdict: the
    settled migration phase per request, completed-move counts, and the
    acked/restored step pair the no-lost-work invariant audits — all read
    from the store, byte-identical per seed."""
    reqs = sorted(fake.list(V1ALPHA1, KIND_SLICE_REQUEST), key=name_of)
    phases: Dict[str, int] = {}
    completed = 0
    rows = []
    for req in reqs:
        mig = dict(get_nested(req, "status", "migration",
                              default={}) or {})
        phase = mig.get("phase") or "none"
        phases[phase] = phases.get(phase, 0) + 1
        moves = int(get_nested(req, "status", "migrations",
                               default=0) or 0)
        completed += moves
        rows.append({
            "name": name_of(req),
            "phase": phase,
            "migrations": moves,
            "ackedStep": mig.get("ackedStep"),
            "restoredStep": mig.get("restoredStep"),
            "reason": mig.get("reason"),
            "path": mig.get("path"),
            "bytesMoved": mig.get("bytesMoved"),
            "shardsMoved": mig.get("shardsMoved"),
        })
    return {
        "requests": len(reqs),
        "phases": {k: phases[k] for k in sorted(phases)},
        "completed_moves": completed,
        "resharded": sum(1 for r in rows
                         if r["path"] == "sharded-handoff"),
        "rows": rows,
    }


def _telemetry_summary(fake: FakeClient, telemetry, state: dict) -> dict:
    """Deterministic telemetry outcome block for the verdict: the fleet
    rollup over the settled store, the scorer's condemned set and
    streaks, the digest publish ledger, and every eviction the telemetry
    path caused — the evidence the no-flap-evict invariant audited."""
    from ..metrics.fleet import rollup_nodes

    tel_evictions = []
    for req in sorted(fake.list(V1ALPHA1, KIND_SLICE_REQUEST),
                      key=name_of):
        reason = get_nested(req, "status", "lastEvictionReason") or ""
        if "condemned by telemetry" in reason:
            tel_evictions.append({
                "request": name_of(req), "reason": reason,
                "evictions": int(get_nested(req, "status", "evictions",
                                            default=0) or 0)})
    return {
        "rollup": rollup_nodes(fake.list("v1", "Node")),
        "condemned": telemetry.condemned() if telemetry is not None else [],
        "targets": dict(sorted(
            (state.get("digest_targets") or {}).items())),
        "digest_publishes": dict(sorted(
            (state.get("digest_seq") or {}).items())),
        "telemetry_evictions": tel_evictions,
    }


def _goodput_summary(fake: FakeClient, now_s: float, state: dict) -> dict:
    """Deterministic slice-goodput block for the verdict: each request's
    durably-checkpointed steps rated against the generation-ideal rate
    over its own virtual lifetime — pure store + virtual-clock reads,
    byte-identical per seed. Feeds the ``slice-goodput`` SLO row: a
    slice that lost its node to a condemned chip spends virtual time
    evicted, and those slow steps burn the budget by design."""
    from ..metrics.fleet import GOODPUT_DEGRADED_RATIO, ideal_steps_per_s

    created = state.get("req_created") or {}
    rows = []
    good = bad = 0
    for req in sorted(fake.list(V1ALPHA1, KIND_SLICE_REQUEST),
                      key=name_of):
        nm = name_of(req)
        acked = get_nested(req, "status", "progress", "checkpointedStep",
                           default=None)
        if acked is None:
            acked = get_nested(req, "status", "migration", "ackedStep",
                               default=None)
        if acked is None:
            continue
        acked = int(acked)
        born = created.get(nm)
        elapsed = (now_s - born) if born is not None else 0.0
        pool = str(get_nested(req, "status", "pool", default="") or "")
        gen = pool.split("-")[0] if pool else ""
        ratio = ((acked / elapsed) / ideal_steps_per_s(gen)) \
            if elapsed > 0 else 0.0
        quality = "good" if ratio >= GOODPUT_DEGRADED_RATIO \
            else "degraded"
        if quality == "good":
            good += acked
        else:
            bad += acked
        rows.append({"name": nm, "acked_steps": acked,
                     "virtual_s": round(elapsed, 1), "generation": gen,
                     "goodput_ratio": round(ratio, 4),
                     "quality": quality})
    return {"rows": rows, "steps_good": good, "steps_degraded": bad}


# the convergence SLO's virtual budget: converging inside this many
# virtual seconds past the last fault is "good". Generous next to the
# soak budget (150 passes * 20s) so only a genuinely struggling run
# burns it — convergence FAILURE already fails the verdict outright.
CONVERGENCE_SLO_VIRTUAL_S = 600.0
# single-window burn threshold for the chaos SLO block: the settled
# store is one window (there is no time series to diff), so the classic
# fast/slow pair collapses to one threshold
CHAOS_BURN_THRESHOLD = 2.0


def _slo_verdict(scenario: str, out: dict,
                 conv_s: Optional[float]) -> dict:
    """Deterministic SLO block for the verdict: settled-store event
    counts (never wall-clock histograms) fed through the same
    :func:`~tpu_operator.metrics.slo.burn_verdict` math the production
    SLOEngine runs, so the verdicts are byte-identical per seed yet
    exercise the identical formula. Scenarios engineered to violate an
    objective (slice-migrate's rigid requests, the contention storm's
    preemptions) must show up in ``breached`` — a chaos invariant."""
    from ..api.slicerequest import MIG_ABORTED, MIG_RESUMED
    from ..metrics.slo import burn_verdict

    conv_ok = (out["converged"] and conv_s is not None
               and conv_s <= CONVERGENCE_SLO_VIRTUAL_S)
    slos = {
        # 0/1 SLI: the run either converged inside the virtual budget or
        # it torched the whole error budget
        "convergence-latency": burn_verdict(
            good=1 if conv_ok else 0, bad=0 if conv_ok else 1,
            objective=0.99, threshold=CHAOS_BURN_THRESHOLD),
    }
    pl = out.get("placement")
    if pl is not None:
        slos["placement-stability"] = burn_verdict(
            good=pl["phases"].get(PHASE_PLACED, 0),
            bad=pl["evictions"],
            objective=0.90, threshold=CHAOS_BURN_THRESHOLD)
    mig = out.get("migrations")
    if mig is not None:
        slos["migration-success"] = burn_verdict(
            good=mig["phases"].get(MIG_RESUMED, 0),
            bad=mig["phases"].get(MIG_ABORTED, 0),
            objective=0.90, threshold=CHAOS_BURN_THRESHOLD)
    gp = out.get("goodput")
    if gp is not None:
        # the same objective the production slice-goodput SLOSpec
        # carries (metrics/slo.py), fed the verdict's deterministic
        # step classification instead of the live counters
        slos["slice-goodput"] = burn_verdict(
            good=gp["steps_good"], bad=gp["steps_degraded"],
            objective=0.90, threshold=CHAOS_BURN_THRESHOLD)
    return {
        "objective_threshold": CHAOS_BURN_THRESHOLD,
        "slos": {k: slos[k] for k in sorted(slos)},
        "breached": sorted(n for n, v in slos.items() if v["breached"]),
    }


# -- scenario driver --------------------------------------------------------


def run_scenario(scenario: str, nodes: int = 100, seed: int = 0,
                 steps: Optional[int] = None, cached: bool = True) -> dict:
    """Run one named scenario and return its deterministic verdict.

    ``cached=True`` (the default, matching production) puts an
    informer-backed :class:`~tpu_operator.runtime.cache.CachedClient`
    between the controllers and the fault-injecting apiserver — the
    watch-drop scenarios then exercise the cache's relist healing, and
    the checker's ``cache-staleness`` invariant holds it to account."""
    if scenario not in SCENARIOS:
        raise ValueError(f"unknown chaos scenario {scenario!r}; "
                         f"choose from {', '.join(SCENARIOS)}")
    if scenario in FEDERATION_SCENARIOS:
        # N-cell scenarios run the federation plane's own loop; it owns
        # its globals ctx (and its own restart-coherent wrapper), so it
        # is imported lazily to keep the module graphs independent
        from .federation import run_federation_scenario

        return run_federation_scenario(scenario, nodes=nodes, seed=seed,
                                       steps=steps)
    import logging

    # injected faults make the controllers log real ERROR tracebacks by
    # design — hundreds of them. The verdict is the signal; the expected
    # failure spam is not. Anything that matters (a dropped invariant, a
    # non-convergence) lands in the verdict, not the log.
    op_log = logging.getLogger("tpu_operator")
    prev_level = op_log.level
    op_log.setLevel(logging.CRITICAL)
    try:
        return _run_scenario(scenario, nodes, seed, steps, cached)
    finally:
        op_log.setLevel(prev_level)


def _chaos_globals(scenario: str, seed: int):
    """Context manager owning the process-wide recorders for one run.

    Span timestamps come from the yielded virtual clock and sequence ids
    restart at 0, so traces/timelines embedded in the verdict are part
    of the deterministic output (byte-identical per seed). The DAG
    scheduler runs in VIRTUAL mode: waves execute sequentially in a
    seeded shuffle, so branch interleaving is adversarial yet the run
    stays single-threaded. A fresh RNG per run makes back-to-back runs
    of the same seed identical too."""
    import random
    from contextlib import contextmanager

    from ..runtime.tracing import TRACER
    from ..state.scheduler import DAG_GATE

    @contextmanager
    def _ctx():
        clock = VirtualClock()
        prev_clock, prev_enabled = TRACER.clock, TRACER.enabled
        TRACER.reset(clock=clock, enabled=True)
        prev_tl_clock, prev_tl_enabled = TIMELINE.clock, TIMELINE.enabled
        TIMELINE.reset(clock=clock, enabled=True)
        prev_dag, prev_rng = DAG_GATE.enabled, DAG_GATE.virtual_rng
        DAG_GATE.enabled = True
        DAG_GATE.virtual_rng = random.Random(f"dag:{scenario}:{seed}")
        try:
            yield clock
        finally:
            DAG_GATE.enabled, DAG_GATE.virtual_rng = prev_dag, prev_rng
            TRACER.reset(clock=prev_clock, enabled=prev_enabled)
            TIMELINE.reset(clock=prev_tl_clock, enabled=prev_tl_enabled)

    return _ctx()


def _run_scenario(scenario: str, nodes: int, seed: int,
                  steps: Optional[int], cached: bool) -> dict:
    with _chaos_globals(scenario, seed) as clock:
        out = _run_scenario_impl(scenario, nodes, seed, steps, cached,
                                 clock)
    if scenario in RESTART_COHERENT_SCENARIOS:
        # restart-coherent: re-run the same seed with ONLY the crash
        # faults stripped — every other fault, request and clock tick
        # identical — and demand the byte-identical canonical settled
        # state. A crash changing what settled state the fleet reaches
        # is exactly the bug class this scenario exists to catch. For
        # the saturation scenario this also pins the snapshot-restored
        # deficit clocks and budget tokens: a restart that re-ran or
        # skipped a rescue would settle a different set of placements.
        with _chaos_globals(scenario, seed) as base_clock:
            base = _run_scenario_impl(scenario, nodes, seed, steps,
                                      cached, base_clock,
                                      strip_crashes=True)
        coherent = (base["converged"]
                    and base["settled_digest"] == out["settled_digest"])
        out["restart_coherent"] = {
            "ok": bool(out["converged"] and coherent),
            "digest": out["settled_digest"],
            "baseline_digest": base["settled_digest"],
            "baseline_converged": base["converged"],
        }
        if out["converged"] and not coherent:
            out["violations"].append({
                "invariant": "restart-coherent", "step": out["steps"],
                "detail": "settled state after crash+restore diverged "
                          "from the never-crashed baseline "
                          f"({out['settled_digest'][:12]} != "
                          f"{base['settled_digest'][:12]}, baseline "
                          f"converged={base['converged']})"})
            out["ok"] = False
    return out


def _run_scenario_impl(scenario: str, nodes: int, seed: int,
                       steps: Optional[int], cached: bool,
                       clock: VirtualClock,
                       strip_crashes: bool = False) -> dict:
    from ..runtime.tracing import TRACER, TracingClient

    n_steps = steps or DEFAULT_STEPS
    fake = build_cluster(n_tpu=nodes)
    chaos = ChaosClient(fake, clock)
    # controllers read through the cache (which reads through the chaos
    # client, so informer relists still eat armed faults); the adversary
    # and the checker keep talking to the unwrapped fake. The cache runs
    # on the virtual clock so degraded-mode reconnect backoff and
    # staleness age are part of the deterministic schedule.
    client = CachedClient(chaos, now=clock) if cached else chaos
    # the reconcilers' client verbs get trace spans; the checker and the
    # verdict's relist counter keep the bare client
    traced = TracingClient(client)
    upgrade_spec = {"autoUpgrade": True,
                    "maxParallelUpgrades": MAX_PARALLEL_UPGRADES}
    if scenario in SHIM_SCENARIOS:
        # a short virtual migrate window (3 ticks): the elastic requests
        # complete the handshake inside it, the rigid ones demonstrably
        # time out into the hard-drain degradation path
        upgrade_spec["migrationTimeoutSeconds"] = int(MIGRATION_TIMEOUT_S)
    fake.create(new_cluster_policy(spec={"upgradePolicy": upgrade_spec}))
    # the saturation scenario runs under a quota tree (seeded as the
    # production ConfigMap, so the controller exercises its own config
    # loading) and the throughput-aware finish-time admission policy;
    # every other scenario has no tree, so its admission layer — and
    # its verdict — is byte-identical to before this plane existed
    quota_tree = None
    admission_policy = None
    if scenario == "saturation-storm":
        import json as _json

        from ..scheduling.quota import (
            QUOTA_CONFIG_KEY,
            QUOTA_CONFIGMAP,
            QuotaTree,
        )

        quota_doc = _saturation_quota(nodes)
        fake.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": QUOTA_CONFIGMAP,
                         "namespace": NAMESPACE},
            "data": {QUOTA_CONFIG_KEY: _json.dumps(quota_doc,
                                                   sort_keys=True)}})
        quota_tree = QuotaTree.from_config(quota_doc)
        admission_policy = "finish-time"
    prec = ClusterPolicyReconciler(client=traced, namespace=NAMESPACE)
    urec = UpgradeReconciler(client=traced, namespace=NAMESPACE, now=clock)
    # the failover scenario runs sharded queues (kills rehash keys); every
    # other scenario keeps one shard — identical routing to before
    shards = FAILOVER_SHARDS if scenario == "shard-failover" else 1
    ctrls = [_SyncController(prec, traced, clock, shards=shards,
                             name="policy"),
             _SyncController(urec, traced, clock, shards=shards,
                             name="upgrade")]
    prec.setup_controller(ctrls[0], None)
    urec.setup_controller(ctrls[1], None)
    # the placement controller only joins the scenarios built around it:
    # the other scenarios create no SliceRequests, and keeping their
    # controller set unchanged keeps their verdicts unchanged. Preemption
    # is ON for the contention storm (off by default in production) so it
    # also exercises the priority-eviction path under fire; the migrate
    # scenario keeps it off so every rebind is a migration, not an
    # eviction, and runs on the virtual clock for the intent deadlines.
    # the storm scenario keeps preemption off: its whole demand wave is
    # same-age Pending, so the interesting machinery is the batched gang
    # pass and the index's churn survival, not the eviction path
    place_ctrl = None
    if scenario in PLACEMENT_SCENARIOS:
        lrec = PlacementReconciler(
            client=traced, namespace=NAMESPACE,
            preemption=(scenario == "placement-contention"),
            now=clock, resize_timeout=RESIZE_TIMEOUT_VIRTUAL_S,
            admission_policy=admission_policy)
        place_ctrl = _SyncController(lrec, traced, clock, shards=shards,
                                     name="placement")
        lrec.setup_controller(place_ctrl, None)
        ctrls.append(place_ctrl)
    # the fleet-telemetry plane joins the chip-degrade scenario: a fresh
    # scorer on the virtual clock folds digests O(delta) off the cache's
    # delta hook (per-tick resync when uncached), and the telemetry
    # reconciler publishes its verdict as the node condition the
    # placement engine then drains on — the full ingest -> score ->
    # condemn -> evict loop under fire
    telemetry = None
    tel_ctrl = None
    if scenario == "chip-degrade":
        telemetry = FleetTelemetry(now=clock)
        if cached:
            telemetry.attach(client)
        trec = TelemetryReconciler(client=traced, telemetry=telemetry)
        tel_ctrl = _SyncController(trec, traced, clock, shards=shards,
                                   name="telemetry")
        trec.setup_controller(tel_ctrl, None)
        ctrls.append(tel_ctrl)
    # elastic workload shims (the training jobs' half of the slice-intent
    # protocol) join only the migrate scenario; requests named ``rreq-*``
    # deliberately get none — they model rigid jobs that never ack, so the
    # migrate stage's timeout -> hard-drain fallback is always exercised
    shims: Dict[str, ElasticWorkload] = {}

    state = {"marker": None, "rollout": False, "chips": {}, "drift": False,
             "shims": shims, "ctrls": ctrls}
    resync = Request(name=POLICY)
    checker = InvariantChecker(fake, NAMESPACE,
                               cache=client if cached else None,
                               journal=prec.state_manager.journal,
                               quota=quota_tree, step_dt=STEP_DT)
    relists_lost = 0  # relists crashed processes performed, for the verdict

    def _enqueue_resync(c: _SyncController) -> None:
        # the resync add is the informer-resync analog: the liveness
        # backstop that keeps a scenario about SAFETY invariants — one
        # event lost to an armed fault inside a watch handler must not
        # deadlock the whole run. The placement controller's resync is
        # per-request: its primary kind is the SliceRequest, not the CR.
        if c is place_ctrl:
            for cr in fake.list(V1ALPHA1, KIND_SLICE_REQUEST):
                c.add(Request(name=name_of(cr),
                              namespace=namespace_of(cr)))
        elif c is tel_ctrl:
            # the telemetry reconciler's primary is the Node: its resync
            # re-audits every TPU node's condition against the scorer
            for n in fake.list("v1", "Node"):
                if labels_of(n).get(L.GKE_TPU_ACCELERATOR):
                    c.add(Request(name=name_of(n)))
        else:
            c.add(resync)

    def tick() -> None:
        if telemetry is not None and not cached:
            # no delta hook to ride: feed the same fold from a listing
            telemetry.resync(fake.list("v1", "Node"))
            for cr in fake.list(V1ALPHA1, KIND_SLICE_REQUEST):
                telemetry.on_request_delta("MODIFIED", cr)
        for c in ctrls:
            _enqueue_resync(c)
            c.drain()
        simulate_kubelet(fake, ready=True)
        if scenario in SHIM_SCENARIOS:
            # the training jobs run their quantum: elastic requests get a
            # shim the first time they appear, rigid (rreq-*) never do.
            # Shims talk to the unwrapped fake like any out-of-cluster
            # client — their writes still raise watch events for the
            # controllers, but armed faults stay aimed at the operator.
            # The shims themselves survive an OPERATOR_CRASH: the
            # training jobs don't die when the operator does.
            for cr in fake.list(V1ALPHA1, KIND_SLICE_REQUEST):
                nm = name_of(cr)
                if nm.startswith("ereq-") and nm not in shims:
                    shims[nm] = ElasticWorkload(fake, nm, NAMESPACE,
                                                clock=clock)
            for nm in sorted(shims):
                shims[nm].tick()
        for c in ctrls:
            c.drain()
        clock.advance(STEP_DT)
        for c in ctrls:
            c.drain()

    def _crash_restart(step: int) -> None:
        """OPERATOR_CRASH: the process dies and a successor boots.

        Everything in process memory — work queues, delayed requeues,
        the FleetIndex, Unschedulable backoff counters, the informer
        stores — is gone. The successor warm-restores from the last
        periodic snapshot (``state["snapshot"]``, captured at the end of
        the previous tick like the production writer thread would have),
        seeds its cache stores pre-watch, adopts the restored index, and
        re-derives the requeue state — so recovery work is O(changes
        since snapshot), and every invariant must hold across the gap.
        """
        nonlocal client, traced, prec, urec, place_ctrl, relists_lost
        for c in ctrls:
            c.stop()
        if cached:
            relists_lost += client.relists
            client.close()
        snap = state.get("snapshot") if cached else None
        client = CachedClient(chaos, now=clock) if cached else chaos
        restored = None
        if snap is not None:
            restored = snapshot_mod.restore(client, snap)
        traced = TracingClient(client)
        prec = ClusterPolicyReconciler(client=traced, namespace=NAMESPACE)
        urec = UpgradeReconciler(client=traced, namespace=NAMESPACE,
                                 now=clock)
        ctrls[:] = [_SyncController(prec, traced, clock, shards=shards,
                                    name="policy"),
                    _SyncController(urec, traced, clock, shards=shards,
                                    name="upgrade")]
        lrec = PlacementReconciler(
            client=traced, namespace=NAMESPACE, preemption=False,
            now=clock, resize_timeout=RESIZE_TIMEOUT_VIRTUAL_S,
            admission_policy=admission_policy)
        if snap is not None:
            idx = snapshot_mod.restore_index(snap)
            if idx is not None:
                # before any watch subscribes: the adopted index's delta
                # listener then folds exactly the replayed delta
                lrec.adopt_index(idx)
            adm = snapshot_mod.restore_admission(snap)
            if adm is not None:
                # deficit clocks + preemption-budget tokens survive the
                # crash: a restart must neither reset a starving class's
                # clock nor refill a spent window
                lrec.adopt_admission(adm)
            for skey, payload in snap.get("stores", {}).items():
                if skey.endswith("/" + KIND_SLICE_REQUEST):
                    lrec.seed_requeue_state(payload.get("objects") or [])
        place_ctrl = _SyncController(lrec, traced, clock, shards=shards,
                                     name="placement")
        ctrls.append(place_ctrl)
        # watches subscribe here — seeded stores replay O(delta)
        prec.setup_controller(ctrls[0], None)
        urec.setup_controller(ctrls[1], None)
        lrec.setup_controller(place_ctrl, None)
        state["ctrls"] = ctrls
        state["crashes"] = state.get("crashes", 0) + 1
        state.setdefault("restores", []).append({
            "step": step,
            "outcome": ("restored" if restored is not None
                        else ("cold" if cached else "uncached")),
            "objects": (restored or {}).get("objects", 0),
            "kinds": (restored or {}).get("kinds", 0),
        })
        checker.on_operator_restart(step,
                                    cache=client if cached else None,
                                    journal=prec.state_manager.journal)

    def verdict(plan: FaultPlan, converged: bool, soak: int,
                conv_s: Optional[float]) -> dict:
        # lane-priority invariant: the worst-served health item across
        # every controller waited behind at most LANE_PRIORITY_BUDGET
        # bulk reconciles — checked at verdict time so every exit path
        # (setup failure included) audits it
        for ctrl in ctrls:
            if ctrl.max_health_behind_bulk > LANE_PRIORITY_BUDGET:
                checker.record(
                    "lane-priority", plan.steps,
                    f"[{ctrl.name}] a health-lane event waited behind "
                    f"{ctrl.max_health_behind_bulk} bulk reconciles "
                    f"(budget {LANE_PRIORITY_BUDGET})")
        violations = checker.to_list()
        out = {
            "scenario": scenario,
            "seed": seed,
            "nodes": nodes,
            "steps": plan.steps,
            "schedule": [asdict(f) for f in plan.faults],
            "faults_injected": {k: chaos.injected[k]
                                for k in sorted(chaos.injected)},
            "cached": cached,
            # accumulated across operator restarts: crashed processes'
            # relists plus the live one's
            "cache_relists": (client.relists + relists_lost) if cached
            else 0,
            "converged": converged,
            "soak_passes": soak,
            "convergence_virtual_s": conv_s,
            "violations": violations,
            # flight-recorder evidence: the slowest reconcile (virtual
            # duration — latency faults advance the clock) and every
            # failed one, each a complete span tree down to client verbs
            "traces": {
                "slowest": TRACER.slowest_trace(),
                "failed": TRACER.failed_traces(),
            },
            # fleet-scale queueing evidence: worst health-behind-bulk
            # wait per controller, and (sharded runs) the kill ledger —
            # which shards died and how many queued keys each failover
            # rehashed onto the survivors
            "lanes": {
                "budget": LANE_PRIORITY_BUDGET,
                "max_health_behind_bulk": {
                    ctrl.name: ctrl.max_health_behind_bulk
                    for ctrl in ctrls},
            },
            "shards": {
                "configured": shards,
                "live": {ctrl.name: list(ctrl._live) for ctrl in ctrls},
                "kills": state.get("shard_kills", []),
                "keys_rehashed": sum(ctrl.keys_moved_on_failover
                                     for ctrl in ctrls),
            },
            "ok": bool(converged and not violations),
        }
        if place_ctrl is not None:
            out["placement"] = _placement_summary(fake)
        if scenario in SHIM_SCENARIOS:
            out["migrations"] = _migration_summary(fake)
        if scenario == "slice-migrate":
            # the per-object causal story (enqueue causes, migration
            # phases, placement decisions) rides the verdict for the
            # migrate scenario — the `tpuop-cfg why` golden chain. Only
            # the kinds that tell that story: operand write-avoided
            # noise would dwarf the verdict
            out["timelines"] = {
                k: ev for k, ev in TIMELINE.snapshot().items()
                if k.split("/", 1)[0] in ("SliceRequest",
                                          "TPUClusterPolicy",
                                          "UpgradeUnit")}
        if scenario in RESTART_COHERENT_SCENARIOS:
            out["restarts"] = {
                "crashes": state.get("crashes", 0),
                "restores": state.get("restores", []),
            }
            settled = canonical_settled_state(fake, NAMESPACE)
            out["settled_state"] = settled
            out["settled_digest"] = settled_state_digest(settled)
        if scenario == "saturation-storm" and place_ctrl is not None:
            # the fair-share ledger at settle: per-class usage, queue
            # depth, shares, deficit clocks and remaining budget tokens
            # — all virtual-clock reads, byte-identical per seed
            try:
                out["admission"] = place_ctrl.reconciler.admission_report()
            except ApiError:
                pass  # an unconsumed armed fault ate the report reads
        if scenario == "chip-degrade":
            out["telemetry"] = _telemetry_summary(fake, telemetry, state)
            out["goodput"] = _goodput_summary(fake, clock.t, state)
        if scenario == "apiserver-brownout":
            out["brownout"] = {
                "degraded_entered": bool(state.get("degraded_seen")),
                "max_staleness_virtual_s": round(
                    state.get("max_staleness", 0.0), 1),
                "healed": (not getattr(client, "degraded", False))
                if cached else True,
            }
            if cached and converged and not state.get("degraded_seen"):
                # the scenario exists to prove the degradation path; a
                # breaker that never tripped during a full brownout
                # window means the mode is unreachable, not that the
                # run got lucky
                checker.record(
                    "degraded-mode", plan.steps,
                    "cache never entered degraded mode during the "
                    "brownout window")
                out["violations"] = checker.to_list()
                out["ok"] = bool(converged and not out["violations"])
        out["slo"] = _slo_verdict(scenario, out, conv_s)
        return out

    # baseline convergence — faults only start from a known-good state,
    # so a later non-convergence indicts the storm, not the install
    for _ in range(SETUP_PASS_BUDGET):
        tick()
        if _converged(fake, state):
            break
    else:
        checker.record("convergence", -1,
                       "cluster never reached all-Ready before fault "
                       "injection")
        return verdict(FaultPlan(scenario=scenario, seed=seed, steps=0),
                       converged=False, soak=0, conv_s=None)

    tpu_names = sorted(
        name_of(n) for n in fake.list("v1", "Node")
        if labels_of(n).get(L.GKE_TPU_ACCELERATOR))
    plan = FaultPlan.build(scenario, seed, tpu_names, n_steps)
    if strip_crashes:
        # the restart-coherent baseline: identical schedule minus the
        # crashes themselves (the RNG already ran, so every other fault
        # is byte-identical to the crashed run's)
        plan = FaultPlan(scenario=plan.scenario, seed=plan.seed,
                         steps=plan.steps,
                         faults=[f for f in plan.faults
                                 if f.kind != OPERATOR_CRASH])
    # periodic-snapshot analog: capture at the end of every tick while
    # crash faults remain, so a crash restores from the PREVIOUS tick's
    # state and the successor's recovery is genuinely O(delta)
    take_snapshots = cached and any(f.kind == OPERATOR_CRASH
                                    for f in plan.faults)

    for step in range(plan.steps):
        step_faults = plan.for_step(step)
        dropping = any(f.kind == WATCH_DROP for f in step_faults)
        crashing = any(f.kind == OPERATOR_CRASH for f in step_faults)
        if any(f.kind == BROWNOUT_START for f in step_faults):
            # the apiserver goes dark: every stream dies AND every list
            # fails, while the world below keeps moving
            chaos.suspend_watch_streams()
            chaos.set_brownout(True)
            if cached:
                client.mark_stale()
        if dropping:
            # streams die BEFORE this step's mutations land, so the
            # events are genuinely lost; the resume's relist must heal
            chaos.suspend_watch_streams()
        for fault in step_faults:
            if fault.kind not in (WATCH_DROP, OPERATOR_CRASH,
                                  BROWNOUT_START, BROWNOUT_END):
                _apply_fault(fault, fake, chaos, state)
        if dropping:
            chaos.resume_watch_streams()
        if any(f.kind == BROWNOUT_END for f in step_faults):
            # capture the breaker state at the worst moment — the
            # instant before heal — then let the streams replay
            if cached:
                state["degraded_seen"] = (state.get("degraded_seen")
                                          or client.degraded)
            chaos.set_brownout(False)
            chaos.resume_watch_streams()
        if crashing:
            # the process dies MID-PASS: resync enqueued, a handful of
            # reconciles in, then every queue is abandoned half-drained
            for c in ctrls:
                _enqueue_resync(c)
                c.drain(budget=CRASH_PARTIAL_DRAIN)
            chaos.record(OPERATOR_CRASH)
            _crash_restart(step)
        tick()
        if cached and chaos.brownout:
            state["degraded_seen"] = (state.get("degraded_seen")
                                      or client.degraded)
            state["max_staleness"] = max(state.get("max_staleness", 0.0),
                                         client.staleness_s())
        if take_snapshots:
            import json

            state["snapshot"] = json.loads(json.dumps(
                snapshot_mod.capture(client, index=getattr(
                    place_ctrl.reconciler, "fleet_index", None)
                    if place_ctrl is not None else None,
                    wall=clock(),
                    admission=place_ctrl.reconciler.admission_snapshot()
                    if place_ctrl is not None else None),
                sort_keys=True))
        checker.observe(step)

    faults_stopped_at = clock.t
    soak = 0
    converged = _converged(fake, state)
    while not converged and soak < SOAK_PASS_BUDGET:
        tick()
        soak += 1
        checker.observe(plan.steps + soak - 1)
        converged = _converged(fake, state)
    if converged:
        conv_s = clock.t - faults_stopped_at
        # one final resync pass before the settled audit: the production
        # Manager's periodic-resync analog. Label-only transitions the
        # upgrade controller makes late in a tick (the last unit flipping
        # to done) don't match the policy's node-watch predicate, so the
        # CR's status rows may legitimately trail the cluster by one
        # pass — a liveness gap resync closes, not a lost write.
        for c in ctrls:
            c.add(resync)
            c.drain()
        checker.check_settled(plan.steps + soak)
    else:
        conv_s = None
        checker.record(
            "convergence", plan.steps + soak,
            f"cluster not all-Ready after {soak} soak passes "
            f"({soak * STEP_DT:.0f} virtual s) past the last fault")
    return verdict(plan, converged=converged, soak=soak, conv_s=conv_s)
