"""Deterministic fault injection for the control plane.

Two halves:

- :class:`FaultPlan` — a seeded RNG materialized up-front into a
  reproducible schedule of :class:`Fault` events. Nothing draws from the
  RNG at run time, so the same (scenario, seed, node set, steps) always
  yields a byte-identical schedule — the property the determinism test
  pins (tests/test_chaos.py) and the property that makes a chaos failure
  reproducible from its verdict alone.
- :class:`ChaosClient` — a :class:`~tpu_operator.runtime.client.Client`
  wrapper that injects apiserver-side faults into whatever client the
  controllers actually use: 409 conflict storms, 429 Retry-After
  throttles, transient 5xx, request latency (charged to a virtual
  clock, never a real sleep), and dropped watch streams healed the way
  a real informer heals them — 410 Gone, then relist (the underlying
  ``watch()`` replays ADDED for every live object).

Object-level faults (node NotReady flaps, chip disappearance, operand
pod crash-loops, node churn) are *adversary moves against the world*,
not apiserver behaviors, so they are applied by the runner directly
through the unwrapped inner client — see ``runner._apply_fault``.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field
from typing import Callable, List, Optional, Sequence

from ..metrics.operator_metrics import OPERATOR_METRICS
from ..runtime.client import (
    Client,
    ConflictError,
    ListOptions,
    ServerUnavailableError,
    TooManyRequestsError,
)

# fault kinds consumed by ChaosClient (apiserver-side)
API_CONFLICT = "api-conflict"      # 409 on the next mutating request
API_THROTTLE = "api-throttle"      # 429 Retry-After on the next request
API_UNAVAILABLE = "api-5xx"        # 503 on the next request
API_LATENCY = "api-latency"        # virtual latency on the next requests
WATCH_DROP = "watch-drop"          # drop every stream; 410-then-relist

# fault kinds applied by the runner against cluster objects
NODE_FLAP = "node-flap"            # Ready=False on one node
NODE_HEAL = "node-heal"            # Ready=True again
NODE_ADD = "node-add"              # a TPU node joins
NODE_REMOVE = "node-remove"        # a TPU node (and its pods) vanishes
CHIP_LOSS = "chip-loss"            # health engine reports chips missing
CHIP_RESTORE = "chip-restore"      # chips come back
POD_CRASH = "pod-crash"            # an operand pod crash-loops
MUTATE_POLICY = "mutate-policy"    # spec edit the operator must apply
TRIGGER_ROLLOUT = "trigger-rollout"  # libtpu change -> fleet upgrade FSM
OPERAND_DRIFT = "operand-drift"    # out-of-band spec edit to a live operand
ANNOTATION_CLEAR = "annotation-clear"  # strip the spec-hash annotations
SLICE_REQUEST = "slice-request"    # a SliceRequest lands in the queue
SLICE_RESIZE = "slice-resize"      # spec.chips edit on a live SliceRequest
WORKLOAD_CRASH = "workload-crash"  # elastic shim dies mid-save (torn ckpt)
RESHARD_CRASH = "reshard-crash"    # elastic shim dies mid-shard-handoff
#                                    (torn re-shard manifest must roll
#                                    back to the finalized step); arg
#                                    "name@mismatch" instead bumps the
#                                    shim's layout version so the next
#                                    resize exercises the full-checkpoint
#                                    fallback arc
SHARD_KILL = "shard-kill"          # a reconcile shard's workers die;
#                                    queued keys must rehash losslessly
#                                    onto the survivors (count = shard id)
OPERATOR_CRASH = "operator-crash"  # the process dies mid-pass; the runner
#                                    rebuilds it from the latest snapshot
BROWNOUT_START = "brownout-start"  # apiserver brownout: lists fail and
#                                    watch streams die until the matching
BROWNOUT_END = "brownout-end"      # heal — controllers must serve stale
DIGEST_SEED = "digest-seed"        # every TPU node publishes an OK digest
DIGEST_DEGRADE = "digest-degrade"  # one FAIL digest publish on a node
#                                    (seeded per-chip temp ramp); arg may
#                                    be "@placed:N" — resolved at apply
#                                    time to the N-th node carrying a
#                                    placement lease (deterministic, and
#                                    guarantees the ramp hits a bound
#                                    slice); the resolution is pinned so
#                                    the whole ramp stays on one node
DIGEST_HEAL = "digest-heal"        # one OK digest publish on a node

# fault kinds consumed by the federation runner (chaos/federation.py);
# ``arg`` targets a CELL name, not a node
CELL_PARTITION_START = "cell-partition-start"  # one cell's apiserver
#                                    unreachable from the global plane:
#                                    contacts fail, the breaker opens —
#                                    but the cell keeps running inside
CELL_PARTITION_END = "cell-partition-end"      # the partition heals
DIGEST_STALE_START = "digest-stale-start"  # cell reachable, but its
#                                    digest publishes freeze (a wedged
#                                    publisher): the router must age-
#                                    discount, never trust the last words
DIGEST_STALE_END = "digest-stale-end"
ROUTER_CRASH = "router-crash"      # the global router dies mid-pass;
#                                    the runner rebuilds it from its
#                                    durable snapshot (restart-coherent)
ROUTER_SPLIT = "router-split"      # a shadow router is spawned from the
#                                    snapshot and fed the same digests
#                                    in seeded-permuted order; every
#                                    decision is compared (split-brain)


@dataclass(frozen=True)
class Fault:
    """One scheduled fault. ``step`` indexes the runner's step loop;
    ``arg`` is a target node name or a marker value; ``count``/``seconds``
    parameterize the apiserver faults."""

    step: int
    kind: str
    arg: str = ""
    count: int = 0
    seconds: float = 0.0


class VirtualClock:
    """Monotonic virtual time: the runner advances it per step, latency
    faults charge it per request, and the upgrade FSM's deadlines read it
    (``UpgradeReconciler(now=clock)``) — so timeout behavior is part of
    the deterministic schedule, not the wall clock."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


@dataclass
class FaultPlan:
    scenario: str
    seed: int
    steps: int
    faults: List[Fault] = field(default_factory=list)

    def for_step(self, step: int) -> List[Fault]:
        return [f for f in self.faults if f.step == step]

    def schedule_json(self) -> str:
        """Stable serialization — the determinism contract's artifact."""
        return json.dumps(
            {"scenario": self.scenario, "seed": self.seed,
             "steps": self.steps,
             "faults": [asdict(f) for f in self.faults]},
            sort_keys=True)

    # -- schedule generation ------------------------------------------------

    @classmethod
    def build(cls, scenario: str, seed: int, node_names: Sequence[str],
              steps: int) -> "FaultPlan":
        """Materialize the schedule for a named scenario. ``node_names``
        must be the sorted TPU node list of the cluster under test (the
        runner passes it), so node-targeted faults are reproducible."""
        rng = random.Random(f"{scenario}:{seed}")
        nodes = list(node_names)
        build = {
            "conflict-storm": cls._conflict_storm,
            "watch-flap": cls._watch_flap,
            "node-churn": cls._node_churn,
            "upgrade-under-fire": cls._upgrade_under_fire,
            "chip-loss": cls._chip_loss,
            "operand-drift": cls._operand_drift,
            "dag-race": cls._dag_race,
            "placement-contention": cls._placement_contention,
            "placement-storm": cls._placement_storm,
            "slice-migrate": cls._slice_migrate,
            "shard-failover": cls._shard_failover,
            "operator-crash": cls._operator_crash,
            "apiserver-brownout": cls._apiserver_brownout,
            "chip-degrade": cls._chip_degrade,
            "saturation-storm": cls._saturation_storm,
            # federation scenarios: ``node_names`` is the sorted CELL
            # name list (the federation runner passes it)
            "cell-partition": cls._cell_partition,
            "stale-digest": cls._stale_digest,
            "split-brain-router": cls._split_brain_router,
        }.get(scenario)
        if build is None:
            raise ValueError(f"unknown chaos scenario {scenario!r}")
        faults = build(rng, nodes, steps)
        faults.sort(key=lambda f: (f.step, f.kind, f.arg))
        return cls(scenario=scenario, seed=seed, steps=steps, faults=faults)

    @staticmethod
    def _marker(rng: random.Random, prefix: str) -> str:
        return f"{prefix}-{rng.randrange(1_000_000)}"

    @classmethod
    def _conflict_storm(cls, rng, nodes, steps) -> List[Fault]:
        """Write 409s in bursts, with 429/503 sprinkled in, each burst
        paired with a spec mutation the operator must still land."""
        out: List[Fault] = []
        for step in range(steps):
            if step % 3 == 0:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 6)))
                out.append(Fault(step, MUTATE_POLICY,
                                 arg=cls._marker(rng, "storm")))
            if step % 5 == 2:
                out.append(Fault(step, API_THROTTLE,
                                 count=rng.randrange(1, 3),
                                 seconds=float(rng.randrange(1, 5))))
            if step % 7 == 3:
                out.append(Fault(step, API_UNAVAILABLE, count=1))
        return out

    @classmethod
    def _watch_flap(cls, rng, nodes, steps) -> List[Fault]:
        """Streams die repeatedly; every drop pairs with a mutation so a
        client that fails to relist demonstrably loses the event."""
        out: List[Fault] = []
        for step in range(steps):
            if step % 4 == 1:
                out.append(Fault(step, WATCH_DROP))
                out.append(Fault(step, MUTATE_POLICY,
                                 arg=cls._marker(rng, "flap")))
            if step % 6 == 4:
                out.append(Fault(step, API_LATENCY, count=rng.randrange(3, 8),
                                 seconds=0.5))
        return out

    @classmethod
    def _node_churn(cls, rng, nodes, steps) -> List[Fault]:
        """Nodes flap NotReady, join, and vanish mid-run."""
        out: List[Fault] = []
        join = 0
        for step in range(steps):
            if step % 4 == 0 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, NODE_FLAP, arg=victim))
                out.append(Fault(min(step + 2, steps - 1), NODE_HEAL,
                                 arg=victim))
            if step % 6 == 3:
                join += 1
                out.append(Fault(step, NODE_ADD, arg=f"chaos-join-{join}"))
            if step % 9 == 5 and len(nodes) > 1:
                # never remove a node scheduled to heal later
                flapped = {f.arg for f in out if f.kind == NODE_FLAP}
                candidates = [n for n in nodes if n not in flapped]
                if candidates:
                    victim = rng.choice(candidates)
                    nodes.remove(victim)
                    out.append(Fault(step, NODE_REMOVE, arg=victim))
        return out

    @classmethod
    def _upgrade_under_fire(cls, rng, nodes, steps) -> List[Fault]:
        """A fleet libtpu rollout, then every apiserver fault class plus
        node flaps while the FSM walks the cluster."""
        out: List[Fault] = [
            Fault(0, TRIGGER_ROLLOUT, arg=cls._marker(rng, "/opt/chaos-libtpu"))]
        for step in range(1, steps):
            if step % 3 == 1:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(1, 4)))
            if step % 5 == 2:
                out.append(Fault(step, WATCH_DROP))
            if step % 4 == 3 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, NODE_FLAP, arg=victim))
                out.append(Fault(min(step + 2, steps - 1), NODE_HEAL,
                                 arg=victim))
            if step % 7 == 4:
                out.append(Fault(step, API_THROTTLE, count=1,
                                 seconds=float(rng.randrange(1, 4))))
        return out

    @classmethod
    def _operand_drift(cls, rng, nodes, steps) -> List[Fault]:
        """A config-management adversary edits live operand specs
        out-of-band (the spec-hash annotation stays intact — the exact
        case an annotation-only skip is blind to) and strips the
        spec-hash annotations entirely; the operator must detect both
        and re-converge. ``count`` doubles as the deterministic victim
        index into the sorted DaemonSet list."""
        out: List[Fault] = []
        for step in range(steps):
            if step % 3 == 0:
                out.append(Fault(step, OPERAND_DRIFT,
                                 arg=cls._marker(rng, "drift"),
                                 count=rng.randrange(0, 16)))
            if step % 4 == 1:
                out.append(Fault(step, ANNOTATION_CLEAR,
                                 count=rng.randrange(0, 16)))
            if step % 5 == 3:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(1, 3)))
        return out

    @classmethod
    def _dag_race(cls, rng, nodes, steps) -> List[Fault]:
        """Operand-sync faults aimed at parallel DAG branches: 409/503
        bursts land mid-wave (the seeded virtual scheduler shuffles which
        branch eats them per seed), operand drift forces re-applies on
        one branch while siblings are mid-sync, and spec mutations keep
        every state re-rendering. The dag-order invariant must hold — no
        state may sync before all its ``requires()`` report ready —
        whichever branch the fault lands on."""
        out: List[Fault] = []
        for step in range(steps):
            if step % 2 == 0:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 3 == 0:
                out.append(Fault(step, MUTATE_POLICY,
                                 arg=cls._marker(rng, "race")))
            if step % 3 == 1:
                out.append(Fault(step, OPERAND_DRIFT,
                                 arg=cls._marker(rng, "race-drift"),
                                 count=rng.randrange(0, 16)))
            if step % 5 == 2:
                out.append(Fault(step, API_UNAVAILABLE, count=1))
            if step % 4 == 3:
                out.append(Fault(step, WATCH_DROP))
        return out

    @classmethod
    def _placement_contention(cls, rng, nodes, steps) -> List[Fault]:
        """More demand than chips: waves of SliceRequests (chip count in
        ``count``, priority in ``seconds``) land against a fleet that
        flaps NotReady and shrinks mid-bind, with 409 storms hitting the
        lease/status writes. The placement-sound and placement-stable
        invariants must hold through every storm, and once faults stop
        every request must sit in a terminal phase with consistent
        leases."""
        out: List[Fault] = []
        sizes = (4, 4, 8, 8, 16, 32)
        req = 0
        for step in range(steps):
            # a wave of requests every step: demand outruns the fleet
            # within the first few steps, so the scorer is packing a
            # contended pool for most of the run
            for _ in range(rng.randrange(2, 5)):
                req += 1
                out.append(Fault(step, SLICE_REQUEST,
                                 arg=f"sreq-{req:03d}",
                                 count=rng.choice(sizes),
                                 seconds=float(rng.randrange(0, 3))))
            if step % 3 == 1:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 4 == 2 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, NODE_FLAP, arg=victim))
                out.append(Fault(min(step + 2, steps - 1), NODE_HEAL,
                                 arg=victim))
            if step % 5 == 3 and len(nodes) > 1:
                # a bound node vanishing is the explicit drain event the
                # eviction path exists for; never remove a node scheduled
                # to heal later
                flapped = {f.arg for f in out if f.kind == NODE_FLAP}
                candidates = [n for n in nodes if n not in flapped]
                if candidates:
                    victim = rng.choice(candidates)
                    nodes.remove(victim)
                    out.append(Fault(step, NODE_REMOVE, arg=victim))
        return out

    @classmethod
    def _placement_storm(cls, rng, nodes, steps) -> List[Fault]:
        """Batched-gang-placement stress: the whole demand wave lands
        Pending in the opening steps (2 requests per TPU node — 2k
        requests on a 1k-node fleet), so the controller's first passes
        drain deep batches against one shared index snapshot while nodes
        flap, join and vanish and watch drops force the index through
        its relist/resync healing. The index-coherence invariant then
        holds the O(delta) view to a from-scratch rescan at settle."""
        out: List[Fault] = []
        sizes = (4, 4, 8, 8, 16, 32)
        flood = max(24, 2 * len(nodes))
        front = max(1, min(3, steps))
        for i in range(flood):
            out.append(Fault(i % front, SLICE_REQUEST,
                             arg=f"storm-{i:04d}",
                             count=rng.choice(sizes),
                             seconds=float(rng.randrange(0, 3))))
        join = 0
        for step in range(steps):
            if step % 3 == 1 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, NODE_FLAP, arg=victim))
                out.append(Fault(min(step + 2, steps - 1), NODE_HEAL,
                                 arg=victim))
            if step % 4 == 2:
                join += 1
                out.append(Fault(step, NODE_ADD, arg=f"storm-join-{join}"))
            if step % 5 == 3:
                out.append(Fault(step, WATCH_DROP))
            if step % 6 == 4:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 7 == 5 and len(nodes) > 1:
                # never remove a node scheduled to heal later
                flapped = {f.arg for f in out if f.kind == NODE_FLAP}
                candidates = [n for n in nodes if n not in flapped]
                if candidates:
                    victim = rng.choice(candidates)
                    nodes.remove(victim)
                    out.append(Fault(step, NODE_REMOVE, arg=victim))
        return out

    @classmethod
    def _saturation_storm(cls, rng, nodes, steps) -> List[Fault]:
        """Fair-share admission at ~10x chip oversubscription: the
        opportunist classes (``batch``/``research``) flood the fleet
        first and soak every chip, then the min-guaranteed ``prod``
        class arrives into a saturated cluster — at LOWER priority, so
        only the deficit-clock watchdog (never the baseline sort) can
        rescue it, via budgeted elastic preemption of the over-share
        incumbents. A few rigid (``rreq-*``) opportunists verify the
        drain routes around slices that cannot checkpoint. One seeded
        operator crash lands mid-rescue: deficit clocks and budget
        tokens must ride the snapshot, and the restart-coherent rerun
        demands the same settled state as a never-crashed run. Node
        capacity deliberately never changes — the fair-share math under
        audit, not churn survival."""
        out: List[Fault] = []
        sizes = (4, 8, 8, 16, 16)
        flood = max(24, 2 * len(nodes))
        front = max(1, min(2, steps))
        n = 0
        for i in range(flood):
            n += 1
            qclass = "batch" if i % 3 else "research"
            out.append(Fault(i % front, SLICE_REQUEST,
                             arg=f"ereq-sat-{n:04d}@{qclass}",
                             count=rng.choice(sizes),
                             seconds=float(rng.randrange(1, 3))))
        for _ in range(3):
            n += 1
            out.append(Fault(0, SLICE_REQUEST,
                             arg=f"rreq-sat-{n:04d}@batch",
                             count=rng.choice(sizes),
                             seconds=float(rng.randrange(1, 3))))
        prod_step = min(2, steps - 1)
        for _ in range(max(4, len(nodes) // 10)):
            n += 1
            out.append(Fault(prod_step, SLICE_REQUEST,
                             arg=f"ereq-sat-{n:04d}@prod",
                             count=rng.choice((4, 8)),
                             seconds=0.0))
        if steps > prod_step + 3:
            out.append(Fault(rng.randrange(prod_step + 2, steps - 1),
                             OPERATOR_CRASH))
        for step in range(steps):
            if step % 3 == 2:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 5 == 4:
                out.append(Fault(step, WATCH_DROP))
        return out

    @classmethod
    def _slice_migrate(cls, rng, nodes, steps) -> List[Fault]:
        """Drain-safe migrate/resize under fire: elastic (``ereq-*``) and
        rigid (``rreq-*``) requests land in the opening steps, then a
        fleet rollout forces every placed slice through the migrate
        stage while 409 storms, watch drops, torn-checkpoint workload
        crashes, spec resizes and a node removal interleave. The
        no-acked-work-lost invariant must hold on every path — including
        the rigid requests' timeout → hard-drain degradation."""
        out: List[Fault] = []
        sizes = (4, 4, 8, 8, 16)
        n_elastic = n_rigid = 0
        for step in range(min(3, steps)):
            for _ in range(rng.randrange(2, 4)):
                if rng.random() < 0.7:
                    n_elastic += 1
                    name = f"ereq-{n_elastic:03d}"
                else:
                    n_rigid += 1
                    name = f"rreq-{n_rigid:03d}"
                out.append(Fault(step, SLICE_REQUEST, arg=name,
                                 count=rng.choice(sizes),
                                 seconds=float(rng.randrange(0, 3))))
        if n_rigid == 0:
            # the timeout degradation path is part of the contract; a
            # seed must not be able to roll it off the schedule
            n_rigid = 1
            out.append(Fault(0, SLICE_REQUEST, arg="rreq-001",
                             count=rng.choice(sizes)))
        rollout_step = min(3, steps - 1)
        out.append(Fault(rollout_step, TRIGGER_ROLLOUT,
                         arg=cls._marker(rng, "/opt/elastic-libtpu")))
        removed = False
        for step in range(rollout_step + 1, steps):
            if step % 3 == 1:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 4 == 2 and n_elastic:
                out.append(Fault(
                    step, WORKLOAD_CRASH,
                    arg=f"ereq-{rng.randrange(1, n_elastic + 1):03d}"))
            if step % 5 == 3:
                idx = rng.randrange(1, n_elastic + n_rigid + 1)
                name = (f"ereq-{idx:03d}" if idx <= n_elastic
                        else f"rreq-{idx - n_elastic:03d}")
                out.append(Fault(step, SLICE_RESIZE, arg=name,
                                 count=rng.choice(sizes)))
            if step % 5 == 4:
                out.append(Fault(step, WATCH_DROP))
            if not removed and step % 6 == 5 and len(nodes) > 4:
                # a bound node vanishing mid-handshake: the eviction
                # path must retire the in-flight attempt cleanly
                victim = rng.choice(nodes)
                nodes.remove(victim)
                out.append(Fault(step, NODE_REMOVE, arg=victim))
                removed = True
        # live-resharding arcs (appended AFTER the loop so the rng draw
        # sequence above is untouched): seeded mid-shard-handoff kills —
        # the torn re-shard manifest must roll back to the finalized
        # step — plus one deterministic layout-version mismatch so every
        # seed also exercises the full-checkpoint fallback path
        if n_elastic:
            for step in range(rollout_step + 1, steps):
                if step % 7 == 6:
                    out.append(Fault(
                        step, RESHARD_CRASH,
                        arg=f"ereq-{rng.randrange(1, n_elastic + 1):03d}"))
            out.append(Fault(min(rollout_step + 2, steps - 1),
                             RESHARD_CRASH, arg="ereq-001@mismatch"))
        return out

    @classmethod
    def _operator_crash(cls, rng, nodes, steps) -> List[Fault]:
        """Crash-safe instant restart: the slice-migrate opening (elastic
        and rigid requests, then a fleet rollout forcing every placed
        slice through the migrate stage), with the operator process
        killed at seeded points — once right after the rollout posts
        migrate intents (mid-migration) and once with a same-step gang
        wave half-drained (mid-gang-batch). Each crash discards every
        queue, in-memory index and backoff counter; the successor warms
        from the last snapshot and must converge to the same settled
        state as a run that never crashed (restart-coherent), with no
        acked work lost."""
        out: List[Fault] = []
        sizes = (4, 4, 8, 8, 16)
        n_elastic = n_rigid = 0
        for step in range(min(3, steps)):
            for _ in range(rng.randrange(2, 4)):
                if rng.random() < 0.7:
                    n_elastic += 1
                    name = f"ereq-{n_elastic:03d}"
                else:
                    n_rigid += 1
                    name = f"rreq-{n_rigid:03d}"
                out.append(Fault(step, SLICE_REQUEST, arg=name,
                                 count=rng.choice(sizes),
                                 seconds=float(rng.randrange(0, 3))))
        if n_elastic == 0:
            # a crash mid-migration of an *elastic* slice is the
            # hardest path (checkpoint handshake in flight); pin one
            n_elastic = 1
            out.append(Fault(0, SLICE_REQUEST, arg="ereq-001",
                             count=rng.choice(sizes)))
        rollout_step = min(3, steps - 1)
        out.append(Fault(rollout_step, TRIGGER_ROLLOUT,
                         arg=cls._marker(rng, "/opt/crash-libtpu")))
        # crash #1: right after the rollout posts migrate intents
        crash1 = min(rollout_step + 1, steps - 1)
        out.append(Fault(crash1, OPERATOR_CRASH))
        # crash #2: a seeded later step, with a same-step request wave
        # so the gang batch is half-drained when the process dies
        if steps > crash1 + 2:
            crash2 = rng.randrange(crash1 + 2, steps - 1)
            for _ in range(3):
                n_elastic += 1
                out.append(Fault(crash2, SLICE_REQUEST,
                                 arg=f"ereq-{n_elastic:03d}",
                                 count=rng.choice(sizes)))
            out.append(Fault(crash2, OPERATOR_CRASH))
        for step in range(rollout_step + 1, steps):
            if step % 3 == 2:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 5 == 4:
                out.append(Fault(step, WATCH_DROP))
        return out

    @classmethod
    def _chip_degrade(cls, rng, nodes, steps) -> List[Fault]:
        """Fleet telemetry under fire: elastic slices land and train,
        every node starts publishing OK digests, then two telemetry
        stories run concurrently on the virtual clock. A *ramp* node
        (resolved at apply time to a node actually hosting a placed
        slice) publishes FAIL digests every step — after CONDEMN_AFTER
        consecutive publishes the scorer condemns it, the condition
        lands, and its slice must evict and re-place with no acked work
        lost. A *flap* node (a different placed node) alternates
        FAIL/FAIL/OK forever — its streak never sustains, so it must
        cause ZERO evictions (telemetry-no-flap-evict). Background 409s
        and watch drops make sure the digest fold rides the same
        delta/relist machinery as everything else."""
        out: List[Fault] = []
        sizes = (4, 4, 8)
        n_elastic = 0
        for step in range(min(3, steps)):
            for _ in range(rng.randrange(2, 4)):
                n_elastic += 1
                out.append(Fault(step, SLICE_REQUEST,
                                 arg=f"ereq-{n_elastic:03d}",
                                 count=rng.choice(sizes),
                                 seconds=float(rng.randrange(0, 3))))
        # everyone reports healthy before anyone degrades: the rollup
        # sees a full fleet, and the scorer's streaks start from OK
        out.append(Fault(min(3, steps - 1), DIGEST_SEED))
        ramp_start = min(4, steps - 1)
        for step in range(ramp_start, steps):
            # sustained temp ramp: FAIL every publish, never healing
            out.append(Fault(step, DIGEST_DEGRADE, arg="@placed:0",
                             seconds=float(90 + 2 * (step - ramp_start))))
            # flapping chip: two FAILs then an OK, forever — one short
            # of the condemn threshold on every cycle
            if (step - ramp_start) % 3 < 2:
                out.append(Fault(step, DIGEST_DEGRADE, arg="@placed:1",
                                 seconds=float(91)))
            else:
                out.append(Fault(step, DIGEST_HEAL, arg="@placed:1"))
            if step % 4 == 1:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(2, 5)))
            if step % 5 == 4:
                out.append(Fault(step, WATCH_DROP))
        return out

    @classmethod
    def _apiserver_brownout(cls, rng, nodes, steps) -> List[Fault]:
        """The apiserver browns out for a seeded window: every list
        fails and every watch stream dies, while the world keeps moving
        (spec mutations, node flaps the operator cannot see). The
        controllers must degrade to stale cached reads — no crash-loop,
        bounded staleness — and fully converge on the backlog once the
        window heals."""
        out: List[Fault] = [
            Fault(0, MUTATE_POLICY, arg=cls._marker(rng, "pre"))]
        start = min(2, steps - 1)
        end = min(start + max(2, steps // 3), steps - 1)
        out.append(Fault(start, BROWNOUT_START,
                         seconds=float(max(0, end - start))))
        out.append(Fault(end, BROWNOUT_END))
        for step in range(start, end):
            if (step - start) % 2 == 0:
                # a mutation the operator is blind to until the heal
                out.append(Fault(step, MUTATE_POLICY,
                                 arg=cls._marker(rng, "dark")))
            if (step - start) % 3 == 1 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, NODE_FLAP, arg=victim))
                out.append(Fault(min(end + 1, steps - 1), NODE_HEAL,
                                 arg=victim))
        for step in range(end, steps):
            # catch-up happens under mild conflict pressure, like a real
            # post-outage thundering herd
            if step % 3 == 0:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(1, 3)))
            if step == end + 1:
                out.append(Fault(step, MUTATE_POLICY,
                                 arg=cls._marker(rng, "post")))
        return out

    @classmethod
    def _shard_failover(cls, rng, nodes, steps) -> List[Fault]:
        """A fleet rollout keeps every reconcile shard churning bulk
        work, node flaps keep the health lane hot, and two of the four
        shards die mid-run — their queued keys must rehash losslessly
        onto the survivors (rendezvous hashing: only the dead shard's
        keys move). Convergence, all standing invariants and the
        lane-priority bound must hold through both failovers, and the
        verdict stays byte-identical per seed. Shard 0 is never a
        victim, so at least one shard always survives."""
        out: List[Fault] = [
            Fault(0, TRIGGER_ROLLOUT,
                  arg=cls._marker(rng, "/opt/shard-libtpu"))]
        kill_steps = sorted(rng.sample(range(2, max(4, steps - 2)), 2))
        for idx, kill_step in enumerate(kill_steps):
            # a same-step CR mutation lands first (faults sort by kind
            # within a step, "mutate-policy" < "shard-kill"), so its
            # watch events are queued on every controller when the shard
            # dies — the kill demonstrably rehashes in-flight keys, not
            # an empty queue. ``count`` seeds the victim preference; the
            # runner kills the busiest killable shard deterministically.
            out.append(Fault(kill_step, MUTATE_POLICY,
                             arg=cls._marker(rng, f"failover-{idx}")))
            out.append(Fault(kill_step, SHARD_KILL,
                             count=rng.randrange(1, 4)))
        join = 0
        for step in range(1, steps):
            if step % 3 == 1 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, NODE_FLAP, arg=victim))
                out.append(Fault(min(step + 2, steps - 1), NODE_HEAL,
                                 arg=victim))
            if step % 4 == 2:
                out.append(Fault(step, API_CONFLICT,
                                 count=rng.randrange(1, 4)))
            if step % 4 == 3:
                # labeled TPU nodes joining pass the policy controller's
                # label predicate on ADDED — live health-lane traffic
                # racing the rollout's bulk churn
                join += 1
                out.append(Fault(step, NODE_ADD,
                                 arg=f"failover-join-{join}"))
            if step % 5 == 4:
                out.append(Fault(step, WATCH_DROP))
        return out

    @classmethod
    def _chip_loss(cls, rng, nodes, steps) -> List[Fault]:
        """Chips disappear from health samples (allocatable drops), come
        back, and operand pods crash-loop in between."""
        out: List[Fault] = []
        for step in range(steps):
            if step % 4 == 0 and nodes:
                victim = rng.choice(nodes)
                out.append(Fault(step, CHIP_LOSS, arg=victim))
                out.append(Fault(min(step + 3, steps - 1), CHIP_RESTORE,
                                 arg=victim))
            if step % 5 == 2 and nodes:
                out.append(Fault(step, POD_CRASH, arg=rng.choice(nodes)))
            if step % 6 == 5:
                out.append(Fault(step, API_UNAVAILABLE, count=1))
        return out

    # -- federation scenarios (``nodes`` is the sorted CELL name list) -----

    @classmethod
    def _federation_load(cls, rng, cells, steps, prefix="freq",
                         front=2) -> List[Fault]:
        """Shared request load for the federation scenarios: elastic
        SliceRequests land on the GLOBAL queue across the opening steps
        and keep trickling, ~a third carrying a data-locality affinity
        (arg suffix ``@<cell>``) the router should honor while the cell
        stays competitive."""
        out: List[Fault] = []
        sizes = (4, 4, 8, 8)
        n = 0
        for step in range(steps):
            burst = rng.randrange(2, 5) if step < front else (
                1 if step % 2 == 0 else 0)
            for _ in range(burst):
                n += 1
                affinity = (rng.choice(cells)
                            if cells and rng.random() < 0.35 else "")
                out.append(Fault(step, SLICE_REQUEST,
                                 arg=f"{prefix}-{n:03d}@{affinity}",
                                 count=rng.choice(sizes)))
        return out

    @classmethod
    def _cell_partition(cls, rng, cells, steps) -> List[Fault]:
        """One cell drops off the global plane for a seeded window while
        request load keeps arriving. The breaker must open (no request
        routed to the Open cell — the no-route-to-open invariant), the
        cell's bound slices are left alone through the window, and past
        the condemnation horizon they migrate cross-cell with no acked
        work lost. A router crash lands mid-window: the rebuilt-from-
        snapshot router must carry the Open/backoff state forward, and
        the restart-coherent rerun must settle byte-identically."""
        out = cls._federation_load(rng, cells, steps)
        victim = rng.choice(cells) if cells else ""
        start = min(2, steps - 1)
        end = min(start + max(3, steps // 2), steps - 1)
        out.append(Fault(start, CELL_PARTITION_START, arg=victim,
                         seconds=float(max(0, end - start))))
        out.append(Fault(end, CELL_PARTITION_END, arg=victim))
        if steps > start + 2:
            out.append(Fault(rng.randrange(start + 1, end), ROUTER_CRASH))
        return out

    @classmethod
    def _stale_digest(cls, rng, cells, steps) -> List[Fault]:
        """One cell stays perfectly reachable but its digest publisher
        wedges: seq stops advancing while the cell's real capacity
        drains under routed load. The router must age-discount the
        frozen digest toward zero — a stale cell fades out of the score
        race — instead of stampeding capacity its last words promised."""
        out = cls._federation_load(rng, cells, steps)
        victim = rng.choice(cells) if cells else ""
        start = min(1, steps - 1)
        end = min(start + max(3, steps // 2), steps - 1)
        out.append(Fault(start, DIGEST_STALE_START, arg=victim,
                         seconds=float(max(0, end - start))))
        out.append(Fault(end, DIGEST_STALE_END, arg=victim))
        return out

    @classmethod
    def _split_brain_router(cls, rng, cells, steps) -> List[Fault]:
        """A shadow router is forked from the primary's snapshot and fed
        the same digest stream in seeded-permuted arrival order, with a
        cell partition thrown in so breaker transitions interleave with
        digest delivery. Every routing decision is cross-checked: any
        divergence is a violation — the arrival-order-independence
        property, run as chaos instead of a unit test."""
        out = cls._federation_load(rng, cells, steps)
        out.append(Fault(0, ROUTER_SPLIT))
        if cells and steps >= 4:
            victim = rng.choice(cells)
            start = min(3, steps - 1)
            end = min(start + 2, steps - 1)
            out.append(Fault(start, CELL_PARTITION_START, arg=victim,
                             seconds=float(max(0, end - start))))
            out.append(Fault(end, CELL_PARTITION_END, arg=victim))
        return out


# mutating verbs a 409 can hit (create 409s are AlreadyExists, a
# different controller path — conflict storms target RV'd writes)
_CONFLICT_VERBS = ("update", "update_status", "patch")


class ChaosClient(Client):
    """Client wrapper injecting armed apiserver faults into every verb.

    Faults are armed as a FIFO; each incoming request consumes the head
    fault if it applies to the request's verb (conflicts only hit RV'd
    writes, throttles/5xx hit anything, latency charges the virtual
    clock and lets the request through). With a synchronous runner the
    consumption order — and therefore the whole run — is deterministic.
    """

    def __init__(self, inner: Client, clock: Optional[VirtualClock] = None):
        self.inner = inner
        self.clock = clock
        self.injected: dict = {}            # kind -> count, for the verdict
        self._armed: List[Fault] = []
        self._watches: List[dict] = []
        self.brownout = False               # lists fail while set

    def set_brownout(self, on: bool) -> None:
        """Enter/exit apiserver brownout: while on, every ``list()``
        raises 503 — the informer cache's relists fail until its breaker
        trips into degraded mode. The runner pairs this with
        ``suspend_watch_streams()`` so reads AND streams are dark."""
        if on and not self.brownout:
            self.record(BROWNOUT_START)
        elif not on and self.brownout:
            self.record(BROWNOUT_END)
        self.brownout = on

    @property
    def supports_chunked_list(self) -> bool:
        # pass-through: list() forwards opts verbatim, so chunking works
        # iff the wrapped client chunks (the cache's relist then pages
        # through the fault injector, eating armed faults per page)
        return getattr(self.inner, "supports_chunked_list", False)

    # -- arming -------------------------------------------------------------

    def arm(self, fault: Fault) -> None:
        """Queue an apiserver fault: count N expands to N queued shots."""
        for _ in range(max(1, fault.count)):
            self._armed.append(fault)

    def record(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1
        OPERATOR_METRICS.chaos_faults_injected.labels(kind=kind).inc()

    def _intercept(self, verb: str) -> None:
        while self._armed:
            fault = self._armed[0]
            if fault.kind == API_LATENCY:
                self._armed.pop(0)
                self.record(API_LATENCY)
                if self.clock is not None:
                    self.clock.advance(fault.seconds)
                continue  # slow, not failed — let the request through
            if fault.kind == API_CONFLICT:
                if verb not in _CONFLICT_VERBS:
                    return  # head stays armed for the next RV'd write
                self._armed.pop(0)
                self.record(API_CONFLICT)
                raise ConflictError(
                    "chaos: the object has been modified; please apply "
                    "your changes to the latest version")
            if fault.kind == API_THROTTLE:
                self._armed.pop(0)
                self.record(API_THROTTLE)
                if self.clock is not None:
                    self.clock.advance(fault.seconds)
                raise TooManyRequestsError(
                    "chaos: too many requests", retry_after=fault.seconds)
            if fault.kind == API_UNAVAILABLE:
                self._armed.pop(0)
                self.record(API_UNAVAILABLE)
                raise ServerUnavailableError(
                    "chaos: the server is currently unable to handle "
                    "the request")
            return  # unknown armed kind: ignore defensively

    # -- Client verbs -------------------------------------------------------

    def get(self, api_version, kind, name, namespace=None,
            metadata_only=False):
        self._intercept("get")
        return self.inner.get(api_version, kind, name, namespace,
                              metadata_only=metadata_only)

    def list(self, api_version, kind, opts: Optional[ListOptions] = None):
        if self.brownout:
            raise ServerUnavailableError(
                "chaos: apiserver brownout — list unavailable")
        self._intercept("list")
        return self.inner.list(api_version, kind, opts)

    def create(self, obj):
        self._intercept("create")
        return self.inner.create(obj)

    def update(self, obj):
        self._intercept("update")
        return self.inner.update(obj)

    def update_status(self, obj):
        self._intercept("update_status")
        return self.inner.update_status(obj)

    def patch(self, api_version, kind, name, patch, namespace=None):
        self._intercept("patch")
        return self.inner.patch(api_version, kind, name, patch, namespace)

    def delete(self, api_version, kind, name, namespace=None):
        self._intercept("delete")
        return self.inner.delete(api_version, kind, name, namespace)

    @property
    def supports_watch_resume(self):
        return getattr(self.inner, "supports_watch_resume", False)

    def watch(self, api_version, kind, handler: Callable,
              since_rv=None) -> Callable:
        kw = {} if since_rv is None else {"since_rv": since_rv}
        entry = {"av": api_version, "kind": kind, "handler": handler,
                 "cancel": self.inner.watch(api_version, kind, handler,
                                            **kw)}
        self._watches.append(entry)

        def cancel():
            entry["cancel"]()
            if entry in self._watches:
                self._watches.remove(entry)

        return cancel

    def suspend_watch_streams(self) -> None:
        """Every active stream dies (the 410 Gone analog). Events
        published while suspended are genuinely lost to the controllers —
        the runner mutates cluster objects in exactly this window."""
        self.record(WATCH_DROP)
        for entry in self._watches:
            entry["cancel"]()

    def resume_watch_streams(self) -> None:
        """Re-establish every suspended stream — the underlying
        ``watch()`` replays ADDED for all live objects, which is exactly
        an informer relist. A client that skipped the relist would
        silently miss every event between drop and resubscribe; pairing
        drops with mutations in the plan makes that failure mode a
        convergence violation, not a mystery."""
        for entry in self._watches:
            entry["cancel"] = self.inner.watch(entry["av"], entry["kind"],
                                               entry["handler"])

    def drop_watch_streams(self) -> None:
        """Suspend + immediately resume: a plain stream reset."""
        self.suspend_watch_streams()
        self.resume_watch_streams()
