#!/usr/bin/env bash
# One-command install/upgrade/uninstall for the TPU operator — the
# reference's `helm install/upgrade/uninstall gpu-operator` UX
# (deployments/gpu-operator/) without requiring Helm. Thin wrapper over
# `tpuop-cfg install|upgrade|uninstall`, which renders the full stream
# from a values file and applies it against $KUBECONFIG (or the
# in-cluster service account).
#
#   scripts/install.sh install  [-f values.yaml] [-n namespace] [--wait]
#   scripts/install.sh upgrade  [-f values.yaml] [-n namespace] [--wait]
#   scripts/install.sh uninstall [--purge-crds]
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}"

VERB="${1:-}"
case "$VERB" in
  install|upgrade|uninstall) shift ;;
  *) echo "usage: $0 install|upgrade|uninstall [args]" >&2; exit 2 ;;
esac

ARGS=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    -f|--values|-n|--namespace)
      [[ $# -ge 2 ]] || { echo "error: $1 requires a value" >&2; exit 2; }
      case "$1" in
        -f|--values) ARGS+=(--values "$2") ;;
        *) ARGS+=(-n "$2") ;;
      esac
      shift 2 ;;
    *) ARGS+=("$1"); shift ;;
  esac
done

# stock distros ship python3 only; prefer it, fall back to python
PY="$(command -v python3 || command -v python)" || {
  echo "python3 not found" >&2; exit 127; }
exec "$PY" -m tpu_operator.cli.tpuop_cfg "$VERB" "${ARGS[@]+"${ARGS[@]}"}"
