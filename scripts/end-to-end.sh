#!/usr/bin/env bash
# Shell e2e — the tests/scripts/end-to-end.sh tier of the reference
# (install-operator -> verify-operator -> workload -> update-clusterpolicy
# -> restart-operator -> uninstall), run against the in-memory cluster so
# it needs no kubeconfig or TPU hardware. CI entrypoint:
#
#     bash scripts/end-to-end.sh
#
# Each stage prints STAGE_OK <name>; the script fails fast on any error.
set -euo pipefail
cd "$(dirname "$0")/.."

PY=${PYTHON:-python}
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

stage() { echo "STAGE_OK $1"; }

# -- install-operator: the full bundle must render and self-validate ------
$PY -m tpu_operator.cli.tpuop_cfg generate all > "$WORK/bundle.yaml"
grep -q "kind: CustomResourceDefinition" "$WORK/bundle.yaml"
grep -q "kind: TPUClusterPolicy" "$WORK/bundle.yaml"
$PY -m tpu_operator.cli.tpuop_cfg generate bundle > "$WORK/csv.yaml"
grep -q "kind: ClusterServiceVersion" "$WORK/csv.yaml"
grep -q "operators.operatorframework.io.bundle.mediatype.v1" "$WORK/csv.yaml"
stage install-manifests

# -- values pipeline: user overrides render a valid, merged CR ------------
cat > "$WORK/values.yaml" <<'EOF'
clusterPolicy:
  spec:
    tpuHealth:
      enabled: true
    metricsExporter:
      serviceMonitor: true
EOF
$PY -m tpu_operator.cli.tpuop_cfg generate all --values "$WORK/values.yaml" \
    > "$WORK/bundle-custom.yaml"
grep -q "serviceMonitor: true" "$WORK/bundle-custom.yaml"
if $PY -m tpu_operator.cli.tpuop_cfg generate all \
       --values <(echo "bogusKey: {}") >/dev/null 2>"$WORK/err"; then
  echo "FAIL: invalid values accepted"; exit 1
fi
grep -q "INVALID values" "$WORK/err"
stage values-pipeline

# -- lifecycle hooks: upgrade-CRD rides the stream, cleanup is explicit ---
cat > "$WORK/hook-values.yaml" <<'EOF'
operator:
  upgradeCRD: true
  cleanupCRD: true
EOF
$PY -m tpu_operator.cli.tpuop_cfg generate all \
    --values "$WORK/hook-values.yaml" > "$WORK/bundle-hooks.yaml" \
    2> "$WORK/hooks.err"
grep -q "tpu-operator-upgrade-crd" "$WORK/bundle-hooks.yaml"
# the DESTRUCTIVE cleanup Job must NOT be in the install stream
if grep -q "tpu-operator-cleanup-crd" "$WORK/bundle-hooks.yaml"; then
  echo "FAIL: cleanup Job leaked into the install stream"; exit 1
fi
grep -q "generate cleanup" "$WORK/hooks.err"   # the reminder note
$PY -m tpu_operator.cli.tpuop_cfg generate cleanup > "$WORK/cleanup.yaml"
grep -q "tpu-operator-cleanup-crd" "$WORK/cleanup.yaml"
stage lifecycle-hooks

# -- offline CR validation (gpuop-cfg slot) -------------------------------
$PY - > "$WORK/policy.yaml" <<'EOF'
import yaml
from tpu_operator.deploy.packaging import sample_cluster_policy
print(yaml.safe_dump(sample_cluster_policy()), end="")
EOF
$PY -m tpu_operator.cli.tpuop_cfg validate clusterpolicy -f "$WORK/policy.yaml"
stage validate-clusterpolicy

# -- verify-operator: reconcile the fake cluster to all-operands-Ready ----
$PY -m tpu_operator.cli.operator --fake-cluster --once > "$WORK/op1.log" 2>&1
grep -q "reached ready" "$WORK/op1.log"
stage verify-operator

# -- restart-operator: a fresh manager must converge again (stateless) ----
$PY -m tpu_operator.cli.operator --fake-cluster --once > "$WORK/op2.log" 2>&1
grep -q "reached ready" "$WORK/op2.log"
stage restart-operator

# -- per-node validation components (validator barrier protocol) ----------
export TPU_VALIDATION_DIR="$WORK/validations"
mkdir -p "$TPU_VALIDATION_DIR"
TPU_FAKE_CHIPS=4 $PY -m tpu_operator.cli.validator -c driver
test -f "$TPU_VALIDATION_DIR/driver-ready"
TPU_FAKE_CHIPS=4 $PY -m tpu_operator.cli.validator -c runtime
$PY -m tpu_operator.cli.validator -c dcn   # single-slice skip path
test -f "$TPU_VALIDATION_DIR/dcn-ready"
$PY -m tpu_operator.cli.validator cleanup
test ! -f "$TPU_VALIDATION_DIR/driver-ready"
stage validator-components

# -- workload proof (the cuda-workload slot): single-device JAX matmul ----
JAX_PLATFORMS=cpu TPU_VALIDATOR_ALLOW_CPU=true MATMUL_SIZE=256 \
    $PY -m tpu_operator.cli.validator -c jax
test -f "$TPU_VALIDATION_DIR/jax-ready"
stage workload-proof

# -- isolated-workload plane (sandbox tier): fence -> vTPU -> proofs ------
export TPU_FENCING_FILE="$WORK/fencing.json" TPU_VTPU_FILE="$WORK/vtpu.json"
export TPU_FAKE_CHIPS=4 TPU_WORKLOAD_CONFIG=virtual
if $PY -m tpu_operator.cli.validator -c fencing 2>/dev/null; then
  echo "FAIL: fencing proof passed without a fence"; exit 1
fi
$PY - <<'EOF'
import os
from tpu_operator.isolation.fencing import write_fencing_file
from tpu_operator.isolation.vtpu import VTPUProfile, build_vtpu_devices, write_vtpu_file
write_fencing_file(os.environ["TPU_FENCING_FILE"], ["accel0", "accel1"],
                   "accel0,accel1")
write_vtpu_file(os.environ["TPU_VTPU_FILE"], VTPUProfile("vtpu-2", 2),
                build_vtpu_devices(["accel0", "accel1"],
                                   VTPUProfile("vtpu-2", 2), 16384))
EOF
$PY -m tpu_operator.cli.validator -c fencing
test -f "$TPU_VALIDATION_DIR/fencing-ready"
$PY -m tpu_operator.cli.validator -c vtpu
test -f "$TPU_VALIDATION_DIR/vtpu-ready"
unset TPU_FENCING_FILE TPU_VTPU_FILE TPU_FAKE_CHIPS TPU_WORKLOAD_CONFIG
stage isolated-plane

# -- optional live-cluster tier (the holodeck/kind slot) ------------------
# Opt-in: TPUOP_E2E_LIVE=1 with KUBECONFIG pointing at a real cluster
# (e.g. kind) runs the actual lifecycle there: install --wait, drift
# check, uninstall. The reference runs this tier on provisioned cloud
# instances (tests/holodeck.yaml, tests/e2e/gpu_operator_test.go:36-100);
# without TPU nodes the CR sits notReady, so --wait is only enforced
# when TPUOP_E2E_EXPECT_READY=1 (a cluster with TPU-labeled nodes).
if [[ "${TPUOP_E2E_LIVE:-}" == "1" && -n "${KUBECONFIG:-}" ]]; then
  if [[ "${TPUOP_E2E_EXPECT_READY:-}" == "1" ]]; then
    $PY -m tpu_operator.cli.tpuop_cfg install --wait \
        --timeout "${TPUOP_E2E_TIMEOUT:-300}"
  else
    $PY -m tpu_operator.cli.tpuop_cfg install
  fi
  $PY -m tpu_operator.cli.tpuop_cfg diff       # fresh install: no drift
  $PY -m tpu_operator.cli.tpuop_cfg uninstall --purge-crds
  stage live-cluster
fi

echo "END_TO_END_OK"
