#!/usr/bin/env python3
"""One-shot HBM STREAM-triad tuning sweep on the live chip.

Runs pallas_probe across a small (size_mb, iters) grid in ONE process
(one backend init — chip-hygiene: never spawn parallel JAX clients at a
tunneled chip) and prints a JSON report. Used to pick the bench's triad
configuration; the round-3 matmul sweep (BENCH_LOCAL_r03.json) is the
pattern.

    python scripts/hbm_sweep.py            # defaults
    python scripts/hbm_sweep.py --quick    # 3-point grid
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from tpu_operator.workloads import backend, pallas_probe

    # JAX_PLATFORMS must stay authoritative even under the axon plugin
    # (a cpu-pinned smoke must never block on the remote tunnel)
    backend.honor_jax_platforms_env()
    try:
        devices = backend.init_devices(attempts=1)
    except Exception as e:  # JSON contract holds even when init fails
        print(json.dumps({"error": f"backend init failed: "
                                   f"{type(e).__name__}: {e}"}))
        return 1
    if devices[0].platform != "tpu":
        print(json.dumps({"error": f"platform={devices[0].platform}, "
                                   f"not tpu"}))
        return 1
    grid = [(256.0, 24), (512.0, 24), (1024.0, 24)] if args.quick else [
        (256.0, 24), (512.0, 16), (512.0, 24), (512.0, 48),
        (1024.0, 24), (2048.0, 16), (2048.0, 24)]
    results = {}
    best = (None, 0.0)  # compares on fraction when known, else GB/s —
    # an unknown chip (no spec entry) still gets a usable best pick
    for size_mb, iters in grid:
        r = pallas_probe.run(size_mb=size_mb, iters=iters, repeats=2)
        key = f"{size_mb:.0f}MBx{iters}"
        results[key] = {
            "bandwidth_gbps": round(r.bandwidth_gbps, 1),
            "fraction_of_peak": (round(r.fraction_of_peak, 4)
                                 if r.fraction_of_peak is not None else None),
            "correct": r.correct,
        }
        print(f"# {key}: {results[key]}", file=sys.stderr)
        score = (r.fraction_of_peak if r.fraction_of_peak is not None
                 else r.bandwidth_gbps)
        if r.correct and score > best[1]:
            best = (key, score)
    print(json.dumps({"device_kind": getattr(devices[0], "device_kind", ""),
                      "results": results,
                      "best": {"config": best[0],
                               "score": round(best[1], 4)}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
