#!/usr/bin/env python3
"""One-shot HBM STREAM-triad tuning sweep on the live chip.

Runs pallas_probe across a small (size_mb, iters) grid in ONE process
(one backend init — chip-hygiene: never spawn parallel JAX clients at a
tunneled chip) and prints a JSON report. Used to pick the bench's triad
configuration; the round-3 matmul sweep (BENCH_LOCAL_r03.json) is the
pattern.

    python scripts/hbm_sweep.py            # defaults
    python scripts/hbm_sweep.py --quick    # 3-point grid
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    sys.path.insert(0, ".")
    from tpu_operator.workloads import backend, pallas_probe

    # JAX_PLATFORMS must stay authoritative even under the axon plugin
    # (a cpu-pinned smoke must never block on the remote tunnel)
    backend.honor_jax_platforms_env()
    devices = backend.init_devices(attempts=1)
    if devices[0].platform != "tpu":
        print(json.dumps({"error": f"platform={devices[0].platform}, "
                                   f"not tpu"}))
        return 1
    grid = [(256.0, 24), (512.0, 24), (1024.0, 24)] if args.quick else [
        (256.0, 24), (512.0, 16), (512.0, 24), (512.0, 48),
        (1024.0, 24), (2048.0, 16), (2048.0, 24)]
    results = {}
    best = (None, 0.0)
    for size_mb, iters in grid:
        r = pallas_probe.run(size_mb=size_mb, iters=iters, repeats=2)
        key = f"{size_mb:.0f}MBx{iters}"
        results[key] = {
            "bandwidth_gbps": round(r.bandwidth_gbps, 1),
            "fraction_of_peak": (round(r.fraction_of_peak, 4)
                                 if r.fraction_of_peak is not None else None),
            "correct": r.correct,
        }
        print(f"# {key}: {results[key]}", file=sys.stderr)
        frac = r.fraction_of_peak or 0.0
        if r.correct and frac > best[1]:
            best = (key, frac)
    print(json.dumps({"device_kind": getattr(devices[0], "device_kind", ""),
                      "results": results,
                      "best": {"config": best[0],
                               "fraction_of_peak": round(best[1], 4)}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
