"""Upgrade controller: per-node FSM, budget, drain semantics
(upgrade_controller.go tier) — plus the TPU-specific slice-grouped and
failure-path semantics (eviction drain with PDBs + deadlines into
`failed`)."""

from tpu_operator.api import V1, KIND_CLUSTER_POLICY, new_cluster_policy
from tpu_operator.api import labels as L
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
)
from tpu_operator.controllers.upgrade_controller import (
    STATE_DONE,
    STATE_DRAIN,
    STATE_FAILED,
    STATE_UPGRADE_REQUIRED,
    STATE_VALIDATION,
    UpgradeReconciler,
)
from tpu_operator.runtime import FakeClient, ListOptions, Request
from tpu_operator.runtime.objects import get_nested, labels_of, name_of, thaw_obj


def build_converged_cluster(n_nodes=2, auto_upgrade=True):
    """Fake cluster with the driver DS deployed and ready on every node."""
    c = FakeClient()
    for i in range(n_nodes):
        c.add_node(f"tpu-{i}", labels={
            L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
            L.GKE_TPU_TOPOLOGY: "2x2x1",
            L.GKE_ACCELERATOR_COUNT: "4"},
            allocatable={"google.com/tpu": "4"})
    c.create(new_cluster_policy(spec={
        "upgradePolicy": {"autoUpgrade": auto_upgrade,
                          "maxParallelUpgrades": 1}}))
    prec = ClusterPolicyReconciler(client=c, namespace="tpu-operator")
    prec.reconcile(Request(name="tpu-cluster-policy"))
    c.simulate_kubelet(ready=True)
    prec.reconcile(Request(name="tpu-cluster-policy"))
    return c, prec


def change_driver_spec(c, prec):
    """Bump the libtpu config so the driver DS template changes; OnDelete
    keeps existing pods on the old revision."""
    cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
    spec = cr.get("spec") or {}
    spec["libtpu"] = {"installDir": "/opt/new-libtpu"}
    cr["spec"] = spec
    c.update(cr)
    prec.reconcile(Request(name="tpu-cluster-policy"))
    c.simulate_kubelet(ready=True)


def driver_pods(c):
    return c.list("v1", "Pod", ListOptions(
        label_selector={"tpu.graft.dev/component": "libtpu-driver"}))


class TestUpgradeFSM:
    def test_noop_when_current(self):
        c, _ = build_converged_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        result = rec.reconcile(Request(name="tpu-cluster-policy"))
        assert result.requeue_after == 120.0
        for node in c.list("v1", "Node"):
            assert labels_of(node).get(L.UPGRADE_STATE) in (None, STATE_DONE)

    def test_auto_upgrade_off_strips_labels(self):
        c, _ = build_converged_cluster(auto_upgrade=False)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"labels": {L.UPGRADE_STATE: "upgrade-required"}}})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))

    def test_single_node_full_upgrade_cycle(self):
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        # pod still on old revision (OnDelete)
        [pod] = driver_pods(c)
        old_hash = labels_of(pod)["controller-revision-hash"]
        # pass 1: cordon + drain + delete driver pod -> validation wait
        result = rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_VALIDATION
        assert get_nested(node, "spec", "unschedulable") is True
        assert driver_pods(c) == []  # driver pod deleted
        assert result.requeue_after == 5.0
        # kubelet recreates the pod on the new revision
        c.simulate_kubelet(ready=True)
        [pod] = driver_pods(c)
        assert labels_of(pod)["controller-revision-hash"] != old_hash
        # pass 2: validation passes -> uncordon -> done
        result = rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_DONE
        assert not get_nested(node, "spec", "unschedulable", default=False)
        assert result.requeue_after == 120.0

    def test_validation_waits_for_validator_pods(self):
        # after the driver restarts, the node's validator pods must
        # re-prove the stack before uncordon — driver readiness alone is
        # not validation (cmd/gpu-operator/main.go:151 semantics)
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # validator pods were deleted along with the driver pod
        assert rec._validator_pods_by_node().get("tpu-0", []) == []
        c.simulate_kubelet(ready=True)
        # force the recreated validator pod NotReady: validation must hold
        for pod in rec._validator_pods_by_node().get("tpu-0", []):
            pod = thaw_obj(pod)
            for cond in get_nested(pod, "status", "conditions",
                                   default=[]) or []:
                if cond.get("type") == "Ready":
                    cond["status"] = "False"
            c.update(pod)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_VALIDATION
        # validator recovers -> upgrade completes
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_DONE

    def test_parallel_budget_respected(self):
        c, prec = build_converged_cluster(n_nodes=3)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        states = [labels_of(n).get(L.UPGRADE_STATE)
                  for n in c.list("v1", "Node")]
        # maxParallelUpgrades=1: exactly one node advanced past
        # upgrade-required
        assert states.count(STATE_UPGRADE_REQUIRED) == 2
        assert states.count(STATE_VALIDATION) == 1

    def test_drain_evicts_tpu_workloads_but_respects_skip_label(self):
        c, prec = build_converged_cluster(n_nodes=1)
        for name, skip in (("train-job", False), ("sacred-job", True)):
            labels = {L.UPGRADE_SKIP_DRAIN: "true"} if skip else {}
            c.create({"apiVersion": "v1", "kind": "Pod",
                      "metadata": {"name": name, "namespace": "default",
                                   "labels": labels},
                      "spec": {"nodeName": "tpu-0",
                               "containers": [{
                                   "name": "t",
                                   "resources": {"requests":
                                                 {"google.com/tpu": "4"}}}]},
                      "status": {"phase": "Running"}})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert c.get_or_none("v1", "Pod", "train-job", "default") is None
        assert c.get_or_none("v1", "Pod", "sacred-job", "default") is not None

    def test_eventual_full_fleet_upgrade(self):
        c, prec = build_converged_cluster(n_nodes=3)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        for _ in range(12):  # budget 1 -> a few passes per node
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        states = {labels_of(n).get(L.UPGRADE_STATE)
                  for n in c.list("v1", "Node")}
        assert states == {STATE_DONE}
        # and all driver pods are on the new revision + nodes schedulable
        for node in c.list("v1", "Node"):
            assert not get_nested(node, "spec", "unschedulable", default=False)


def build_mixed_cluster(auto_upgrade=True, max_parallel=1):
    """2-host v5p slice (multi-host: 2x2x2 = 8 chips > 4/host) sharing one
    gke-nodepool, plus one independent single-host node."""
    c = FakeClient()
    for name in ("slice-h0", "slice-h1"):
        c.add_node(name, labels={
            L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
            L.GKE_TPU_TOPOLOGY: "2x2x2",
            L.GKE_NODEPOOL: "pool-slice-a",
            L.GKE_ACCELERATOR_COUNT: "4"},
            allocatable={"google.com/tpu": "4"})
    c.add_node("z-single-0", labels={
        L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
        L.GKE_TPU_TOPOLOGY: "2x2x1",
        L.GKE_ACCELERATOR_COUNT: "4"},
        allocatable={"google.com/tpu": "4"})
    c.create(new_cluster_policy(spec={
        "upgradePolicy": {"autoUpgrade": auto_upgrade,
                          "maxParallelUpgrades": max_parallel}}))
    prec = ClusterPolicyReconciler(client=c, namespace="tpu-operator")
    prec.reconcile(Request(name="tpu-cluster-policy"))
    c.simulate_kubelet(ready=True)
    prec.reconcile(Request(name="tpu-cluster-policy"))
    return c, prec


def node_state(c, name):
    return labels_of(c.get("v1", "Node", name)).get(L.UPGRADE_STATE)


class TestSliceGroupedUpgrades:
    """Multi-host slices move through the FSM as ONE unit: no slice ever
    runs mixed libtpu versions across its hosts (SURVEY.md section 7
    grouped-readiness hard part; VERDICT r2 item 3)."""

    def test_slice_hosts_move_together(self):
        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        # budget=1: the slice (one unit) starts; the single host must wait
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_VALIDATION
        assert node_state(c, "slice-h1") == STATE_VALIDATION
        assert node_state(c, "z-single-0") == STATE_UPGRADE_REQUIRED
        # both slice hosts cordoned, both driver pods deleted together
        for name in ("slice-h0", "slice-h1"):
            assert get_nested(c.get("v1", "Node", name), "spec",
                              "unschedulable") is True
        assert all(get_nested(p, "spec", "nodeName") == "z-single-0"
                   for p in driver_pods(c))
        # kubelet recreates on the new revision -> both validate together,
        # then the single host takes its turn
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_DONE
        assert node_state(c, "slice-h1") == STATE_DONE
        for _ in range(4):
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        assert node_state(c, "z-single-0") == STATE_DONE

    def test_slice_never_half_validated(self):
        """If one host of the slice fails to re-prove, the whole unit
        stays in validation — the upgraded host is NOT uncordoned alone."""
        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        c.simulate_kubelet(ready=True)
        # force h1's recreated validator NotReady
        for pod in rec._validator_pods_by_node().get("slice-h1", []):
            pod = thaw_obj(pod)
            for cond in get_nested(pod, "status", "conditions",
                                   default=[]) or []:
                if cond.get("type") == "Ready":
                    cond["status"] = "False"
            c.update(pod)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_VALIDATION
        assert node_state(c, "slice-h1") == STATE_VALIDATION
        # h0 stays cordoned while its slice sibling is unproven
        assert get_nested(c.get("v1", "Node", "slice-h0"), "spec",
                          "unschedulable") is True

    def test_budget_counts_units_not_nodes(self):
        """maxParallelUpgrades=1 still lets a whole 2-host slice proceed
        at once (it is one unit), where 2 independent hosts could not."""
        c, prec = build_mixed_cluster(max_parallel=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        in_flight = [n for n in ("slice-h0", "slice-h1", "z-single-0")
                     if node_state(c, n) == STATE_VALIDATION]
        assert sorted(in_flight) == ["slice-h0", "slice-h1"]

    def test_healing_diverged_member_label(self):
        """A wiped member label re-syncs to the unit's earliest stage
        instead of letting hosts drift apart."""
        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        c.patch("v1", "Node", "slice-h1",
                {"metadata": {"labels": {L.UPGRADE_STATE: None}}})
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # the unit converges: both hosts end in the same state
        assert node_state(c, "slice-h0") == node_state(c, "slice-h1")

    def test_wiped_state_and_stamp_heal_without_losing_the_unit(self):
        """Both stage label AND stage-started stamp wiped on one member
        mid-upgrade (the partial-write/restart shape): the next pass
        re-syncs the member to the unit's surviving stage WITHOUT
        waiting for a transition, and the stage deadline (anchored on
        the surviving member's stamp) still fires for the whole unit."""
        clock = [5000.0]
        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h1") == STATE_VALIDATION
        c.patch("v1", "Node", "slice-h1",
                {"metadata": {"labels": {L.UPGRADE_STATE: None},
                              "annotations": {
                                  L.UPGRADE_STAGE_STARTED: None}}})
        # block validation so the unit is parked, not transitioning
        for pod in rec._validator_pods_by_node().get("slice-h0", []):
            pod = thaw_obj(pod)
            for cond in get_nested(pod, "status", "conditions",
                                   default=[]) or []:
                if cond.get("type") == "Ready":
                    cond["status"] = "False"
            c.update(pod)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_VALIDATION
        assert node_state(c, "slice-h1") == STATE_VALIDATION
        # the validation deadline survived the wipe: the unit fails
        # together instead of h1 wedging label-less forever
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_FAILED
        assert node_state(c, "slice-h1") == STATE_FAILED

    def test_diverged_members_resync_to_earliest_stage(self):
        """When members report different stages (a crash between the
        per-node label writes), the unit's aggregate is the EARLIEST
        stage — the host that got ahead is dragged back and the pair
        re-walks together, never leaving one host upgraded alone."""
        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_VALIDATION
        # h1 crashed back to drain-required; h0 still says validation
        c.patch("v1", "Node", "slice-h1",
                {"metadata": {"labels": {
                    L.UPGRADE_STATE: STATE_DRAIN}}})
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # the unit re-walked from drain as one: both members agree and
        # neither was uncordoned while the other was mid-stage
        assert node_state(c, "slice-h0") == node_state(c, "slice-h1")
        c.simulate_kubelet(ready=True)
        for _ in range(4):
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        assert node_state(c, "slice-h0") == STATE_DONE
        assert node_state(c, "slice-h1") == STATE_DONE


def add_tpu_pod(c, name, node, labels=None, ready=True):
    conditions = [{"type": "Ready", "status": "True" if ready else "False"}]
    c.create({"apiVersion": "v1", "kind": "Pod",
              "metadata": {"name": name, "namespace": "default",
                           "labels": labels or {}},
              "spec": {"nodeName": node,
                       "containers": [{
                           "name": "t",
                           "resources": {"requests":
                                         {"google.com/tpu": "4"}}}]},
              "status": {"phase": "Running", "conditions": conditions}})


class TestEvictionDrain:
    """Drain goes through the Eviction API: PodDisruptionBudgets block it
    (429) until the drain deadline, which forces or fails per policy
    (upgrade_controller.go:157-187 drain-spec semantics)."""

    def pdb(self, c, match, min_available=1):
        c.create({"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
                  "metadata": {"name": "guard", "namespace": "default"},
                  "spec": {"selector": {"matchLabels": match},
                           "minAvailable": min_available}})

    def test_pdb_blocks_drain_until_timeout_then_failed(self):
        clock = [1000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        add_tpu_pod(c, "guarded", "tpu-0", labels={"app": "guarded"})
        self.pdb(c, {"app": "guarded"})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # eviction blocked: still draining, pod alive, node cordoned
        assert node_state(c, "tpu-0") == STATE_DRAIN
        assert c.get_or_none("v1", "Pod", "guarded", "default") is not None
        # past the drain deadline without drainForce -> failed
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        anns = c.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert "drain timed out" in anns[L.UPGRADE_FAILED_REASON]
        assert c.get_or_none("v1", "Pod", "guarded", "default") is not None

    def test_drain_force_deletes_at_deadline(self):
        clock = [1000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["upgradePolicy"]["drainForce"] = True
        c.update(cr)
        add_tpu_pod(c, "guarded", "tpu-0", labels={"app": "guarded"})
        self.pdb(c, {"app": "guarded"})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DRAIN
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # force kicked in: pod deleted, FSM moved on past drain
        assert c.get_or_none("v1", "Pod", "guarded", "default") is None
        assert node_state(c, "tpu-0") == STATE_VALIDATION

    def test_eviction_proceeds_when_pdb_has_headroom(self):
        c, prec = build_converged_cluster(n_nodes=1)
        add_tpu_pod(c, "a", "tpu-0", labels={"app": "multi"})
        # a second READY replica elsewhere keeps the budget satisfied
        c.add_node("other", labels={L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
                                    L.GKE_TPU_TOPOLOGY: "2x2x1"})
        add_tpu_pod(c, "b", "other", labels={"app": "multi"})
        self.pdb(c, {"app": "multi"}, min_available=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert c.get_or_none("v1", "Pod", "a", "default") is None
        assert c.get_or_none("v1", "Pod", "b", "default") is not None

    def test_drain_respects_custom_timeout(self):
        clock = [0.0]
        c, prec = build_converged_cluster(n_nodes=1)
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["upgradePolicy"]["drainTimeoutSeconds"] = 10
        c.update(cr)
        add_tpu_pod(c, "guarded", "tpu-0", labels={"app": "guarded"})
        self.pdb(c, {"app": "guarded"})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DRAIN
        clock[0] += 11
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED


class TestUpgradeFailureSemantics:
    """STATE_FAILED is reachable, alertable, and recoverable: validation
    deadlines fail the node; failed nodes retry after backoff (VERDICT r2
    weak 3 / item 4)."""

    def test_validation_timeout_drives_node_to_failed(self):
        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_VALIDATION
        # the validator never re-proves (no kubelet recreation). Before
        # the deadline: still validating
        clock[0] += 100
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_VALIDATION
        clock[0] += 250  # past validationTimeoutSeconds=300
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        anns = c.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert "validation timed out" in anns[L.UPGRADE_FAILED_REASON]

    def test_failed_node_retries_after_backoff_and_recovers(self):
        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        # within backoff: stays failed
        clock[0] += 10
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        # past failedRetryBackoffSeconds=60: re-enters the FSM; with the
        # kubelet recreating pods the retry completes the upgrade
        clock[0] += 60
        rec.reconcile(Request(name="tpu-cluster-policy"))
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DONE
        anns = c.get("v1", "Node", "tpu-0")["metadata"].get(
            "annotations") or {}
        assert L.UPGRADE_FAILED_REASON not in anns

    def test_upgrade_units_metric_counts_slices_once(self):
        from tpu_operator.metrics.operator_metrics import OPERATOR_METRICS

        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # the 2-host slice is in flight = ONE unit (nodes gauge says 2)
        assert OPERATOR_METRICS.upgrade_units_in_progress._value.get() == 1
        assert OPERATOR_METRICS.driver_upgrades_in_progress._value.get() == 2

    def test_failed_state_surfaced_in_metrics(self):
        from tpu_operator.metrics.operator_metrics import OPERATOR_METRICS

        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        gauge = OPERATOR_METRICS.upgrade_state_nodes.labels(
            state=STATE_FAILED)
        assert gauge._value.get() == 1

    def test_whole_slice_fails_and_retries_together(self):
        clock = [5000.0]
        c, prec = build_mixed_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_FAILED
        assert node_state(c, "slice-h1") == STATE_FAILED
        clock[0] += 61
        rec.reconcile(Request(name="tpu-cluster-policy"))
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "slice-h0") == STATE_DONE
        assert node_state(c, "slice-h1") == STATE_DONE


class TestReviewRegressions:
    def test_validation_waits_for_driver_pod_recreation(self):
        """With no validator gate deployed, a unit must still not pass
        validation while its driver pod is absent mid-restart."""
        c, prec = build_converged_cluster(n_nodes=1)
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["validator"] = {"enabled": False}
        c.update(cr)
        prec.reconcile(Request(name="tpu-cluster-policy"))
        c.simulate_kubelet(ready=True)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # driver pod deleted by POD_RESTART; no kubelet recreation yet:
        # another pass must hold in validation, cordon intact
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_VALIDATION
        assert get_nested(c.get("v1", "Node", "tpu-0"), "spec",
                          "unschedulable") is True
        # kubelet recreates on the new revision -> completes
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DONE

    def test_opted_out_host_excludes_whole_slice(self):
        """Pausing one host of a multi-host slice must pause the slice —
        upgrading the rest alone would run mixed libtpu versions over one
        ICI fabric."""
        c, prec = build_mixed_cluster()
        c.patch("v1", "Node", "slice-h1",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        for _ in range(6):
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        # neither slice host entered the FSM; the single host converged
        assert node_state(c, "slice-h0") is None
        assert node_state(c, "slice-h1") is None
        assert node_state(c, "z-single-0") == STATE_DONE
        # both slice driver pods still on the OLD revision (no mixed state)
        hashes = {labels_of(p)["controller-revision-hash"]
                  for p in driver_pods(c)
                  if get_nested(p, "spec", "nodeName") != "z-single-0"}
        assert len(hashes) == 1

    def test_pdb_match_expressions_blocks_eviction(self):
        from tpu_operator.runtime.client import EvictionBlockedError

        c = FakeClient()
        add_tpu_pod(c, "guarded", "n0", labels={"app": "guarded"})
        c.create({"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
                  "metadata": {"name": "guard", "namespace": "default"},
                  "spec": {"selector": {"matchExpressions": [
                      {"key": "app", "operator": "In",
                       "values": ["guarded"]}]},
                      "minAvailable": 1}})
        import pytest as _pytest
        with _pytest.raises(EvictionBlockedError):
            c.evict("guarded", "default")

    def test_terminating_driver_pod_does_not_shadow_replacement(self):
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        [pod] = driver_pods(c)
        # mark the live pod Terminating: the map must not include it
        c.patch("v1", "Pod", pod["metadata"]["name"],
                {"metadata": {"deletionTimestamp": "2026-01-01T00:00:00Z"}},
                pod["metadata"]["namespace"])
        assert rec._driver_pods_by_node() == {}


class TestFailureReleaseAndHealing:
    def test_disabling_upgrade_uncordons_failed_node(self):
        """A failed node stays cordoned while the FSM owns it, but turning
        autoUpgrade off must release the cordon along with the label."""
        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        assert get_nested(c.get("v1", "Node", "tpu-0"), "spec",
                          "unschedulable") is True
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["upgradePolicy"]["autoUpgrade"] = False
        c.update(cr)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert L.UPGRADE_STATE not in labels_of(node)
        assert not get_nested(node, "spec", "unschedulable", default=False)

    def test_unstamped_drain_state_still_times_out(self):
        """A drain-required label with no stage-started annotation (older
        operator version / recreated Node) must not wedge: the controller
        stamps a deadline on first sight and the timeout then fires."""
        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        add_tpu_pod(c, "guarded", "tpu-0", labels={"app": "guarded"})
        c.create({"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
                  "metadata": {"name": "guard", "namespace": "default"},
                  "spec": {"selector": {"matchLabels": {"app": "guarded"}},
                           "minAvailable": 1}})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        # simulate the legacy state: label written, stamp missing
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"labels": {L.UPGRADE_STATE: STATE_DRAIN}},
                 "spec": {"unschedulable": True}})
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DRAIN  # stamped, waiting
        clock[0] += 301
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED


class TestPerNodeUpgradeOptOut:
    """VERDICT round-1 item 10: the driver-upgrade-enabled annotation lets
    an operator pause a single node's rollout without CR spec surgery."""

    def test_annotation_pause_excludes_node(self):
        c, prec = build_converged_cluster(n_nodes=2)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        change_driver_spec(c, prec)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        for _ in range(8):
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        # paused node never entered the FSM; the other converged
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))
        assert labels_of(c.get("v1", "Node", "tpu-1")).get(
            L.UPGRADE_STATE) == STATE_DONE

    def test_pause_mid_rollout_strips_fsm_label(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert labels_of(c.get("v1", "Node", "tpu-0")).get(L.UPGRADE_STATE)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "paused"}}})
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))

    def test_cr_annotation_pauses_whole_rollout(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr.setdefault("metadata", {}).setdefault("annotations", {})[
            L.DRIVER_UPGRADE_ENABLED] = "false"
        c.update(cr)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))

    def test_node_pause_survives_policy_reconcile(self):
        c, prec = build_converged_cluster(n_nodes=1)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        prec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert node["metadata"]["annotations"][
            L.DRIVER_UPGRADE_ENABLED] == "false"

    def test_pause_mid_rollout_uncordons(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node).get(L.UPGRADE_STATE)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "paused"}}})
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert L.UPGRADE_STATE not in labels_of(node)
        assert not get_nested(node, "spec", "unschedulable", default=False)

    def test_node_pause_survives_global_disable_cycle(self):
        c, prec = build_converged_cluster(n_nodes=2)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["upgradePolicy"] = {"autoUpgrade": False}
        c.update(cr)
        prec.reconcile(Request(name="tpu-cluster-policy"))
        # reconciler-stamped "true" unwound; explicit pause preserved
        anns0 = c.get("v1", "Node", "tpu-0")["metadata"].get(
            "annotations") or {}
        anns1 = c.get("v1", "Node", "tpu-1")["metadata"].get(
            "annotations") or {}
        assert anns0.get(L.DRIVER_UPGRADE_ENABLED) == "false"
        assert L.DRIVER_UPGRADE_ENABLED not in anns1
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["upgradePolicy"] = {"autoUpgrade": True}
        c.update(cr)
        prec.reconcile(Request(name="tpu-cluster-policy"))
        anns0 = c.get("v1", "Node", "tpu-0")["metadata"].get(
            "annotations") or {}
        assert anns0.get(L.DRIVER_UPGRADE_ENABLED) == "false"

    def test_sandbox_plane_halts_rollout(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["sandboxWorkloads"] = {"enabled": True}
        c.update(cr)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))


class TestUpgradeEvents:
    """Node Events at every FSM transition (the reference upgrade lib's
    recorder calls, drain_manager.go:105-129): kubectl describe node
    shows the rollout's footprint."""

    def test_full_walk_emits_start_and_complete(self):
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DONE
        reasons = {(e["involvedObject"]["name"], e["reason"], e["type"])
                   for e in c.list("v1", "Event")}
        assert ("tpu-0", "DriverUpgradeStarted", "Normal") in reasons
        assert ("tpu-0", "DriverUpgradeComplete", "Normal") in reasons

    def test_validation_timeout_emits_failure_warning(self):
        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator",
                                now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        clock[0] += 10_000  # blow through the validation deadline
        rec.reconcile(Request(name="tpu-cluster-policy"))
        events = [e for e in c.list("v1", "Event")
                  if e["reason"] == "DriverUpgradeFailed"]
        assert events, "no DriverUpgradeFailed event"
        assert events[0]["type"] == "Warning"
        assert "timed out" in events[0]["message"]


class TestTPUDriverCRUpgradePath:
    """The rolling-upgrade FSM selects driver DaemonSets by the
    component label, so per-pool DaemonSets rendered by the TPUDriver CR
    (engine-B path) roll through the same cordon/drain/validate walk as
    the ClusterPolicy-rendered one — prove it end to end."""

    def test_tpudriver_rendered_ds_rolls_through_fsm(self):
        from tpu_operator.api.tpudriver import V1ALPHA1, new_tpu_driver
        from tpu_operator.controllers.tpudriver_controller import (
            TPUDriverReconciler,
        )

        c = FakeClient()
        c.add_node("tpu-0", labels={
            L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
            L.GKE_TPU_TOPOLOGY: "2x2x1",
            L.GKE_ACCELERATOR_COUNT: "4",
            L.deploy_label("libtpu-driver"): "true"},
            allocatable={"google.com/tpu": "4"})
        c.create(new_cluster_policy(spec={
            "libtpu": {"enabled": False},  # CRD mode: no policy-owned DS
            "upgradePolicy": {"autoUpgrade": True,
                              "maxParallelUpgrades": 1}}))
        prec = ClusterPolicyReconciler(client=c, namespace="tpu-operator")
        prec.reconcile(Request(name="tpu-cluster-policy"))
        c.create(new_tpu_driver("pool-a"))
        drec = TPUDriverReconciler(client=c, namespace="tpu-operator")
        drec.reconcile(Request(name="pool-a"))
        c.simulate_kubelet(ready=True)
        drec.reconcile(Request(name="pool-a"))
        cr = thaw_obj(c.get(V1ALPHA1, "TPUDriver", "pool-a"))
        assert cr["status"]["state"] == "ready"

        # change the driver flavor: OnDelete keeps the old pod running
        cr["spec"] = {"installDir": "/opt/new-flavor"}
        c.update(cr)
        drec.reconcile(Request(name="pool-a"))
        c.simulate_kubelet(ready=True)

        urec = UpgradeReconciler(client=c, namespace="tpu-operator")
        urec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_VALIDATION
        assert get_nested(node, "spec", "unschedulable") is True
        c.simulate_kubelet(ready=True)  # kubelet recreates on new revision
        urec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_DONE
        assert not get_nested(node, "spec", "unschedulable", default=False)


class TestIsolatedPlaneDrain:
    def test_isolated_and_vtpu_pods_are_drained_too(self):
        """gpuPodSpecFilter prefix semantics (main.go:198-207): pods
        holding google.com/tpu-isolated or google.com/vtpu occupy chips
        exactly like google.com/tpu ones — a libtpu swap must evict them
        before the driver pod restarts."""
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        add_tpu_pod(c, "shared", "tpu-0")
        c.create({"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "isolated-wl", "namespace": "default"},
                  "spec": {"nodeName": "tpu-0", "containers": [{
                      "name": "c", "resources": {"requests": {
                          "google.com/tpu-isolated": "1"}}}]},
                  "status": {"phase": "Running"}})
        c.create({"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "vtpu-wl", "namespace": "default"},
                  "spec": {"nodeName": "tpu-0", "containers": [{
                      "name": "c", "resources": {"requests": {
                          "google.com/vtpu": "1"}}}]},
                  "status": {"phase": "Running"}})
        c.create({"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "cpu-only", "namespace": "default"},
                  "spec": {"nodeName": "tpu-0", "containers": [{
                      "name": "c", "resources": {"requests": {
                          "cpu": "1"}}}]},
                  "status": {"phase": "Running"}})
        by_node = rec._tpu_workload_pods_by_node()
        names = sorted(p["metadata"]["name"] for p in by_node["tpu-0"])
        assert "isolated-wl" in names and "vtpu-wl" in names
        assert "shared" in names and "cpu-only" not in names

    def test_completed_pods_not_in_drain_set(self):
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        c.create({"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "done-job", "namespace": "default"},
                  "spec": {"nodeName": "tpu-0", "containers": [{
                      "name": "c", "resources": {"requests": {
                          "google.com/tpu": "4"}}}]},
                  "status": {"phase": "Succeeded"}})
        assert "tpu-0" not in rec._tpu_workload_pods_by_node()

    def test_renamed_plugin_resources_still_drained(self):
        """isolatedPlugin.resourceName / vtpuResourceName are CR knobs; a
        renamed resource's pods must still land in the drain set."""
        c, prec = build_converged_cluster(n_nodes=1)
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["isolatedDevicePlugin"] = {
            "resourceName": "example.com/tpu-dedicated"}
        c.update(cr)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        c.create({"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "renamed-wl", "namespace": "default"},
                  "spec": {"nodeName": "tpu-0", "containers": [{
                      "name": "c", "resources": {"requests": {
                          "example.com/tpu-dedicated": "1"}}}]},
                  "status": {"phase": "Running"}})
        change_driver_spec(c, prec)
        # drive one pass: the drain stage must evict the renamed consumer
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert c.get_or_none("v1", "Pod", "renamed-wl", "default") is None


class TestOperatorRestartMidUpgrade:
    """Operator crash mid-rollout: the reconciler holds NO in-memory FSM
    state — the state label and every deadline stamp live on the node —
    so a FRESH reconciler instance must resume an in-flight rollout
    exactly where the dead one stopped. The reference relies on the same
    label-resident FSM for restart safety (upgrade_controller.go requeues
    rebuild the picture from node labels every pass)."""

    def test_fresh_instance_resumes_validation_without_redrain(self):
        c, prec = build_converged_cluster(n_nodes=3)
        rec1 = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec1.reconcile(Request(name="tpu-cluster-policy"))
        in_flight = [name_of(n) for n in c.list("v1", "Node")
                     if labels_of(n).get(L.UPGRADE_STATE) == STATE_VALIDATION]
        assert len(in_flight) == 1  # budget 1
        node_name = in_flight[0]
        # kubelet recreates the driver pod on the new revision
        c.simulate_kubelet(ready=True)
        [new_pod] = [p for p in driver_pods(c)
                     if get_nested(p, "spec", "nodeName") == node_name]
        new_rv = get_nested(new_pod, "metadata", "resourceVersion")
        # the operator dies; a brand-new instance picks up the cluster
        rec2 = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec2.reconcile(Request(name="tpu-cluster-policy"))
        # the in-flight node resumed forward (validation -> done), was
        # NOT walked back through cordon/drain...
        assert node_state(c, node_name) == STATE_DONE
        assert not get_nested(c.get("v1", "Node", node_name), "spec",
                              "unschedulable", default=False)
        # ...and its new-revision driver pod was not deleted again
        [pod_after] = [p for p in driver_pods(c)
                       if get_nested(p, "spec", "nodeName") == node_name]
        assert get_nested(pod_after, "metadata",
                          "resourceVersion") == new_rv
        # the rollout also moves on: the next pass hands the freed budget
        # slot to another node
        rec2.reconcile(Request(name="tpu-cluster-policy"))
        states = [labels_of(n).get(L.UPGRADE_STATE)
                  for n in c.list("v1", "Node")]
        assert states.count(STATE_VALIDATION) == 1
        assert states.count(STATE_DONE) == 1

    def test_drain_deadline_survives_restart(self):
        """A PDB-blocked drain stamped by the dead operator must time out
        against the ORIGINAL stamp — a restart cannot re-base the drain
        window and give the blocking pod another full timeout."""
        clock = [1000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        add_tpu_pod(c, "guarded", "tpu-0", labels={"app": "guarded"})
        c.create({"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
                  "metadata": {"name": "guard", "namespace": "default"},
                  "spec": {"selector": {"matchLabels": {"app": "guarded"}},
                           "minAvailable": 1}})
        rec1 = UpgradeReconciler(client=c, namespace="tpu-operator",
                                 now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec1.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_DRAIN  # stamped at t=1000
        # operator restarts 301s later; the new instance must see the
        # original stamp and fail the node immediately, not at t+300
        clock[0] += 301.0
        rec2 = UpgradeReconciler(client=c, namespace="tpu-operator",
                                 now=lambda: clock[0])
        rec2.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        anns = c.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert "drain timed out" in anns[L.UPGRADE_FAILED_REASON]

    def test_validation_deadline_survives_restart(self):
        """Same contract for the validation window: the stamp set by the
        dead operator bounds the wait, not the restart time."""
        clock = [5000.0]
        c, prec = build_converged_cluster(n_nodes=1)
        rec1 = UpgradeReconciler(client=c, namespace="tpu-operator",
                                 now=lambda: clock[0])
        change_driver_spec(c, prec)
        rec1.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_VALIDATION
        # validator never re-proves; restart past the 300s window
        clock[0] += 301.0
        rec2 = UpgradeReconciler(client=c, namespace="tpu-operator",
                                 now=lambda: clock[0])
        rec2.reconcile(Request(name="tpu-cluster-policy"))
        assert node_state(c, "tpu-0") == STATE_FAILED
        anns = c.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert "validation timed out" in anns[L.UPGRADE_FAILED_REASON]
