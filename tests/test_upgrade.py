"""Upgrade controller: per-node FSM, budget, drain semantics
(upgrade_controller.go tier)."""

from tpu_operator.api import V1, KIND_CLUSTER_POLICY, new_cluster_policy
from tpu_operator.api import labels as L
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
)
from tpu_operator.controllers.upgrade_controller import (
    STATE_DONE,
    STATE_UPGRADE_REQUIRED,
    STATE_VALIDATION,
    UpgradeReconciler,
)
from tpu_operator.runtime import FakeClient, ListOptions, Request
from tpu_operator.runtime.objects import get_nested, labels_of


def build_converged_cluster(n_nodes=2, auto_upgrade=True):
    """Fake cluster with the driver DS deployed and ready on every node."""
    c = FakeClient()
    for i in range(n_nodes):
        c.add_node(f"tpu-{i}", labels={
            L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
            L.GKE_TPU_TOPOLOGY: "2x2x1",
            L.GKE_ACCELERATOR_COUNT: "4"},
            allocatable={"google.com/tpu": "4"})
    c.create(new_cluster_policy(spec={
        "upgradePolicy": {"autoUpgrade": auto_upgrade,
                          "maxParallelUpgrades": 1}}))
    prec = ClusterPolicyReconciler(client=c, namespace="tpu-operator")
    prec.reconcile(Request(name="tpu-cluster-policy"))
    c.simulate_kubelet(ready=True)
    prec.reconcile(Request(name="tpu-cluster-policy"))
    return c, prec


def change_driver_spec(c, prec):
    """Bump the libtpu config so the driver DS template changes; OnDelete
    keeps existing pods on the old revision."""
    cr = c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
    spec = cr.get("spec") or {}
    spec["libtpu"] = {"installDir": "/opt/new-libtpu"}
    cr["spec"] = spec
    c.update(cr)
    prec.reconcile(Request(name="tpu-cluster-policy"))
    c.simulate_kubelet(ready=True)


def driver_pods(c):
    return c.list("v1", "Pod", ListOptions(
        label_selector={"tpu.graft.dev/component": "libtpu-driver"}))


class TestUpgradeFSM:
    def test_noop_when_current(self):
        c, _ = build_converged_cluster()
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        result = rec.reconcile(Request(name="tpu-cluster-policy"))
        assert result.requeue_after == 120.0
        for node in c.list("v1", "Node"):
            assert labels_of(node).get(L.UPGRADE_STATE) in (None, STATE_DONE)

    def test_auto_upgrade_off_strips_labels(self):
        c, _ = build_converged_cluster(auto_upgrade=False)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"labels": {L.UPGRADE_STATE: "upgrade-required"}}})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))

    def test_single_node_full_upgrade_cycle(self):
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        # pod still on old revision (OnDelete)
        [pod] = driver_pods(c)
        old_hash = labels_of(pod)["controller-revision-hash"]
        # pass 1: cordon + drain + delete driver pod -> validation wait
        result = rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_VALIDATION
        assert get_nested(node, "spec", "unschedulable") is True
        assert driver_pods(c) == []  # driver pod deleted
        assert result.requeue_after == 5.0
        # kubelet recreates the pod on the new revision
        c.simulate_kubelet(ready=True)
        [pod] = driver_pods(c)
        assert labels_of(pod)["controller-revision-hash"] != old_hash
        # pass 2: validation passes -> uncordon -> done
        result = rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_DONE
        assert not get_nested(node, "spec", "unschedulable", default=False)
        assert result.requeue_after == 120.0

    def test_validation_waits_for_validator_pods(self):
        # after the driver restarts, the node's validator pods must
        # re-prove the stack before uncordon — driver readiness alone is
        # not validation (cmd/gpu-operator/main.go:151 semantics)
        c, prec = build_converged_cluster(n_nodes=1)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        # validator pods were deleted along with the driver pod
        assert rec._validator_pods_by_node().get("tpu-0", []) == []
        c.simulate_kubelet(ready=True)
        # force the recreated validator pod NotReady: validation must hold
        for pod in rec._validator_pods_by_node().get("tpu-0", []):
            for cond in get_nested(pod, "status", "conditions",
                                   default=[]) or []:
                if cond.get("type") == "Ready":
                    cond["status"] = "False"
            c.update(pod)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_VALIDATION
        # validator recovers -> upgrade completes
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node)[L.UPGRADE_STATE] == STATE_DONE

    def test_parallel_budget_respected(self):
        c, prec = build_converged_cluster(n_nodes=3)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        states = [labels_of(n).get(L.UPGRADE_STATE)
                  for n in c.list("v1", "Node")]
        # maxParallelUpgrades=1: exactly one node advanced past
        # upgrade-required
        assert states.count(STATE_UPGRADE_REQUIRED) == 2
        assert states.count(STATE_VALIDATION) == 1

    def test_drain_evicts_tpu_workloads_but_respects_skip_label(self):
        c, prec = build_converged_cluster(n_nodes=1)
        for name, skip in (("train-job", False), ("sacred-job", True)):
            labels = {L.UPGRADE_SKIP_DRAIN: "true"} if skip else {}
            c.create({"apiVersion": "v1", "kind": "Pod",
                      "metadata": {"name": name, "namespace": "default",
                                   "labels": labels},
                      "spec": {"nodeName": "tpu-0",
                               "containers": [{
                                   "name": "t",
                                   "resources": {"requests":
                                                 {"google.com/tpu": "4"}}}]},
                      "status": {"phase": "Running"}})
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert c.get_or_none("v1", "Pod", "train-job", "default") is None
        assert c.get_or_none("v1", "Pod", "sacred-job", "default") is not None

    def test_eventual_full_fleet_upgrade(self):
        c, prec = build_converged_cluster(n_nodes=3)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        change_driver_spec(c, prec)
        for _ in range(12):  # budget 1 -> a few passes per node
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        states = {labels_of(n).get(L.UPGRADE_STATE)
                  for n in c.list("v1", "Node")}
        assert states == {STATE_DONE}
        # and all driver pods are on the new revision + nodes schedulable
        for node in c.list("v1", "Node"):
            assert not get_nested(node, "spec", "unschedulable", default=False)


class TestPerNodeUpgradeOptOut:
    """VERDICT round-1 item 10: the driver-upgrade-enabled annotation lets
    an operator pause a single node's rollout without CR spec surgery."""

    def test_annotation_pause_excludes_node(self):
        c, prec = build_converged_cluster(n_nodes=2)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        change_driver_spec(c, prec)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        for _ in range(8):
            rec.reconcile(Request(name="tpu-cluster-policy"))
            c.simulate_kubelet(ready=True)
        # paused node never entered the FSM; the other converged
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))
        assert labels_of(c.get("v1", "Node", "tpu-1")).get(
            L.UPGRADE_STATE) == STATE_DONE

    def test_pause_mid_rollout_strips_fsm_label(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert labels_of(c.get("v1", "Node", "tpu-0")).get(L.UPGRADE_STATE)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "paused"}}})
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))

    def test_cr_annotation_pauses_whole_rollout(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        cr = c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
        cr.setdefault("metadata", {}).setdefault("annotations", {})[
            L.DRIVER_UPGRADE_ENABLED] = "false"
        c.update(cr)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))

    def test_node_pause_survives_policy_reconcile(self):
        c, prec = build_converged_cluster(n_nodes=1)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        prec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert node["metadata"]["annotations"][
            L.DRIVER_UPGRADE_ENABLED] == "false"

    def test_pause_mid_rollout_uncordons(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert labels_of(node).get(L.UPGRADE_STATE)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "paused"}}})
        rec.reconcile(Request(name="tpu-cluster-policy"))
        node = c.get("v1", "Node", "tpu-0")
        assert L.UPGRADE_STATE not in labels_of(node)
        assert not get_nested(node, "spec", "unschedulable", default=False)

    def test_node_pause_survives_global_disable_cycle(self):
        c, prec = build_converged_cluster(n_nodes=2)
        c.patch("v1", "Node", "tpu-0",
                {"metadata": {"annotations":
                              {L.DRIVER_UPGRADE_ENABLED: "false"}}})
        cr = c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
        cr["spec"]["upgradePolicy"] = {"autoUpgrade": False}
        c.update(cr)
        prec.reconcile(Request(name="tpu-cluster-policy"))
        # reconciler-stamped "true" unwound; explicit pause preserved
        anns0 = c.get("v1", "Node", "tpu-0")["metadata"].get(
            "annotations") or {}
        anns1 = c.get("v1", "Node", "tpu-1")["metadata"].get(
            "annotations") or {}
        assert anns0.get(L.DRIVER_UPGRADE_ENABLED) == "false"
        assert L.DRIVER_UPGRADE_ENABLED not in anns1
        cr = c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
        cr["spec"]["upgradePolicy"] = {"autoUpgrade": True}
        c.update(cr)
        prec.reconcile(Request(name="tpu-cluster-policy"))
        anns0 = c.get("v1", "Node", "tpu-0")["metadata"].get(
            "annotations") or {}
        assert anns0.get(L.DRIVER_UPGRADE_ENABLED) == "false"

    def test_sandbox_plane_halts_rollout(self):
        c, prec = build_converged_cluster(n_nodes=1)
        change_driver_spec(c, prec)
        cr = c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
        cr["spec"]["sandboxWorkloads"] = {"enabled": True}
        c.update(cr)
        rec = UpgradeReconciler(client=c, namespace="tpu-operator")
        rec.reconcile(Request(name="tpu-cluster-policy"))
        assert L.UPGRADE_STATE not in labels_of(c.get("v1", "Node", "tpu-0"))
