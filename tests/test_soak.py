"""Seeded chaos soak: the full Manager (all three reconcilers) over the
live mock HTTP apiserver while a scripted adversary mutates the world —
policy edits, operand deletion, node churn, watch-stream drops, injected
write conflicts. After every disruption the system must re-converge to
`ready` with the desired config actually in effect.

Nothing like this exists in the reference (its shell e2e runs a fixed
scenario list); the deterministic seed keeps failures reproducible.
"""

from __future__ import annotations

import os
import random
import time

from tpu_operator.api import KIND_CLUSTER_POLICY, V1, new_cluster_policy
from tpu_operator.api import labels as L
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
)
from tpu_operator.controllers.tpudriver_controller import TPUDriverReconciler
from tpu_operator.controllers.upgrade_controller import UpgradeReconciler
from tpu_operator.runtime import ListOptions
from tpu_operator.runtime.fake import simulate_kubelet
from tpu_operator.runtime.kubeclient import HTTPClient, KubeConfig
from tpu_operator.runtime.manager import Manager
from tpu_operator.runtime.objects import get_nested, labels_of

from mock_apiserver import MockApiServer

NS = "tpu-operator"
# deterministic by default so a failure reproduces; override to widen
# coverage across runs: TPU_SOAK_SEED=<n> pytest -m soak
SEED = int(os.environ.get("TPU_SOAK_SEED", "20260730"))


def tpu_node(name):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": {
            L.GKE_TPU_ACCELERATOR: "tpu-v5e-slice",
            L.GKE_TPU_TOPOLOGY: "2x2",
            L.GKE_ACCELERATOR_COUNT: "4"}},
        "spec": {},
        "status": {"allocatable": {"google.com/tpu": "4"},
                   "capacity": {"google.com/tpu": "4"},
                   "nodeInfo": {"containerRuntimeVersion":
                                "containerd://1.7.0"},
                   "conditions": [{"type": "Ready", "status": "True"}]},
    }


def wait_converged(ops, pred, desc, timeout=90.0):
    # pred evaluates every pass even when the kubelet tick loses a write
    # race — sustained contention must not starve an already-true check.
    # Kubelet and pred errors are tracked separately: a persistent
    # kubelet failure often causes the pred error, and the root cause
    # must not be masked by its downstream symptom.
    end = time.time() + timeout
    kubelet_err = None
    pred_err = None
    while time.time() < end:
        try:
            simulate_kubelet(ops, ready=True)
        except Exception as e:
            kubelet_err = e
        try:
            if pred():
                return
        except Exception as e:
            pred_err = e
        time.sleep(0.25)
    raise AssertionError(f"soak: no convergence after {desc} "
                         f"(kubelet error: {kubelet_err}; "
                         f"pred error: {pred_err})")


def cr_state(ops):
    cr = ops.get_or_none(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
    return ((cr or {}).get("status") or {}).get("state")


def test_chaos_soak_converges_after_every_disruption():
    rng = random.Random(SEED)
    srv = MockApiServer().start()
    cfg = KubeConfig(server=srv.url, token="soak", namespace=NS)
    ops = HTTPClient(config=cfg)
    mgr_client = HTTPClient(config=cfg)
    mgr = Manager(mgr_client, namespace=NS)
    mgr.add_reconciler(ClusterPolicyReconciler(mgr_client, namespace=NS))
    mgr.add_reconciler(TPUDriverReconciler(mgr_client, namespace=NS))
    mgr.add_reconciler(UpgradeReconciler(mgr_client, namespace=NS))
    next_node = [2]

    def ready():
        return cr_state(ops) == "ready"

    # -- the adversary's moves (each returns a description) -------------
    def mutate_policy():
        marker = f"SOAK_{rng.randrange(1_000_000)}"
        for _ in range(10):
            cr = ops.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
            spec = cr.setdefault("spec", {})
            spec.setdefault("devicePlugin", {})["env"] = [
                {"name": "SOAK_MARKER", "value": marker}]
            try:
                ops.update(cr)
                break
            except Exception:
                time.sleep(0.1)

        def applied():
            ds = ops.get_or_none("apps/v1", "DaemonSet",
                                 "tpu-device-plugin-daemonset", NS)
            env = get_nested(ds or {}, "spec", "template", "spec",
                             "containers", default=[{}])[0].get("env") or []
            return any(e.get("value") == marker for e in env) and ready()

        return f"policy mutation {marker}", applied

    def delete_operand():
        victims = [d for d in ops.list(
            "apps/v1", "DaemonSet", ListOptions(namespace=NS))
            if "device-plugin" in d["metadata"]["name"]
            or "metrics" in d["metadata"]["name"]]
        if victims:
            v = rng.choice(victims)
            ops.delete("apps/v1", "DaemonSet", v["metadata"]["name"], NS)
            name = v["metadata"]["name"]
        else:
            name = "(none)"

        def recreated():
            return ready() and all(
                ops.get_or_none("apps/v1", "DaemonSet",
                                d["metadata"]["name"], NS) is not None
                for d in victims)

        return f"operand {name} deleted", recreated

    def add_node():
        name = f"tpu-{next_node[0]}"
        next_node[0] += 1
        ops.create(tpu_node(name))

        def labeled():
            n = ops.get("v1", "Node", name)
            return labels_of(n).get(L.TPU_PRESENT) == "true" and ready()

        return f"node {name} joined", labeled

    def remove_node():
        nodes = [n for n in ops.list("v1", "Node")
                 if n["metadata"]["name"] != "tpu-0"]  # keep >=1 TPU node
        if nodes:
            victim = rng.choice(nodes)["metadata"]["name"]
            # drop its pods first (a vanished node takes its pods along)
            for p in ops.list("v1", "Pod", ListOptions(namespace=NS)):
                if get_nested(p, "spec", "nodeName") == victim:
                    ops.delete("v1", "Pod", p["metadata"]["name"], NS)
            ops.delete("v1", "Node", victim)
        return "node removed", ready

    def drop_watches():
        # pair the disruption with a mutation the operator must still
        # apply: "ready" alone is already true when the streams drop, so
        # it would never prove the clients resumed
        srv.drop_watch_streams()
        desc, pred = mutate_policy()
        return f"watch streams dropped + {desc}", pred

    def inject_conflicts():
        # mutate FIRST, then arm the conflicts: armed first, the
        # adversary's own update retry loop would consume the 409s and
        # the operator would never see one
        desc, pred = mutate_policy()
        n = rng.randrange(1, 4)
        srv.fail_next_writes = n
        return f"{desc} + {n} write conflicts injected", pred

    moves = [mutate_policy, delete_operand, add_node, remove_node,
             drop_watches, inject_conflicts]

    mgr.start()
    try:
        for i in range(2):
            ops.create(tpu_node(f"tpu-{i}"))
        ops.create(new_cluster_policy())
        wait_converged(ops, ready, "initial install")

        # default 10 disruptions; TPU_SOAK_STEPS=200 turns this into a
        # long-soak tier for release qualification
        for step in range(int(os.environ.get("TPU_SOAK_STEPS", "10"))):
            move = rng.choice(moves)
            desc, pred = move()
            wait_converged(ops, pred, f"step {step}: {desc}")
    finally:
        mgr.stop()
        ops._stop.set()
        mgr_client._stop.set()
        srv.stop()
