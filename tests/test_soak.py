"""Seeded chaos soak: the full Manager (all three reconcilers) over the
live mock HTTP apiserver while a scripted adversary mutates the world —
policy edits, operand deletion, node churn, watch-stream drops, injected
write conflicts. After every disruption the system must re-converge to
`ready` with the desired config actually in effect.

Nothing like this exists in the reference (its shell e2e runs a fixed
scenario list); the deterministic seed keeps failures reproducible.
"""

from __future__ import annotations

import os
import random
import time

from tpu_operator.api import KIND_CLUSTER_POLICY, V1, new_cluster_policy
from tpu_operator.api import labels as L
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
)
from tpu_operator.controllers.tpudriver_controller import TPUDriverReconciler
from tpu_operator.controllers.upgrade_controller import UpgradeReconciler
from tpu_operator.runtime import ListOptions
from tpu_operator.runtime.fake import simulate_kubelet
from tpu_operator.runtime.kubeclient import HTTPClient, KubeConfig
from tpu_operator.runtime.manager import Manager
from tpu_operator.runtime.objects import get_nested, labels_of
from tpu_operator.utils.hash import object_hash

from mock_apiserver import MockApiServer

NS = "tpu-operator"
# deterministic by default so a failure reproduces; override to widen
# coverage across runs: TPU_SOAK_SEED=<n> pytest -m soak
SEED = int(os.environ.get("TPU_SOAK_SEED", "20260730"))


def tpu_node(name):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": {
            L.GKE_TPU_ACCELERATOR: "tpu-v5e-slice",
            L.GKE_TPU_TOPOLOGY: "2x2",
            L.GKE_ACCELERATOR_COUNT: "4"}},
        "spec": {},
        "status": {"allocatable": {"google.com/tpu": "4"},
                   "capacity": {"google.com/tpu": "4"},
                   "nodeInfo": {"containerRuntimeVersion":
                                "containerd://1.7.0"},
                   "conditions": [{"type": "Ready", "status": "True"}]},
    }


def wait_converged(ops, pred, desc, timeout=90.0):
    # pred evaluates every pass even when the kubelet tick loses a write
    # race — sustained contention must not starve an already-true check.
    # Kubelet and pred errors are tracked separately: a persistent
    # kubelet failure often causes the pred error, and the root cause
    # must not be masked by its downstream symptom.
    # Deadlines scale with measured CI contention (the same discipline
    # as every other tier, conftest.load_factor): the 200-step long
    # soak shares a one-core box with whatever else runs.
    from conftest import load_factor

    end = time.time() + timeout * load_factor()
    kubelet_err = None
    pred_err = None
    while time.time() < end:
        try:
            simulate_kubelet(ops, ready=True)
        except Exception as e:
            kubelet_err = e
        try:
            if pred():
                return
        except Exception as e:
            pred_err = e
        time.sleep(0.25)
    raise AssertionError(f"soak: no convergence after {desc} "
                         f"(kubelet error: {kubelet_err}; "
                         f"pred error: {pred_err})")


def cr_state(ops):
    cr = ops.get_or_none(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
    return ((cr or {}).get("status") or {}).get("state")


def test_chaos_soak_converges_after_every_disruption():
    rng = random.Random(SEED)
    srv = MockApiServer().start()
    cfg = KubeConfig(server=srv.url, token="soak", namespace=NS)
    ops = HTTPClient(config=cfg)
    mgr_client = HTTPClient(config=cfg)
    mgr = Manager(mgr_client, namespace=NS)
    mgr.add_reconciler(ClusterPolicyReconciler(mgr_client, namespace=NS))
    mgr.add_reconciler(TPUDriverReconciler(mgr_client, namespace=NS))
    mgr.add_reconciler(UpgradeReconciler(mgr_client, namespace=NS))
    next_node = [2]

    def ready():
        return cr_state(ops) == "ready"

    def update_policy(mutate_fn):
        """Conflict-retried CR mutation: the manager writes status in
        parallel, so the adversary re-reads and retries on any write
        failure. Exhausting the retries raises — a move that never
        landed must fail loudly, not time out later with a baffling
        'pred error: None'."""
        for _ in range(10):
            cr = ops.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
            mutate_fn(cr.setdefault("spec", {}))
            try:
                ops.update(cr)
                return
            except Exception:
                time.sleep(0.1)
        raise AssertionError("soak: policy mutation never landed "
                             "after 10 conflict retries")

    # -- the adversary's moves (each returns a description) -------------
    def mutate_policy():
        marker = f"SOAK_{rng.randrange(1_000_000)}"
        update_policy(lambda spec: spec.setdefault("devicePlugin", {})
                      .__setitem__("env", [{"name": "SOAK_MARKER",
                                            "value": marker}]))

        def applied():
            ds = ops.get_or_none("apps/v1", "DaemonSet",
                                 "tpu-device-plugin-daemonset", NS)
            env = get_nested(ds or {}, "spec", "template", "spec",
                             "containers", default=[{}])[0].get("env") or []
            return any(e.get("value") == marker for e in env) and ready()

        return f"policy mutation {marker}", applied

    def delete_operand():
        victims = [d for d in ops.list(
            "apps/v1", "DaemonSet", ListOptions(namespace=NS))
            if "device-plugin" in d["metadata"]["name"]
            or "metrics" in d["metadata"]["name"]]
        if victims:
            v = rng.choice(victims)
            ops.delete("apps/v1", "DaemonSet", v["metadata"]["name"], NS)
            name = v["metadata"]["name"]
        else:
            name = "(none)"

        def recreated():
            return ready() and all(
                ops.get_or_none("apps/v1", "DaemonSet",
                                d["metadata"]["name"], NS) is not None
                for d in victims)

        return f"operand {name} deleted", recreated

    def add_node():
        name = f"tpu-{next_node[0]}"
        next_node[0] += 1
        ops.create(tpu_node(name))

        def labeled():
            n = ops.get("v1", "Node", name)
            return labels_of(n).get(L.TPU_PRESENT) == "true" and ready()

        return f"node {name} joined", labeled

    def remove_node():
        nodes = [n for n in ops.list("v1", "Node")
                 if n["metadata"]["name"] != "tpu-0"]  # keep >=1 TPU node
        if nodes:
            victim = rng.choice(nodes)["metadata"]["name"]
            # drop its pods first (a vanished node takes its pods along)
            for p in ops.list("v1", "Pod", ListOptions(namespace=NS)):
                if get_nested(p, "spec", "nodeName") == victim:
                    ops.delete("v1", "Pod", p["metadata"]["name"], NS)
            ops.delete("v1", "Node", victim)
        return "node removed", ready

    def drop_watches():
        # pair the disruption with a mutation the operator must still
        # apply: "ready" alone is already true when the streams drop, so
        # it would never prove the clients resumed
        srv.drop_watch_streams()
        desc, pred = mutate_policy()
        return f"watch streams dropped + {desc}", pred

    def inject_conflicts():
        # mutate FIRST, then arm the conflicts: armed first, the
        # adversary's own update retry loop would consume the 409s and
        # the operator would never see one
        desc, pred = mutate_policy()
        n = rng.randrange(1, 4)
        srv.fail_next_writes = n
        return f"{desc} + {n} write conflicts injected", pred

    def trigger_upgrade():
        # change the OnDelete driver DS template: nothing rolls until
        # the upgrade FSM walks every node through cordon -> drain ->
        # pod restart -> re-validation -> uncordon — under whatever
        # chaos the other moves have left behind (churned nodes,
        # conflict injection, dropped watches)
        marker = f"/opt/soak-libtpu-{rng.randrange(1_000_000)}"
        update_policy(lambda spec: spec.setdefault("libtpu", {})
                      .__setitem__("installDir", marker))

        def rolled():
            if not ready():
                return False
            nodes = ops.list("v1", "Node")
            tpu_nodes = [n for n in nodes
                         if labels_of(n).get(L.GKE_TPU_ACCELERATOR)]
            # the FSM finished everywhere and left the fleet schedulable
            if any(labels_of(n).get(L.UPGRADE_STATE) not in (None, "done")
                   for n in tpu_nodes):
                return False
            if any(get_nested(n, "spec", "unschedulable", default=False)
                   for n in nodes):
                return False
            # the marker reached the rendered template, and the rollout
            # really happened: one live driver pod per TPU node, every
            # one at the NEW template revision (the simulated kubelet
            # stamps pods with controller-revision-hash only — the same
            # key the FSM itself rolls on)
            import json as _json

            ds = ops.get_or_none("apps/v1", "DaemonSet",
                                 "tpu-libtpu-driver-daemonset", NS)
            if ds is None or marker not in _json.dumps(ds):
                return False
            want = object_hash(
                get_nested(ds, "spec", "template", default={}))
            pods = [p for p in ops.list("v1", "Pod",
                                        ListOptions(namespace=NS))
                    if (get_nested(p, "metadata", "labels", default={})
                        or {}).get("tpu.graft.dev/component")
                    == "libtpu-driver"
                    and not get_nested(p, "metadata", "deletionTimestamp")]
            return (len(pods) == len(tpu_nodes)
                    and all((get_nested(p, "metadata", "labels",
                                        "controller-revision-hash"))
                            == want for p in pods))

        return f"driver template changed ({marker}): FSM rollout", rolled

    moves = [mutate_policy, delete_operand, add_node, remove_node,
             drop_watches, inject_conflicts, trigger_upgrade]

    mgr.start()
    try:
        for i in range(2):
            ops.create(tpu_node(f"tpu-{i}"))
        # autoUpgrade on, wide budget: the trigger_upgrade move needs the
        # FSM live, and a parallel budget keeps a fleet rollout inside
        # the per-step convergence window
        ops.create(new_cluster_policy(spec={
            "upgradePolicy": {"autoUpgrade": True,
                              "maxParallelUpgrades": 4}}))
        wait_converged(ops, ready, "initial install")

        # default 10 disruptions; TPU_SOAK_STEPS=200 turns this into a
        # long-soak tier for release qualification
        for step in range(int(os.environ.get("TPU_SOAK_STEPS", "10"))):
            move = rng.choice(moves)
            desc, pred = move()
            # a fleet FSM walk is the slowest convergence in the suite;
            # it gets the same wider window as the final rollout
            wait_converged(ops, pred, f"step {step}: {desc}",
                           timeout=180.0 if move is trigger_upgrade
                           else 90.0)

        # one guaranteed fleet rollout regardless of what the seed drew,
        # against whatever cluster the chaos steps left behind; a full
        # FSM walk over every node is the slowest convergence in the
        # suite, so it gets a wider window
        desc, pred = trigger_upgrade()
        wait_converged(ops, pred, f"final: {desc}", timeout=180.0)
    finally:
        mgr.stop()
        ops._stop.set()
        mgr_client._stop.set()
        srv.stop()
