"""Incremental placement index (topology/index.py) and the batched
gang-placement pass built on it.

The load-bearing property: a long-lived ``FleetIndex`` fed any
interleaving of watch deltas, resyncs, and book/release calls must
serve byte-identical rankings to a ``FleetState`` rebuilt from scratch
over the same nodes — candidate for candidate, including the
UNLABELED_TPU chunking path and ``unschedulable_reason``. It runs as a
stdlib seeded-random interleaving test always, and additionally under
hypothesis when the package is installed.
"""

import random

import pytest

from tpu_operator.api import labels as L
from tpu_operator.api.slicerequest import (
    KIND_SLICE_REQUEST,
    PHASE_PENDING,
    PHASE_PLACED,
    PHASE_UNSCHEDULABLE,
    V1ALPHA1,
    SliceRequestSpec,
    new_slice_request,
)
from tpu_operator.controllers.placement_controller import PlacementReconciler
from tpu_operator.runtime import FakeClient, Request
from tpu_operator.runtime.objects import annotations_of, get_nested, thaw_obj
from tpu_operator.topology.index import (
    PLACEMENT_INDEX_GATE,
    FleetIndex,
    env_placement_index_enabled,
)
from tpu_operator.topology.placement import (
    FleetState,
    rank_candidates,
    unschedulable_reason,
)


def add_tpu(c, name, accel="tpu-v5e-slice", topo="2x4", chips=4,
            worker_id=None, pool=None):
    labels = {
        L.GKE_TPU_ACCELERATOR: accel,
        L.GKE_TPU_TOPOLOGY: topo,
        L.GKE_ACCELERATOR_COUNT: str(chips),
    }
    if worker_id is not None:
        labels[L.GKE_TPU_WORKER_ID] = str(worker_id)
    if pool is not None:
        labels[L.GKE_NODEPOOL] = pool
    return c.add_node(name, labels=labels,
                      allocatable={"google.com/tpu": str(chips)})


def churn_fleet():
    """Heterogeneous fleet that exercises every index code path: a
    labeled v5p 4x4 slice, v4 singles, and an UNLABELED v5e pool (no
    worker ids) big enough to trigger the topology-chunking fallback."""
    c = FakeClient()
    for i in range(6):
        add_tpu(c, f"v5e-{i}")                      # unlabeled -> chunked
    for i in range(4):
        add_tpu(c, f"v5p-{i}", accel="tpu-v5p-slice", topo="4x4",
                worker_id=i)
    for i in range(2):
        add_tpu(c, f"v4-{i}", accel="tpu-v4-podslice", topo="2x2x1")
    return c


PROBES = [SliceRequestSpec(chips=n) for n in (4, 8, 16, 32)] + [
    SliceRequestSpec(chips=8, accelerator="tpu-v5p-slice"),
    SliceRequestSpec(chips=8, preferred_generations=("v5p", "v4")),
    SliceRequestSpec(chips=10 ** 6),  # always unschedulable
]


def _assert_coherent(index, nodes, context):
    fleet = FleetState(list(nodes.values()))
    for spec in PROBES:
        scratch = [c.sort_key() for c in rank_candidates(spec, fleet)]
        served = [c.sort_key() for c in index.rank(spec)]
        assert served == scratch, (context, spec.chips)
        best = index.best(spec)
        assert (best.sort_key() if best else None) == \
            (scratch[0] if scratch else None), (context, spec.chips)
        assert index.unschedulable_reason(spec) == \
            unschedulable_reason(spec, fleet), (context, spec.chips)


def _run_interleaving(seed, steps=60, check_every=12):
    """Drive one seeded interleaving of node churn (via apply AND
    resync), cordon flips, lease-annotation echoes, and direct
    book/release; assert index == from-scratch FleetState along the
    way. Shared by the always-on stdlib test and the hypothesis one."""
    rng = random.Random(seed)
    client = churn_fleet()
    nodes = {get_nested(n, "metadata", "name"): thaw_obj(n)
             for n in client.list("v1", "Node")}
    index = FleetIndex(list(nodes.values()))
    owners = {}

    def mutate(name, fn, rv):
        node = thaw_obj(nodes[name])
        fn(node)
        node["metadata"]["resourceVersion"] = str(rv)
        nodes[name] = node
        return node

    for step in range(steps):
        op = rng.random()
        if op < 0.20 and nodes:  # lease-annotation echo
            name = rng.choice(sorted(nodes))

            def flip(node):
                ann = node.setdefault("metadata", {}).setdefault(
                    "annotations", {})
                if rng.random() < 0.5:
                    ann[L.PLACED_BY] = f"ns/req-{rng.randrange(6)}"
                else:
                    ann.pop(L.PLACED_BY, None)

            index.apply("MODIFIED", mutate(name, flip, 1000 + step))
        elif op < 0.38 and nodes:  # cordon flip, via apply or resync
            name = rng.choice(sorted(nodes))

            def cordon(node):
                spec = node.setdefault("spec", {})
                spec["unschedulable"] = not spec.get("unschedulable")

            changed = mutate(name, cordon, 1000 + step)
            if rng.random() < 0.5:
                index.apply("MODIFIED", changed)
            else:
                index.resync(list(nodes.values()))
        elif op < 0.50 and len(nodes) > 6:  # node removal
            name = rng.choice(sorted(nodes))
            gone = nodes.pop(name)
            for held in owners.values():
                held.discard(name)
            index.apply("DELETED", gone)
        elif op < 0.62:  # node join (keeps the unlabeled pool churning)
            name = f"join-{step}"
            add_tpu(client, name)
            fresh = thaw_obj(client.get("v1", "Node", name))
            nodes[name] = fresh
            if rng.random() < 0.5:
                index.apply("ADDED", fresh)
            else:
                index.resync(list(nodes.values()))
        elif op < 0.85:  # place + book, mirrored into annotations
            spec = rng.choice(PROBES[:6])
            best = index.best(spec)
            if best:
                owner = f"ns/g-{step}"
                index.book(best.nodes, owner)
                owners[owner] = set(best.nodes)
                for bound in best.nodes:
                    if bound in nodes:
                        def lease(node, o=owner):
                            node.setdefault("metadata", {}).setdefault(
                                "annotations", {})[L.PLACED_BY] = o
                        mutate(bound, lease, 2000 + step)
        elif owners:  # O(owned) release, echoed back
            owner = rng.choice(sorted(owners))
            held = owners.pop(owner)
            index.release(owner=owner)
            for bound in held:
                if bound in nodes:
                    def clear(node, o=owner):
                        ann = node.setdefault("metadata", {}).setdefault(
                            "annotations", {})
                        if ann.get(L.PLACED_BY) == o:
                            ann.pop(L.PLACED_BY)
                    index.apply("MODIFIED",
                                mutate(bound, clear, 2000 + step))
        if step % check_every == 0:
            _assert_coherent(index, nodes, (seed, step))
    _assert_coherent(index, nodes, (seed, "final"))


class TestIndexCoherenceProperty:
    @pytest.mark.parametrize("seed", [0, 1, 7, 42, 1337])
    def test_seeded_interleavings_match_rescan(self, seed):
        """Stdlib fallback for the property: always runs, no hypothesis
        needed — five fixed seeds over 60-step interleavings."""
        _run_interleaving(seed)

    def test_hypothesis_interleavings_match_rescan(self):
        pytest.importorskip("hypothesis")
        from hypothesis import HealthCheck, given, settings
        from hypothesis import strategies as st

        @settings(max_examples=20, deadline=None,
                  suppress_health_check=list(HealthCheck))
        @given(st.integers(min_value=0, max_value=2 ** 32 - 1))
        def check(seed):
            _run_interleaving(seed, steps=40, check_every=10)

        check()

    def test_snapshot_state_is_independent_trial_board(self):
        index = FleetIndex(churn_fleet().list("v1", "Node"))
        best = index.best(SliceRequestSpec(chips=8))
        index.book(best.nodes, "ns/held")
        twin = index.snapshot_state()
        twin.release(owner="ns/held")  # trial drain
        # the trial sees the capacity back...
        assert rank_candidates(SliceRequestSpec(chips=8), twin)
        # ...the live index still holds the lease
        assert index.owned_nodes("ns/held") == tuple(sorted(best.nodes))


class TestOwnerReverseIndex:
    """FleetState.release(owner=) rides the owner->nodes reverse index:
    O(nodes that owner holds), never a scan of the whole lease table."""

    def test_release_by_owner_touches_only_owned_entries(self):
        c = FakeClient()
        for i in range(40):
            add_tpu(c, f"n-{i:02d}")
        fleet = FleetState(c.list("v1", "Node"))
        for i in range(0, 36, 2):
            fleet.book([f"n-{i:02d}", f"n-{i + 1:02d}"], f"ns/o-{i // 2}")

        class CountingDict(dict):
            pops = 0

            def pop(self, *a):
                CountingDict.pops += 1
                return super().pop(*a)

        fleet.owner_of = CountingDict(fleet.owner_of)
        CountingDict.pops = 0
        fleet.release(owner="ns/o-3")
        # exactly the two owned entries left the table — not O(leases)
        assert CountingDict.pops == 2
        assert fleet.owned_nodes("ns/o-3") == ()
        assert fleet.owned_nodes("ns/o-4") == ("n-08", "n-09")

    def test_book_steal_keeps_reverse_index_consistent(self):
        c = FakeClient()
        for i in range(4):
            add_tpu(c, f"n-{i}")
        fleet = FleetState(c.list("v1", "Node"))
        fleet.book(["n-0", "n-1"], "ns/a")
        fleet.book(["n-1"], "ns/b")  # steal one
        assert fleet.owned_nodes("ns/a") == ("n-0",)
        assert fleet.owned_nodes("ns/b") == ("n-1",)
        fleet.release(owner="ns/a")
        fleet.release(owner="ns/b")
        assert not fleet.owner_of and not fleet._owner_nodes


class TestCacheDeltaListener:
    """CachedClient.add_delta_listener: the informer-to-index hook fires
    after the store reflects each change, for watch ingest and
    write-through alike, and cancel() detaches it."""

    def _cached(self):
        from tpu_operator.runtime.cache import CachedClient

        fake = churn_fleet()
        return fake, CachedClient(fake)

    def test_listener_sees_watch_and_write_through_deltas(self):
        fake, cached = self._cached()
        events = []
        cancel = cached.add_delta_listener(
            "v1", "Node", lambda et, obj: events.append(
                (et, get_nested(obj, "metadata", "name"))))
        cached.list("v1", "Node")  # prime the store
        events.clear()
        cached.patch("v1", "Node", "v5e-0",
                     {"metadata": {"annotations": {L.PLACED_BY: "ns/x"}}})
        assert ("MODIFIED", "v5e-0") in events
        # the store already reflects the change when the listener fires
        seen = annotations_of(cached.get("v1", "Node", "v5e-0"))
        assert seen.get(L.PLACED_BY) == "ns/x"
        cached.delete("v1", "Node", "v4-0")
        assert ("DELETED", "v4-0") in events
        n = len(events)
        cancel()
        cached.patch("v1", "Node", "v5e-1",
                     {"metadata": {"annotations": {L.PLACED_BY: "ns/y"}}})
        assert len(events) == n  # detached

    def test_listener_exceptions_never_break_ingest(self):
        fake, cached = self._cached()

        def boom(et, obj):
            raise RuntimeError("listener bug")

        cached.add_delta_listener("v1", "Node", boom)
        cached.list("v1", "Node")
        cached.patch("v1", "Node", "v5e-0",
                     {"metadata": {"labels": {"x": "y"}}})  # must not raise
        assert cached.get("v1", "Node", "v5e-0") is not None


@pytest.fixture
def gate_on():
    prev = PLACEMENT_INDEX_GATE.enabled
    PLACEMENT_INDEX_GATE.enabled = True
    yield
    PLACEMENT_INDEX_GATE.enabled = prev


@pytest.fixture
def gate_off():
    prev = PLACEMENT_INDEX_GATE.enabled
    PLACEMENT_INDEX_GATE.enabled = False
    yield
    PLACEMENT_INDEX_GATE.enabled = prev


class TestBatchedGangPlacement:
    def make(self):
        c = churn_fleet()
        rec = PlacementReconciler(client=c, namespace="default")
        return c, rec

    def pend(self, c, name, **kw):
        c.create(new_slice_request(
            name, spec=SliceRequestSpec(**kw).to_obj(),
            namespace="default"))
        return Request(name=name, namespace="default")

    def phase(self, c, name):
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, name, "default")
        return get_nested(cr, "status", "phase")

    def test_one_pass_drains_all_pending(self, gate_on):
        """The tentpole batching contract: reconciling ONE pending
        request places every queued sibling in the same pass, against
        one shared index snapshot."""
        from tpu_operator.metrics.operator_metrics import OPERATOR_METRICS

        c, rec = self.make()
        reqs = [self.pend(c, f"r-{i}", chips=4) for i in range(3)]
        rec.reconcile(reqs[0])
        assert [self.phase(c, f"r-{i}") for i in range(3)] == \
            [PHASE_PLACED] * 3
        assert OPERATOR_METRICS.placement_batch_size._value.get() == 3

    def test_batch_places_by_priority_not_arrival(self, gate_on):
        """Two requests contend for the only v5p domain; the
        higher-priority one wins even though it arrived second."""
        c, rec = self.make()
        self.pend(c, "late-low", chips=16, accelerator="tpu-v5p-slice",
                  priority=0)
        self.pend(c, "high", chips=16, accelerator="tpu-v5p-slice",
                  priority=5)
        rec.reconcile(Request(name="late-low", namespace="default"))
        assert self.phase(c, "high") == PHASE_PLACED
        assert self.phase(c, "late-low") == PHASE_UNSCHEDULABLE

    def test_batch_skips_unschedulable_siblings(self, gate_on):
        """A sibling already in Unschedulable keeps its own backoff
        cadence — the batch must not re-score it on every pass."""
        c, rec = self.make()
        big = self.pend(c, "big", chips=10 ** 4)
        rec.reconcile(big)
        assert self.phase(c, "big") == PHASE_UNSCHEDULABLE
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "big", "default")
        rv = get_nested(cr, "metadata", "resourceVersion")
        rec.reconcile(self.pend(c, "small", chips=4))
        assert self.phase(c, "small") == PHASE_PLACED
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "big", "default")
        assert get_nested(cr, "metadata", "resourceVersion") == rv

    def test_in_pass_booking_prevents_double_grant(self, gate_on):
        """Both pending requests want the single v5p 4x4 domain whole;
        the batch books in-pass, so exactly one wins — no overlapping
        leases, no stale-snapshot double grant."""
        c, rec = self.make()
        self.pend(c, "gang-a", chips=16, accelerator="tpu-v5p-slice")
        self.pend(c, "gang-b", chips=16, accelerator="tpu-v5p-slice")
        rec.reconcile(Request(name="gang-a", namespace="default"))
        phases = sorted([self.phase(c, "gang-a"), self.phase(c, "gang-b")])
        assert phases == [PHASE_PLACED, PHASE_UNSCHEDULABLE]
        leased = [get_nested(n, "metadata", "name")
                  for n in c.list("v1", "Node")
                  if annotations_of(n).get(L.PLACED_BY)]
        assert len(leased) == 4  # one grant, not two overlapping

    def test_kill_switch_falls_back_to_per_request(self, gate_off):
        """OPERATOR_PLACEMENT_INDEX=0: the triggering request still
        places (FleetState path), but siblings wait for their own
        reconcile — the pre-index behavior, exactly."""
        c, rec = self.make()
        reqs = [self.pend(c, f"r-{i}", chips=4) for i in range(3)]
        rec.reconcile(reqs[0])
        assert self.phase(c, "r-0") == PHASE_PLACED
        assert self.phase(c, "r-1") is None  # untouched this pass
        rec.reconcile(reqs[1])
        assert self.phase(c, "r-1") == PHASE_PLACED

    def test_index_survives_eviction_and_replace(self, gate_on):
        """Controller-driven lifecycle keeps the long-lived index
        coherent: place, kill a bound node, evict, re-place — then the
        index's view must equal a from-scratch rescan."""
        c, rec = self.make()
        req = self.pend(c, "a", chips=4)
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        [bound] = get_nested(cr, "status", "nodes")
        c.delete("v1", "Node", bound)
        rec.reconcile(req)  # eviction
        assert self.phase(c, "a") == PHASE_PENDING
        rec.reconcile(req)  # re-place
        assert self.phase(c, "a") == PHASE_PLACED
        nodes = {get_nested(n, "metadata", "name"): thaw_obj(n)
                 for n in c.list("v1", "Node")}
        engine = rec._fleet_snapshot()
        assert isinstance(engine, FleetIndex)
        _assert_coherent(engine, nodes, "post-eviction")


class TestKillSwitchEnv:
    def test_env_spellings(self):
        for off in ("0", "false", "no", "off", " OFF "):
            assert not env_placement_index_enabled(
                {"OPERATOR_PLACEMENT_INDEX": off})
        for on in ("1", "true", "yes", "on", ""):
            assert env_placement_index_enabled(
                {"OPERATOR_PLACEMENT_INDEX": on})
        assert env_placement_index_enabled({})  # default on
