"""API layer: conversion machinery, spec defaults, conditions, CRD gen,
image resolution."""

import os

import pytest
import yaml

from tpu_operator.api import (
    TPUClusterPolicySpec,
    TPUDriverSpec,
    new_cluster_policy,
)
from tpu_operator.api.conditions import (
    COND_ERROR,
    COND_READY,
    get_condition,
    set_condition,
    set_error,
    set_ready,
)
from tpu_operator.api.convert import from_dict, schema_of, to_dict
from tpu_operator.api.crd import all_crds, cluster_policy_crd, tpu_driver_crd
from tpu_operator.api.image import env_var_for, image_path
from tpu_operator.api.labels import accelerator_generation, deploy_label
from tpu_operator.runtime import FakeClient


class TestConvert:
    def test_roundtrip_spec(self):
        raw = {
            "libtpu": {"enabled": True, "repository": "gcr.io/x",
                       "image": "libtpu-installer", "version": "1.2.3",
                       "installDir": "/opt/libtpu"},
            "devicePlugin": {"enabled": False},
            "validator": {"matmulSize": 2048,
                          "iciBandwidthThreshold": 0.9},
        }
        spec = TPUClusterPolicySpec.from_obj({"spec": raw})
        assert spec.libtpu.install_dir == "/opt/libtpu"
        assert spec.libtpu.is_enabled()
        assert not spec.device_plugin.is_enabled()
        assert spec.validator.matmul_size == 2048
        assert spec.validator.ici_bandwidth_threshold == 0.9
        wire = to_dict(spec)
        assert wire["libtpu"]["installDir"] == "/opt/libtpu"
        assert wire["validator"]["iciBandwidthThreshold"] == 0.9

    def test_unknown_fields_ignored(self):
        spec = TPUClusterPolicySpec.from_obj(
            {"spec": {"libtpu": {"futureKnob": 1}}})
        assert spec.libtpu is not None

    def test_defaults_fill_missing_sections(self):
        spec = TPUClusterPolicySpec.from_obj({"spec": {}})
        assert spec.device_plugin.resource_name == "google.com/tpu"
        assert spec.host_paths.validation_dir == "/run/tpu/validations"
        assert spec.daemonsets.priority_class_name == "system-node-critical"
        # explicit null sections normalize too
        spec2 = TPUClusterPolicySpec.from_obj({"spec": {"libtpu": None}})
        assert spec2.libtpu.channel == "stable"

    def test_component_enabled_default(self):
        spec = TPUClusterPolicySpec.from_obj({"spec": {}})
        assert spec.libtpu.is_enabled()
        assert not spec.metrics_exporter.is_enabled(default=False)


class TestConditions:
    def test_set_ready_and_flip(self):
        c = FakeClient()
        cr = c.create(new_cluster_policy())
        set_ready(c, cr, "all operands ready")
        got = c.get(cr["apiVersion"], cr["kind"], "tpu-cluster-policy")
        ready = get_condition(got, COND_READY)
        assert ready["status"] == "True"
        t0 = ready["lastTransitionTime"]
        set_error(c, got, "Boom", "bad")
        got = c.get(cr["apiVersion"], cr["kind"], "tpu-cluster-policy")
        assert get_condition(got, COND_READY)["status"] == "False"
        assert get_condition(got, COND_ERROR)["status"] == "True"

    def test_set_condition_reports_change(self):
        cr = {"metadata": {"generation": 1}}
        assert set_condition(cr, COND_READY, "True", "R")
        assert not set_condition(cr, COND_READY, "True", "R")
        assert set_condition(cr, COND_READY, "False", "R")


class TestCRDs:
    def test_crds_render_valid_yaml(self):
        for crd in all_crds():
            text = yaml.safe_dump(crd)
            back = yaml.safe_load(text)
            assert back["kind"] == "CustomResourceDefinition"

    def test_cluster_policy_schema_shape(self):
        crd = cluster_policy_crd()
        v = crd["spec"]["versions"][0]
        assert v["subresources"] == {"status": {}}
        props = v["schema"]["openAPIV3Schema"]["properties"]["spec"]["properties"]
        for key in ("libtpu", "tpuRuntime", "devicePlugin", "metricsExporter",
                    "nodeStatusExporter", "topologyManager", "validator",
                    "upgradePolicy", "hostPaths", "daemonsets", "operator"):
            assert key in props, key
        assert props["libtpu"]["properties"]["installDir"]["type"] == "string"

    def test_driver_type_immutable_cel(self):
        crd = tpu_driver_crd()
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        rules = schema["properties"]["spec"]["properties"]["driverType"][
            "x-kubernetes-validations"]
        assert rules[0]["rule"] == "self == oldSelf"


class TestImage:
    def test_image_path_joins(self):
        assert image_path("libtpu", "gcr.io/proj", "libtpu", "1.0") == \
            "gcr.io/proj/libtpu:1.0"

    def test_digest_uses_at(self):
        assert "@sha256:" in image_path("libtpu", "gcr.io/p", "i",
                                        "sha256:" + "a" * 64)

    def test_env_fallback(self):
        os.environ[env_var_for("metrics-exporter")] = "gcr.io/fallback/me:9"
        try:
            assert image_path("metrics-exporter", None, None, None) == \
                "gcr.io/fallback/me:9"
        finally:
            del os.environ[env_var_for("metrics-exporter")]

    def test_unresolvable_raises(self):
        with pytest.raises(ValueError):
            image_path("nope", None, None, None)

    def test_fully_qualified_passthrough(self):
        assert image_path("x", None, "gcr.io/p/i:tag", None) == "gcr.io/p/i:tag"


class TestLabels:
    def test_generation_mapping(self):
        assert accelerator_generation("tpu-v4-podslice") == "v4"
        assert accelerator_generation("tpu-v5-lite-podslice") == "v5e"
        assert accelerator_generation("tpu-v5p-slice") == "v5p"
        assert accelerator_generation("tpu-v6e-slice") == "v6e"

    def test_deploy_label(self):
        assert deploy_label("libtpu-driver") == "tpu.graft.dev/deploy.libtpu-driver"


class TestTPUDriverSpec:
    def test_defaults(self):
        spec = TPUDriverSpec.from_obj({"spec": {}})
        assert spec.driver_type == "libtpu"
        assert spec.channel == "stable"

    def test_node_selector_roundtrip(self):
        spec = TPUDriverSpec.from_obj(
            {"spec": {"nodeSelector": {"pool": "v5p"},
                      "upgradePolicy": {"maxUnavailable": "50%"}}})
        assert spec.node_selector == {"pool": "v5p"}
        assert spec.upgrade_policy.max_unavailable == "50%"
