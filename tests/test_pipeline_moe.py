"""Pipeline (pp) and expert (ep) parallelism workloads on the virtual
8-device CPU mesh — oracle-checked like ring attention
(tests/test_ringattention.py pattern). Completes the dp/tp/pp/sp/ep
strategy set the dryrun exercises."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_operator.parallel.mesh import ring_mesh
from tpu_operator.workloads import moe, pipeline


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provide 8 virtual CPU devices"
    return devs[:8]


class TestPipelineParallel:
    def test_matches_sequential_oracle(self, devices):
        res = pipeline.run(mesh=ring_mesh(devices, axis_name="pipe"))
        assert res.correct, res
        assert res.stages == 8

    def test_uneven_microbatch_count(self, devices):
        # M=2 microbatches over 8 stages: mostly-bubble schedule must
        # still be exact
        res = pipeline.run(mesh=ring_mesh(devices, axis_name="pipe"),
                           batch=8, n_microbatches=2)
        assert res.correct, res

    def test_four_stage_pipeline(self, devices):
        res = pipeline.run(mesh=ring_mesh(devices[:4], axis_name="pipe"),
                           batch=8, n_microbatches=8)
        assert res.correct, res
        assert res.stages == 4

    def test_stage_fn_differs_per_stage(self):
        """The oracle must actually exercise distinct per-stage weights —
        a pipeline that applied one stage S times would pass a test with
        identical stages."""
        params = pipeline.init_stage_params(jax.random.PRNGKey(0), 4, 8, 16)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8))
        full = pipeline.reference_forward(params, x)
        same = x
        for _ in range(4):
            same = pipeline.stage_fn(
                jax.tree_util.tree_map(lambda a: a[0], params), same)
        assert not np.allclose(full, same)

    def test_batch_must_divide_microbatches(self, devices):
        with pytest.raises(AssertionError):
            pipeline.run(mesh=ring_mesh(devices, axis_name="pipe"),
                         batch=6, n_microbatches=4)


class TestPipelineTrainability:
    def test_gradients_match_sequential_oracle(self, devices):
        """The GPipe schedule is trainable: grads through scan + ppermute
        + the masked-psum output must match autodiff of the sequential
        oracle (stage weights get real gradients on every device)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = ring_mesh(devices[:4], axis_name="pipe")
        params = pipeline.init_stage_params(jax.random.PRNGKey(0), 4, 16, 32)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
        sp = jax.device_put(params, NamedSharding(mesh, P("pipe")))

        def loss(p, x):
            return jnp.sum(pipeline.pipeline_forward(
                p, x, mesh, n_microbatches=4) ** 2)

        def ref_loss(p, x):
            return jnp.sum(pipeline.reference_forward(p, x) ** 2)

        g = jax.grad(loss)(sp, x)
        g_ref = jax.grad(ref_loss)(params, x)
        for key in params:
            np.testing.assert_allclose(
                np.asarray(g[key]), np.asarray(g_ref[key]),
                rtol=1e-3, atol=1e-3, err_msg=key)
            assert float(jnp.max(jnp.abs(g[key]))) > 0, f"dead grad: {key}"


class TestExpertParallel:
    def test_matches_single_device_oracle(self, devices):
        res = moe.run(mesh=ring_mesh(devices, axis_name="expert"))
        assert res.correct, res
        assert res.experts == 8

    def test_capacity_drops_match_oracle(self, devices):
        """With capacity below the resident token count, overflow tokens
        are dropped identically on both paths (zero output rows)."""
        mesh = ring_mesh(devices, axis_name="expert")
        n_dev = 8
        params = moe.init_moe_params(jax.random.PRNGKey(0), n_dev, 16, 32)
        x = jax.random.normal(jax.random.PRNGKey(1), (n_dev * 12, 16))
        cap = 2  # far below 12 resident tokens -> guaranteed drops
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        sp = jax.device_put(params, {
            "router": NamedSharding(mesh, P()),
            "w1": NamedSharding(mesh, P("expert")),
            "w2": NamedSharding(mesh, P("expert"))})
        xs = jax.device_put(x, NamedSharding(mesh, P("expert")))
        out = jax.jit(partial(moe.moe_forward, mesh=mesh,
                              capacity=cap))(sp, xs)
        oracle = moe.reference_moe(params, x, n_dev, cap)
        assert float(jnp.max(jnp.abs(out - oracle))) < 1e-4
        dropped = float(jnp.mean(jnp.all(np.asarray(oracle) == 0.0,
                                         axis=-1)))
        assert dropped > 0.0, "capacity=2 must actually drop tokens"

    def test_router_sends_tokens_to_multiple_experts(self):
        """Routing must be non-degenerate: random tokens spread over >1
        expert (a collapsed router would make the exchange test vacuous)."""
        params = moe.init_moe_params(jax.random.PRNGKey(0), 8, 16, 32)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        logits = x @ params["router"]
        experts = set(np.asarray(jnp.argmax(logits, axis=-1)).tolist())
        assert len(experts) > 2

    def test_four_expert_mesh(self, devices):
        res = moe.run(mesh=ring_mesh(devices[:4], axis_name="expert"),
                      tokens_per_expert=8)
        assert res.correct, res
        assert res.experts == 4

    def test_moe_gradients_match_oracle(self, devices):
        """Switch-style training path: router (through the gate values)
        and per-expert weights all receive gradients matching the
        single-device oracle — all_to_all is transparent to autodiff."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = ring_mesh(devices[:4], axis_name="expert")
        params = moe.init_moe_params(jax.random.PRNGKey(0), 4, 16, 32)
        x = jax.random.normal(jax.random.PRNGKey(1), (4 * 8, 16))
        sp = jax.device_put(params, {
            "router": NamedSharding(mesh, P()),
            "w1": NamedSharding(mesh, P("expert")),
            "w2": NamedSharding(mesh, P("expert"))})
        sx = jax.device_put(x, NamedSharding(mesh, P("expert")))

        def loss(p, x):
            return jnp.sum(moe.moe_forward(p, x, mesh, capacity=8) ** 2)

        def ref_loss(p, x):
            return jnp.sum(moe.reference_moe(p, x, 4, 8) ** 2)

        g = jax.grad(loss)(sp, sx)
        g_ref = jax.grad(ref_loss)(params, x)
        for key in params:
            np.testing.assert_allclose(
                np.asarray(g[key]), np.asarray(g_ref[key]),
                rtol=1e-4, atol=1e-5, err_msg=key)
            assert float(jnp.max(jnp.abs(g[key]))) > 0, f"dead grad: {key}"
