"""Informer-backed cache coherence (runtime/cache.py).

Four claims under test:

1. Read path — warm gets/lists are served from the store with ZERO
   apiserver get/list verbs, copy-free frozen reads (mutation raises,
   thaw_obj yields a private copy), and fake-identical selector
   semantics.
2. Read-your-writes — a get immediately after the client's own
   update/update_status never observes a staler resourceVersion than
   the write returned.
3. Healing — a watch stream dropped mid-gap (writes land while no
   stream is connected) is detected on resume and healed by relist:
   post-gap updates, creates AND deletes all become visible.
4. Indexes — secondary indexes stay consistent under DELETED events,
   and the by-accelerator bucket union equals the TPU node set even
   for capacity-only (unlabeled) nodes.

The 100-node cached chaos runs for every scenario live in
test_chaos.py::TestScenariosConverge (``cached=True`` is the runner
default); here the watch-flap verdict's cache metadata is asserted
explicitly.
"""

import pytest

from tpu_operator.api import labels as L
from tpu_operator.chaos.faults import ChaosClient
from tpu_operator.chaos.runner import run_scenario
from tpu_operator.runtime import CachedClient, FakeClient
from tpu_operator.runtime.objects import (
    FrozenDict,
    FrozenList,
    FrozenObjectError,
    freeze_obj,
    thaw_obj,
)


def _cm(name, data, namespace="tpu-operator"):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": namespace},
            "data": data}


def _pod(name, node, labels=None, namespace="tpu-operator"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": namespace,
                         "labels": labels or {}},
            "spec": {"nodeName": node}}


@pytest.fixture
def fake():
    c = FakeClient()
    yield c


@pytest.fixture
def cached(fake):
    cc = CachedClient(fake)
    yield cc
    cc.close()


class TestFrozenObjects:
    """freeze_obj/thaw_obj invariants the zero-copy read path rests on."""

    def test_freeze_thaw_round_trip(self):
        obj = {"metadata": {"labels": {"a": "1"}},
               "spec": {"containers": [{"name": "c", "ports": [1, 2]}]}}
        frozen = freeze_obj(obj)
        assert isinstance(frozen, FrozenDict)
        assert isinstance(frozen["spec"]["containers"], FrozenList)
        for mutate in (lambda: frozen.update({}),
                       lambda: frozen["spec"]["containers"].append({}),
                       lambda: frozen["metadata"]["labels"].pop("a"),
                       lambda: frozen.setdefault("status", {})):
            with pytest.raises(FrozenObjectError):
                mutate()
        thawed = thaw_obj(frozen)
        assert thawed == obj
        assert type(thawed) is dict
        assert type(thawed["spec"]["containers"]) is list
        thawed["spec"]["containers"][0]["name"] = "other"  # mutable again
        assert frozen["spec"]["containers"][0]["name"] == "c"

    def test_frozen_objects_serialize_like_plain(self):
        import json

        import yaml

        obj = freeze_obj({"kind": "ConfigMap", "data": {"k": ["v", 1]}})
        plain = thaw_obj(obj)
        assert json.dumps(obj, sort_keys=True) == \
            json.dumps(plain, sort_keys=True)
        dumped = yaml.safe_dump(obj)
        assert dumped == yaml.safe_dump(plain)
        assert "!!python" not in dumped  # no type tags leak into manifests


class TestReadPath:
    def test_warm_reads_issue_zero_apiserver_verbs(self, fake, cached):
        for i in range(8):
            fake.add_node(f"tpu-{i}",
                          labels={L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice"},
                          allocatable={L.TPU_RESOURCE: "4"})
        fake.create(_cm("a", {"k": "1"}))
        cached.list("v1", "Node")          # warm: one bootstrap LIST each
        cached.list("v1", "ConfigMap")
        fake.reset_verb_counts()
        for _ in range(25):
            assert len(cached.list("v1", "Node")) == 8
            assert cached.get("v1", "ConfigMap", "a",
                              namespace="tpu-operator")["data"] == {"k": "1"}
        assert "list" not in fake.verb_counts, fake.verb_counts
        assert "get" not in fake.verb_counts, fake.verb_counts

    def test_frozen_reads_isolate_callers(self, fake, cached):
        # copy-free reads: mutating a cached read raises loudly instead
        # of corrupting the shared store (the old deepcopy-on-read
        # isolation, without paying a deepcopy per read)
        fake.create(_cm("a", {"k": "1"}))
        got = cached.get("v1", "ConfigMap", "a", namespace="tpu-operator")
        with pytest.raises(FrozenObjectError):
            got["data"]["k"] = "corrupted"
        # thaw_obj is the sanctioned mutation path: a private copy that
        # leaves the store untouched
        mine = thaw_obj(got)
        mine["data"]["k"] = "corrupted"
        again = cached.get("v1", "ConfigMap", "a", namespace="tpu-operator")
        assert again["data"] == {"k": "1"}

    def test_list_matches_fake_selector_semantics(self, fake, cached):
        fake.create(_pod("p1", "n1", labels={"app": "x", "tier": "db"}))
        fake.create(_pod("p2", "n1", labels={"app": "x"}))
        fake.create(_pod("p3", "n2", labels={"app": "y"}))
        from tpu_operator.runtime.client import ListOptions
        for sel in ({"app": "x"}, {"app": "x", "tier": "db"},
                    {"app": "z"}, None):
            opts = ListOptions(label_selector=sel) if sel else None
            want = sorted(p["metadata"]["name"]
                          for p in fake.list("v1", "Pod", opts))
            got = sorted(p["metadata"]["name"]
                         for p in cached.list("v1", "Pod", opts))
            assert got == want, (sel, got, want)


class TestReadYourWrites:
    def test_get_after_own_update_never_staler(self, fake, cached):
        obj = thaw_obj(cached.create(_cm("rv", {"n": "0"})))
        for i in range(1, 12):
            obj["data"]["n"] = str(i)
            written = cached.update(obj)
            wrote_rv = int(written["metadata"]["resourceVersion"])
            got = cached.get("v1", "ConfigMap", "rv",
                             namespace="tpu-operator")
            got_rv = int(got["metadata"]["resourceVersion"])
            assert got_rv >= wrote_rv, (i, got_rv, wrote_rv)
            assert got["data"]["n"] == str(i)
            obj = thaw_obj(got)

    def test_update_status_write_through(self, fake, cached):
        fake.create({"apiVersion": "v1", "kind": "Node",
                     "metadata": {"name": "n1"}})
        node = thaw_obj(cached.get("v1", "Node", "n1"))
        node.setdefault("status", {})["phase"] = "Ready"
        written = cached.update_status(node)
        got = cached.get("v1", "Node", "n1")
        assert int(got["metadata"]["resourceVersion"]) >= \
            int(written["metadata"]["resourceVersion"])
        assert got["status"]["phase"] == "Ready"


class TestHealing:
    def test_gap_writes_served_after_heal(self, fake):
        chaos = ChaosClient(fake)
        cached = CachedClient(chaos)
        try:
            # one object stays untouched across the gap: its same-RV
            # ADDED replay on resume is the resumed-stream signature the
            # cache keys its relist decision on
            cached.create(_cm("anchor", {"k": "0"}))
            victim = cached.create(_cm("victim", {"k": "0"}))
            cached.create(_cm("doomed", {"k": "0"}))
            assert cached.list("v1", "ConfigMap")  # informer live

            chaos.suspend_watch_streams()
            # mutate behind the cache's back — no stream is connected,
            # so these events are genuinely lost, not merely delayed
            victim = thaw_obj(fake.get("v1", "ConfigMap", "victim",
                                       namespace="tpu-operator"))
            victim["data"]["k"] = "post-gap"
            victim = fake.update(victim)
            fake.create(_cm("born-in-gap", {"k": "1"}))
            fake.delete("v1", "ConfigMap", "doomed",
                        namespace="tpu-operator")
            chaos.resume_watch_streams()  # ADDED replay for live objects

            relists_before = cached.relists
            names = sorted(c["metadata"]["name"]
                           for c in cached.list("v1", "ConfigMap"))
            assert names == ["anchor", "born-in-gap", "victim"], names
            got = cached.get("v1", "ConfigMap", "victim",
                             namespace="tpu-operator")
            assert got["data"]["k"] == "post-gap"
            assert int(got["metadata"]["resourceVersion"]) >= \
                int(victim["metadata"]["resourceVersion"])
            assert cached.relists > relists_before  # healed BY relist
        finally:
            cached.close()

    def test_watch_flap_scenario_runs_cached(self):
        v = run_scenario("watch-flap", nodes=100, seed=7)
        assert v["ok"] is True and v["converged"] is True
        assert v["cached"] is True
        assert v["cache_relists"] > 0  # the drops actually exercised healing
        assert v["violations"] == []

    def test_conflict_storm_cached_flag(self):
        v = run_scenario("conflict-storm", nodes=24, seed=3)
        assert v["ok"] is True
        assert v["cached"] is True


class TestIndexes:
    def test_by_node_index_consistent_under_deleted(self, fake, cached):
        fake.create(_pod("p1", "n1"))
        fake.create(_pod("p2", "n1"))
        fake.create(_pod("p3", "n2"))
        assert sorted(p["metadata"]["name"] for p in
                      cached.index("v1", "Pod", "by-node", "n1")) == \
            ["p1", "p2"]
        fake.delete("v1", "Pod", "p1", namespace="tpu-operator")
        assert [p["metadata"]["name"] for p in
                cached.index("v1", "Pod", "by-node", "n1")] == ["p2"]
        fake.delete("v1", "Pod", "p2", namespace="tpu-operator")
        assert cached.index("v1", "Pod", "by-node", "n1") == []
        # the other bucket is untouched
        assert [p["metadata"]["name"] for p in
                cached.index("v1", "Pod", "by-node", "n2")] == ["p3"]

    def test_label_index_consistent_under_deleted(self, fake, cached):
        from tpu_operator.runtime.client import ListOptions
        fake.create(_pod("p1", "n1", labels={"app": "x"}))
        fake.create(_pod("p2", "n1", labels={"app": "x"}))
        opts = ListOptions(label_selector={"app": "x"})
        assert len(cached.list("v1", "Pod", opts)) == 2
        fake.delete("v1", "Pod", "p1", namespace="tpu-operator")
        assert [p["metadata"]["name"]
                for p in cached.list("v1", "Pod", opts)] == ["p2"]

    def test_accelerator_bucket_union_is_tpu_node_set(self, fake, cached):
        fake.add_node("tpu-a",
                      labels={L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice"},
                      allocatable={L.TPU_RESOURCE: "4"})
        fake.add_node("cpu-1", labels={})
        # capacity-only node: no accelerator label, still a TPU node
        fake.add_node("tpu-bare", labels={},
                      allocatable={L.TPU_RESOURCE: "8"})
        from tpu_operator.runtime.cache import UNLABELED_TPU
        keys = cached.index_keys("v1", "Node", "by-accelerator")
        assert keys == sorted([UNLABELED_TPU, "tpu-v5p-slice"])
        union = sorted(
            n["metadata"]["name"] for k in keys
            for n in cached.index("v1", "Node", "by-accelerator", k))
        assert union == ["tpu-a", "tpu-bare"]
        fake.delete("v1", "Node", "tpu-bare")
        assert cached.index_keys("v1", "Node", "by-accelerator") == \
            ["tpu-v5p-slice"]

    def test_unknown_index_raises(self, fake, cached):
        with pytest.raises(KeyError, match="no index"):
            cached.index("v1", "Pod", "by-zone", "z1")


def _node(name, labels=None, images=0):
    status = {"conditions": [{"type": "Ready", "status": "True"}],
              "capacity": {"cpu": "8"}, "allocatable": {"cpu": "8"}}
    if images:
        status["images"] = [
            {"names": [f"img-{i}@sha256:{i:064x}"], "sizeBytes": i}
            for i in range(images)]
        status["volumesInUse"] = [f"vol-{i}" for i in range(4)]
    return {"apiVersion": "v1", "kind": "Node",
            "metadata": {"name": name, "labels": labels or {}},
            "spec": {}, "status": status}


class TestPagination:
    def test_fake_list_pages_cover_everything_once(self, fake):
        from tpu_operator.runtime import ListOptions
        from tpu_operator.runtime.objects import name_of

        for i in range(23):
            fake.create(_cm(f"cm-{i:02d}", {"v": str(i)}))
        seen, token, pages = [], None, 0
        while True:
            page = fake.list("v1", "ConfigMap",
                             ListOptions(limit=10, continue_=token))
            pages += 1
            seen.extend(name_of(o) for o in page)
            token = getattr(page, "continue_", None)
            if not token:
                break
        assert pages == 3
        assert seen == sorted(seen)  # obj_key order, stable across pages
        assert sorted(seen) == [f"cm-{i:02d}" for i in range(23)]

    def test_limit_at_least_collection_returns_plain_list(self, fake):
        from tpu_operator.runtime import ListOptions

        for i in range(5):
            fake.create(_cm(f"cm-{i}", {}))
        page = fake.list("v1", "ConfigMap", ListOptions(limit=5))
        assert getattr(page, "continue_", None) is None
        assert len(page) == 5

    def test_chunked_relist_matches_unchunked(self, fake):
        for i in range(12):
            fake.add_node(f"n-{i:02d}")
        chunked = CachedClient(fake, relist_chunk=5)
        plain = CachedClient(fake, relist_chunk=0)
        try:
            fake.reset_verb_counts()
            a = {o["metadata"]["name"] for o in chunked.list("v1", "Node")}
            chunked.resync()  # forced heal through the paged path
            pages = fake.reset_verb_counts().get("list", 0)
            assert pages >= 1 + 3  # warm list + ceil(12/5) relist pages
            b = {o["metadata"]["name"] for o in plain.list("v1", "Node")}
            assert a == b == {f"n-{i:02d}" for i in range(12)}
        finally:
            chunked.close()
            plain.close()


class TestRelistGuard:
    def test_reader_losing_the_race_serves_stale_not_blocks(self, fake,
                                                            cached):
        import threading
        import time as _time

        fake.create(_cm("a", {"v": "1"}))
        assert cached.list("v1", "ConfigMap")  # warm the informer
        store = cached._stores[("v1", "ConfigMap")]
        store.needs_relist = True
        assert store.relist_lock.acquire(blocking=False)  # healer busy
        try:
            done = threading.Event()
            result = {}

            def read():
                t0 = _time.perf_counter()
                result["objs"] = cached.list("v1", "ConfigMap")
                result["s"] = _time.perf_counter() - t0
                done.set()

            t = threading.Thread(target=read)
            t.start()
            assert done.wait(2.0), "reader convoyed behind the relist"
            t.join()
            # served the current view immediately, no heal performed
            assert [o["metadata"]["name"] for o in result["objs"]] == ["a"]
            assert store.needs_relist  # still dirty: loser didn't heal
            assert result["s"] < 0.5
        finally:
            store.relist_lock.release()
        cached.list("v1", "ConfigMap")  # next reader wins the lock
        assert not store.needs_relist  # ... and heals


class TestProjection:
    def test_node_projection_drops_fat_status_but_keeps_reads(self, fake,
                                                              cached):
        fake.create(_node("fat", images=30))
        got = cached.get("v1", "Node", "fat")
        # the health-relevant fields survive ...
        assert got["status"]["conditions"][0]["type"] == "Ready"
        assert got["status"]["capacity"] == {"cpu": "8"}
        # ... the kubelet image/volume payload does not
        assert "images" not in got["status"]
        assert "volumesInUse" not in got["status"]
        stats = cached.cache_stats()["kinds"]["v1/Node"]
        assert stats["projected"]
        assert 0 < stats["bytes"] < stats["full_bytes"]

    def test_projection_gate_off_stores_full_objects(self, fake):
        from tpu_operator.runtime.cache import PROJECTION_GATE

        prev = PROJECTION_GATE.enabled
        PROJECTION_GATE.enabled = False
        try:
            cc = CachedClient(fake)
            fake.create(_node("fat", images=30))
            got = cc.get("v1", "Node", "fat")
            assert len(got["status"]["images"]) == 30  # nothing dropped
            stats = cc.cache_stats()
            assert not stats["projection_enabled"]
            assert not stats["kinds"]["v1/Node"]["projected"]
            cc.close()
        finally:
            PROJECTION_GATE.enabled = prev

    def test_bytes_accounting_returns_to_zero_on_delete(self, fake,
                                                        cached):
        fake.create(_node("n1", images=10))
        fake.create(_node("n2", images=10))
        cached.list("v1", "Node")
        stats = cached.cache_stats()["kinds"]["v1/Node"]
        assert stats["objects"] == 2 and stats["bytes"] > 0
        fake.delete("v1", "Node", "n1")
        fake.delete("v1", "Node", "n2")
        cached.list("v1", "Node")
        stats = cached.cache_stats()["kinds"]["v1/Node"]
        assert stats["objects"] == 0
        assert stats["bytes"] == 0 and stats["full_bytes"] == 0


class TestCacheCLI:
    """``tpuop-cfg cache`` renders a /debug/cache snapshot (or a saved
    cache.json) — the same CLI surface test_tracing.py pins for
    ``tpuop-cfg trace``."""

    def _stats(self, fake):
        cc = CachedClient(fake)
        fake.create(_node("fat-0", images=20))
        fake.create(_node("fat-1", images=20))
        cc.list("v1", "Node")
        stats = cc.cache_stats()
        cc.close()
        return stats

    def test_render_shows_projected_vs_full_bytes(self, fake):
        from tpu_operator.cli.tpuop_cfg import render_cache_stats

        out = render_cache_stats(self._stats(fake))
        lines = out.splitlines()
        assert lines[0].startswith("projection: on")
        node_line = next(l for l in lines if l.startswith("v1/Node:"))
        assert "2 objects" in node_line
        assert "projected (" in node_line and "full)" in node_line

    def test_cli_reads_file_and_json_roundtrips(self, tmp_path, capsys,
                                                fake):
        import json

        from tpu_operator.cli.tpuop_cfg import main

        stats = self._stats(fake)
        f = tmp_path / "cache.json"
        f.write_text(json.dumps(stats))
        rc = main(["cache", "-f", str(f)])
        assert rc == 0
        assert "v1/Node" in capsys.readouterr().out
        rc = main(["cache", "-f", str(f), "-o", "json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out) == stats
        rc = main(["cache", "-f", str(tmp_path / "missing.json")])
        assert rc == 1
        assert "cannot read" in capsys.readouterr().err
