"""Informer-backed cache coherence (runtime/cache.py).

Four claims under test:

1. Read path — warm gets/lists are served from the store with ZERO
   apiserver get/list verbs, copy-free frozen reads (mutation raises,
   thaw_obj yields a private copy), and fake-identical selector
   semantics.
2. Read-your-writes — a get immediately after the client's own
   update/update_status never observes a staler resourceVersion than
   the write returned.
3. Healing — a watch stream dropped mid-gap (writes land while no
   stream is connected) is detected on resume and healed by relist:
   post-gap updates, creates AND deletes all become visible.
4. Indexes — secondary indexes stay consistent under DELETED events,
   and the by-accelerator bucket union equals the TPU node set even
   for capacity-only (unlabeled) nodes.

The 100-node cached chaos runs for every scenario live in
test_chaos.py::TestScenariosConverge (``cached=True`` is the runner
default); here the watch-flap verdict's cache metadata is asserted
explicitly.
"""

import pytest

from tpu_operator.api import labels as L
from tpu_operator.chaos.faults import ChaosClient
from tpu_operator.chaos.runner import run_scenario
from tpu_operator.runtime import CachedClient, FakeClient
from tpu_operator.runtime.objects import (
    FrozenDict,
    FrozenList,
    FrozenObjectError,
    freeze_obj,
    thaw_obj,
)


def _cm(name, data, namespace="tpu-operator"):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": namespace},
            "data": data}


def _pod(name, node, labels=None, namespace="tpu-operator"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": namespace,
                         "labels": labels or {}},
            "spec": {"nodeName": node}}


@pytest.fixture
def fake():
    c = FakeClient()
    yield c


@pytest.fixture
def cached(fake):
    cc = CachedClient(fake)
    yield cc
    cc.close()


class TestFrozenObjects:
    """freeze_obj/thaw_obj invariants the zero-copy read path rests on."""

    def test_freeze_thaw_round_trip(self):
        obj = {"metadata": {"labels": {"a": "1"}},
               "spec": {"containers": [{"name": "c", "ports": [1, 2]}]}}
        frozen = freeze_obj(obj)
        assert isinstance(frozen, FrozenDict)
        assert isinstance(frozen["spec"]["containers"], FrozenList)
        for mutate in (lambda: frozen.update({}),
                       lambda: frozen["spec"]["containers"].append({}),
                       lambda: frozen["metadata"]["labels"].pop("a"),
                       lambda: frozen.setdefault("status", {})):
            with pytest.raises(FrozenObjectError):
                mutate()
        thawed = thaw_obj(frozen)
        assert thawed == obj
        assert type(thawed) is dict
        assert type(thawed["spec"]["containers"]) is list
        thawed["spec"]["containers"][0]["name"] = "other"  # mutable again
        assert frozen["spec"]["containers"][0]["name"] == "c"

    def test_frozen_objects_serialize_like_plain(self):
        import json

        import yaml

        obj = freeze_obj({"kind": "ConfigMap", "data": {"k": ["v", 1]}})
        plain = thaw_obj(obj)
        assert json.dumps(obj, sort_keys=True) == \
            json.dumps(plain, sort_keys=True)
        dumped = yaml.safe_dump(obj)
        assert dumped == yaml.safe_dump(plain)
        assert "!!python" not in dumped  # no type tags leak into manifests


class TestReadPath:
    def test_warm_reads_issue_zero_apiserver_verbs(self, fake, cached):
        for i in range(8):
            fake.add_node(f"tpu-{i}",
                          labels={L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice"},
                          allocatable={L.TPU_RESOURCE: "4"})
        fake.create(_cm("a", {"k": "1"}))
        cached.list("v1", "Node")          # warm: one bootstrap LIST each
        cached.list("v1", "ConfigMap")
        fake.reset_verb_counts()
        for _ in range(25):
            assert len(cached.list("v1", "Node")) == 8
            assert cached.get("v1", "ConfigMap", "a",
                              namespace="tpu-operator")["data"] == {"k": "1"}
        assert "list" not in fake.verb_counts, fake.verb_counts
        assert "get" not in fake.verb_counts, fake.verb_counts

    def test_frozen_reads_isolate_callers(self, fake, cached):
        # copy-free reads: mutating a cached read raises loudly instead
        # of corrupting the shared store (the old deepcopy-on-read
        # isolation, without paying a deepcopy per read)
        fake.create(_cm("a", {"k": "1"}))
        got = cached.get("v1", "ConfigMap", "a", namespace="tpu-operator")
        with pytest.raises(FrozenObjectError):
            got["data"]["k"] = "corrupted"
        # thaw_obj is the sanctioned mutation path: a private copy that
        # leaves the store untouched
        mine = thaw_obj(got)
        mine["data"]["k"] = "corrupted"
        again = cached.get("v1", "ConfigMap", "a", namespace="tpu-operator")
        assert again["data"] == {"k": "1"}

    def test_list_matches_fake_selector_semantics(self, fake, cached):
        fake.create(_pod("p1", "n1", labels={"app": "x", "tier": "db"}))
        fake.create(_pod("p2", "n1", labels={"app": "x"}))
        fake.create(_pod("p3", "n2", labels={"app": "y"}))
        from tpu_operator.runtime.client import ListOptions
        for sel in ({"app": "x"}, {"app": "x", "tier": "db"},
                    {"app": "z"}, None):
            opts = ListOptions(label_selector=sel) if sel else None
            want = sorted(p["metadata"]["name"]
                          for p in fake.list("v1", "Pod", opts))
            got = sorted(p["metadata"]["name"]
                         for p in cached.list("v1", "Pod", opts))
            assert got == want, (sel, got, want)


class TestReadYourWrites:
    def test_get_after_own_update_never_staler(self, fake, cached):
        obj = thaw_obj(cached.create(_cm("rv", {"n": "0"})))
        for i in range(1, 12):
            obj["data"]["n"] = str(i)
            written = cached.update(obj)
            wrote_rv = int(written["metadata"]["resourceVersion"])
            got = cached.get("v1", "ConfigMap", "rv",
                             namespace="tpu-operator")
            got_rv = int(got["metadata"]["resourceVersion"])
            assert got_rv >= wrote_rv, (i, got_rv, wrote_rv)
            assert got["data"]["n"] == str(i)
            obj = thaw_obj(got)

    def test_update_status_write_through(self, fake, cached):
        fake.create({"apiVersion": "v1", "kind": "Node",
                     "metadata": {"name": "n1"}})
        node = thaw_obj(cached.get("v1", "Node", "n1"))
        node.setdefault("status", {})["phase"] = "Ready"
        written = cached.update_status(node)
        got = cached.get("v1", "Node", "n1")
        assert int(got["metadata"]["resourceVersion"]) >= \
            int(written["metadata"]["resourceVersion"])
        assert got["status"]["phase"] == "Ready"


class TestHealing:
    def test_gap_writes_served_after_heal(self, fake):
        chaos = ChaosClient(fake)
        cached = CachedClient(chaos)
        try:
            # one object stays untouched across the gap: its same-RV
            # ADDED replay on resume is the resumed-stream signature the
            # cache keys its relist decision on
            cached.create(_cm("anchor", {"k": "0"}))
            victim = cached.create(_cm("victim", {"k": "0"}))
            cached.create(_cm("doomed", {"k": "0"}))
            assert cached.list("v1", "ConfigMap")  # informer live

            chaos.suspend_watch_streams()
            # mutate behind the cache's back — no stream is connected,
            # so these events are genuinely lost, not merely delayed
            victim = thaw_obj(fake.get("v1", "ConfigMap", "victim",
                                       namespace="tpu-operator"))
            victim["data"]["k"] = "post-gap"
            victim = fake.update(victim)
            fake.create(_cm("born-in-gap", {"k": "1"}))
            fake.delete("v1", "ConfigMap", "doomed",
                        namespace="tpu-operator")
            chaos.resume_watch_streams()  # ADDED replay for live objects

            relists_before = cached.relists
            names = sorted(c["metadata"]["name"]
                           for c in cached.list("v1", "ConfigMap"))
            assert names == ["anchor", "born-in-gap", "victim"], names
            got = cached.get("v1", "ConfigMap", "victim",
                             namespace="tpu-operator")
            assert got["data"]["k"] == "post-gap"
            assert int(got["metadata"]["resourceVersion"]) >= \
                int(victim["metadata"]["resourceVersion"])
            assert cached.relists > relists_before  # healed BY relist
        finally:
            cached.close()

    def test_watch_flap_scenario_runs_cached(self):
        v = run_scenario("watch-flap", nodes=100, seed=7)
        assert v["ok"] is True and v["converged"] is True
        assert v["cached"] is True
        assert v["cache_relists"] > 0  # the drops actually exercised healing
        assert v["violations"] == []

    def test_conflict_storm_cached_flag(self):
        v = run_scenario("conflict-storm", nodes=24, seed=3)
        assert v["ok"] is True
        assert v["cached"] is True


class TestIndexes:
    def test_by_node_index_consistent_under_deleted(self, fake, cached):
        fake.create(_pod("p1", "n1"))
        fake.create(_pod("p2", "n1"))
        fake.create(_pod("p3", "n2"))
        assert sorted(p["metadata"]["name"] for p in
                      cached.index("v1", "Pod", "by-node", "n1")) == \
            ["p1", "p2"]
        fake.delete("v1", "Pod", "p1", namespace="tpu-operator")
        assert [p["metadata"]["name"] for p in
                cached.index("v1", "Pod", "by-node", "n1")] == ["p2"]
        fake.delete("v1", "Pod", "p2", namespace="tpu-operator")
        assert cached.index("v1", "Pod", "by-node", "n1") == []
        # the other bucket is untouched
        assert [p["metadata"]["name"] for p in
                cached.index("v1", "Pod", "by-node", "n2")] == ["p3"]

    def test_label_index_consistent_under_deleted(self, fake, cached):
        from tpu_operator.runtime.client import ListOptions
        fake.create(_pod("p1", "n1", labels={"app": "x"}))
        fake.create(_pod("p2", "n1", labels={"app": "x"}))
        opts = ListOptions(label_selector={"app": "x"})
        assert len(cached.list("v1", "Pod", opts)) == 2
        fake.delete("v1", "Pod", "p1", namespace="tpu-operator")
        assert [p["metadata"]["name"]
                for p in cached.list("v1", "Pod", opts)] == ["p2"]

    def test_accelerator_bucket_union_is_tpu_node_set(self, fake, cached):
        fake.add_node("tpu-a",
                      labels={L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice"},
                      allocatable={L.TPU_RESOURCE: "4"})
        fake.add_node("cpu-1", labels={})
        # capacity-only node: no accelerator label, still a TPU node
        fake.add_node("tpu-bare", labels={},
                      allocatable={L.TPU_RESOURCE: "8"})
        from tpu_operator.runtime.cache import UNLABELED_TPU
        keys = cached.index_keys("v1", "Node", "by-accelerator")
        assert keys == sorted([UNLABELED_TPU, "tpu-v5p-slice"])
        union = sorted(
            n["metadata"]["name"] for k in keys
            for n in cached.index("v1", "Node", "by-accelerator", k))
        assert union == ["tpu-a", "tpu-bare"]
        fake.delete("v1", "Node", "tpu-bare")
        assert cached.index_keys("v1", "Node", "by-accelerator") == \
            ["tpu-v5p-slice"]

    def test_unknown_index_raises(self, fake, cached):
        with pytest.raises(KeyError, match="no index"):
            cached.index("v1", "Pod", "by-zone", "z1")
