"""Property/fuzz tier for the two from-scratch engines.

The renderer (render/engine.py, the go-template subset) and the
mini-CEL evaluator (api/cel.py) are the riskiest original code in the
repo: both parse untrusted-ish text (operand templates, CRD admission
rules) and both claim a well-defined failure contract (TemplateError /
EvalError — never a raw Python crash). Example-based tests pin the
happy paths; these Hypothesis properties pin the CONTRACT:

- token-soup inputs either succeed or raise the engine's own error
  type (fail closed — a raw KeyError/IndexError here would be an
  admission bypass or a render crash inside the reconcile loop);
- differential oracles where one exists: CEL boolean precedence vs
  Python's, CEL integer comparisons vs Python's, toYaml round-trip
  through yaml.safe_load;
- the documented trim-marker and missingkey=error semantics hold for
  arbitrary whitespace/identifiers, not just the examples.

Deterministic (derandomize=True): CI failures reproduce exactly.
"""

import os
import string

import pytest
import yaml
from hypothesis import HealthCheck, given, settings, strategies as st

from tpu_operator.api.cel import EvalError, evaluate
from tpu_operator.render.engine import (
    MissingKeyError,
    TemplateError,
    render_string,
)

# 60 deterministic examples per property keeps the whole module ~7s so
# it can stay in the unit tier; raise TPU_FUZZ_EXAMPLES for deep runs.
FUZZ = settings(
    max_examples=int(os.environ.get("TPU_FUZZ_EXAMPLES", "60")),
    deadline=None, derandomize=True,
    suppress_health_check=[HealthCheck.too_slow])

# ---------------------------------------------------------------------------
# renderer
# ---------------------------------------------------------------------------

_TEMPLATE_TOKENS = st.sampled_from([
    "text ", "\n", "  ", "{{ .a }}", "{{ .b.c }}", "{{- .a }}",
    "{{ .a -}}", "{{ if .a }}", "{{ else }}", "{{ end }}",
    "{{ range .list }}", "{{ . }}", '{{ .a | default "x" }}',
    "{{ .a | quote }}", "{{ toYaml .b }}", "{{ .missing }}",
    "{{", "}}", "{{ | }}", "{{ .a | bogusfunc }}", "{{ end }}{{ end }}",
    "{{ if }}", "{{ range }}", '{{ printf "%d" .a }}', "{{ .list }}",
])

_RENDER_DATA = {"a": 1, "b": {"c": "y"}, "list": [1, 2]}


class TestRendererFuzz:
    @FUZZ
    @given(st.lists(_TEMPLATE_TOKENS, min_size=0, max_size=12))
    def test_token_soup_fails_closed(self, parts):
        """Any template assembled from plausible fragments either renders
        to a string or raises TemplateError — never a raw Python error."""
        src = "".join(parts)
        try:
            out = render_string(src, _RENDER_DATA)
        except TemplateError:
            return
        assert isinstance(out, str)

    @FUZZ
    @given(st.recursive(
        st.one_of(st.integers(-10**6, 10**6), st.booleans(),
                  st.text(string.ascii_letters + string.digits + " _-",
                          max_size=20)),
        lambda inner: st.one_of(
            st.lists(inner, max_size=4),
            st.dictionaries(st.text(string.ascii_lowercase, min_size=1,
                                    max_size=8), inner, max_size=4)),
        max_leaves=12))
    def test_to_yaml_roundtrip(self, value):
        """{{ toYaml .v }} output must parse back to the same value —
        operand manifests embed rendered YAML inside YAML, so a quoting
        bug here corrupts DaemonSets silently."""
        out = render_string("{{ toYaml .v }}", {"v": value})
        assert yaml.safe_load(out) == value

    @FUZZ
    @given(st.text(string.ascii_lowercase, min_size=1, max_size=10))
    def test_missingkey_always_errors(self, key):
        """missingkey=error semantics (render.go parity) for arbitrary
        identifiers, not just the examples."""
        data = {"present": 1}
        if key == "present":
            assert render_string("{{ .%s }}" % key, data) == "1"
            return
        with pytest.raises(MissingKeyError):
            render_string("{{ .%s }}" % key, data)

    @FUZZ
    @given(st.text(" \t\n", max_size=6),
           st.text(string.ascii_letters, min_size=1, max_size=8))
    def test_trim_markers(self, ws, val):
        """`{{-` eats ALL preceding whitespace; `-}}` eats following."""
        assert render_string("A" + ws + "{{- .v }}", {"v": val}) == "A" + val
        assert render_string("{{ .v -}}" + ws + "B", {"v": val}) == val + "B"

    @FUZZ
    @given(st.lists(_TEMPLATE_TOKENS, min_size=1, max_size=8))
    def test_deterministic(self, parts):
        src = "".join(parts)
        try:
            first = render_string(src, _RENDER_DATA)
        except TemplateError:
            return
        assert render_string(src, _RENDER_DATA) == first


# ---------------------------------------------------------------------------
# mini-CEL
# ---------------------------------------------------------------------------

_CEL_TOKENS = st.sampled_from([
    "self", "oldSelf", "self.x", "has(self.x)", "size(self)", "==", "!=",
    "<", "<=", "&&", "||", "!", "(", ")", "'s'", "3", "1.5", "in",
    "[1, 2]", "[]", ".", ",", "true", "null", "size(", "has(self",
])


class TestCelFuzz:
    @FUZZ
    @given(st.lists(_CEL_TOKENS, min_size=0, max_size=10),
           st.sampled_from([{"x": 1}, {}, "abc", [1, 2], 3, None]))
    def test_token_soup_fails_closed(self, parts, self_val):
        """Admission rules must fail closed: garbage evaluates to a bool
        or raises EvalError. A raw exception would escape the mock
        apiserver's rejection path — an admission bypass."""
        src = " ".join(parts)
        try:
            out = evaluate(src, self_val, {"x": 2})
        except EvalError:
            return
        assert isinstance(out, bool)

    @FUZZ
    @given(st.lists(st.booleans(), min_size=1, max_size=6),
           st.lists(st.sampled_from(["&&", "||"]), min_size=5, max_size=5),
           st.lists(st.booleans(), min_size=6, max_size=6))
    def test_boolean_precedence_matches_python(self, lits, ops, negs):
        """Differential oracle: mixed &&/||/! chains must bind the way
        CEL (and Python's and/or/not) binds — && over ||."""
        n = len(lits)
        cel_parts, py_parts = [], []
        for i, lit in enumerate(lits):
            neg_c = "!" if negs[i] else ""
            neg_p = "not " if negs[i] else ""
            cel_parts.append(f"{neg_c}{str(lit).lower()}")
            py_parts.append(f"{neg_p}{lit}")
            if i < n - 1:
                cel_parts.append(ops[i])
                py_parts.append("and" if ops[i] == "&&" else "or")
        expected = bool(eval(" ".join(py_parts)))  # noqa: S307 - literals only
        assert evaluate(" ".join(cel_parts), None) is expected

    @FUZZ
    @given(st.integers(-1000, 1000), st.integers(-1000, 1000),
           st.sampled_from(["==", "!=", "<", "<=", ">", ">="]))
    def test_int_comparisons_match_python(self, a, b, op):
        expected = eval(f"{a} {op} {b}")  # noqa: S307 - int literals only
        assert evaluate(f"{a} {op} {b}", None) is expected

    @FUZZ
    @given(st.integers(0, 9), st.lists(st.integers(0, 9), max_size=6))
    def test_in_over_lists_is_membership(self, needle, hay):
        src = f"{needle} in [{', '.join(map(str, hay))}]"
        assert evaluate(src, None) is (needle in hay)

    @FUZZ
    @given(st.integers(-9, 9), st.integers(1, 9))
    def test_list_commas_mandatory(self, a, b):
        """Real CEL evaluates [1-2] as the one-element list [-1] (binary
        minus); this evaluator has no binary minus, so the expression
        must ERROR — parsing it as the two-element [1, -2] would make a
        rule pass offline with different semantics than the apiserver."""
        with pytest.raises(EvalError):
            evaluate(f"{a} in [{a}-{b}]", None)
        with pytest.raises(EvalError):
            evaluate(f"[{a} {b}] == [{a} {b}]", None)

    @FUZZ
    @given(st.text(string.ascii_lowercase, min_size=1, max_size=6),
           st.text(string.ascii_lowercase, min_size=1, max_size=12))
    def test_in_over_strings_rejected(self, needle, hay):
        """Real CEL defines `in` over lists/maps only; the substring
        reading must stay an error so rules that would fail to compile
        on a real apiserver fail offline too (ADVICE r4)."""
        with pytest.raises(EvalError):
            evaluate(f"'{needle}' in '{hay}'", None)

    @FUZZ
    @given(st.dictionaries(st.sampled_from(["x", "y", "z"]),
                           st.integers(0, 5), max_size=3))
    def test_has_vs_member_access(self, obj):
        """has() is the presence probe; bare member access on an absent
        field is an EvalError (the CEL distinction the admission rules
        rely on)."""
        for key in ("x", "y"):
            assert evaluate(f"has(self.{key})", obj) is (key in obj)
            if key in obj:
                assert evaluate(f"self.{key} >= 0", obj) is True
            else:
                with pytest.raises(EvalError):
                    evaluate(f"self.{key} >= 0", obj)

    @FUZZ
    @given(st.one_of(
        st.text(string.ascii_letters, max_size=12),
        st.lists(st.integers(), max_size=6),
        st.dictionaries(st.text(string.ascii_lowercase, min_size=1,
                                max_size=4), st.integers(), max_size=4)))
    def test_size_matches_len(self, val):
        assert evaluate(f"size(self) == {len(val)}", val) is True
