"""Tier-3 shell e2e (tests/scripts/end-to-end.sh slot): the full install
-> verify -> restart -> validate -> workload pipeline through the real
CLIs, as CI would run it."""

import pathlib
import subprocess
import sys


def test_end_to_end_script():
    repo = pathlib.Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        ["bash", str(repo / "scripts" / "end-to-end.sh")],
        capture_output=True, text=True, timeout=600,
        env={"PATH": "/usr/bin:/bin", "PYTHON": sys.executable,
             "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
             "HOME": "/tmp"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "END_TO_END_OK" in proc.stdout
    stages = [ln.split()[1] for ln in proc.stdout.splitlines()
              if ln.startswith("STAGE_OK")]
    assert stages == ["install-manifests", "values-pipeline",
                      "lifecycle-hooks", "validate-clusterpolicy",
                      "verify-operator", "restart-operator",
                      "validator-components", "workload-proof",
                      "isolated-plane"]
