"""One-command install lifecycle over the live mock apiserver
(VERDICT r3 #4: the Helm-chart UX — install/upgrade/uninstall — without
Helm; ref deployments/gpu-operator/templates/clusterpolicy.yaml,
upgrade_crd.yaml, cleanup_crd.yaml).

`tpuop-cfg install` must take an EMPTY cluster to all-operands-ready
(once the operator Deployment it installs is "running" — here: a real
Manager against the same apiserver), `upgrade` must land spec changes,
and `uninstall` must tear down CRs before the operator stream.
"""

import os
import time

import pytest
import yaml

from mock_apiserver import MockApiServer
from test_http_e2e import tpu_node, wait_for, cr_state, NS

from tpu_operator.cli import tpuop_cfg
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
)
from tpu_operator.controllers.tpudriver_controller import TPUDriverReconciler
from tpu_operator.controllers.upgrade_controller import UpgradeReconciler
from tpu_operator.runtime.kubeclient import HTTPClient, KubeConfig
from tpu_operator.runtime.manager import Manager


@pytest.fixture()
def cluster(tmp_path, monkeypatch):
    """(server, ops_client) — an EMPTY cluster except for TPU nodes, with
    $KUBECONFIG pointing the CLI at it (the cluster-admin laptop shape)."""
    srv = MockApiServer().start()
    cfg = KubeConfig(server=srv.url, token="admin", namespace=NS)
    ops = HTTPClient(config=cfg)
    for i in range(2):
        ops.create(tpu_node(f"tpu-{i}"))
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(yaml.safe_dump({
        "apiVersion": "v1", "kind": "Config",
        "current-context": "mock",
        "contexts": [{"name": "mock",
                      "context": {"cluster": "mock", "user": "admin",
                                  "namespace": NS}}],
        "clusters": [{"name": "mock", "cluster": {"server": srv.url}}],
        "users": [{"name": "admin", "user": {"token": "admin"}}],
    }))
    monkeypatch.setenv("KUBECONFIG", str(kubeconfig))
    try:
        yield srv, ops
    finally:
        ops._stop.set()
        srv.stop()


def boot_manager(srv):
    c = HTTPClient(config=KubeConfig(server=srv.url, token="op",
                                     namespace=NS))
    m = Manager(c, namespace=NS)
    m.add_reconciler(ClusterPolicyReconciler(c, namespace=NS))
    m.add_reconciler(TPUDriverReconciler(c, namespace=NS))
    m.add_reconciler(UpgradeReconciler(c, namespace=NS))
    m.start()
    return m, c


def test_install_to_all_ready_then_uninstall(cluster, capsys):
    srv, ops = cluster
    # ---- one command: empty cluster -> full stream
    assert tpuop_cfg.main(["install"]) == 0
    out = capsys.readouterr()
    assert "created" in out.out
    # the stream landed in install order: CRDs (with admission active),
    # namespace, RBAC, operator Deployment, and the CR itself
    crds = ops.list("apiextensions.k8s.io/v1", "CustomResourceDefinition")
    assert {c["metadata"]["name"] for c in crds} == {
        "tpuclusterpolicies.tpu.graft.dev", "tpudrivers.tpu.graft.dev",
        "slicerequests.tpu.graft.dev"}
    assert srv.schema_for_collection(
        "/apis/tpu.graft.dev/v1/tpuclusterpolicies") is not None
    assert ops.get_or_none("apps/v1", "Deployment", "tpu-operator",
                           NS) is not None
    assert cr_state(ops) is None  # CR exists, operator not running yet

    # ---- the installed Deployment "starts" (a real Manager here)
    mgr, mgr_client = boot_manager(srv)
    try:
        wait_for(ops, lambda: cr_state(ops) == "ready",
                 "installed CR converges to all-operands-ready")

        # ---- install is idempotent: re-running only configures
        assert tpuop_cfg.main(["install"]) == 0
        out = capsys.readouterr()
        assert "0 created" in out.out

        # ---- upgrade lands a spec change through the same path
        # (values file flips a knob; the stream re-applies)
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                         delete=False) as f:
            yaml.safe_dump({"clusterPolicy": {"spec": {"metricsExporter": {
                "enabled": False}}}}, f)
            vf = f.name
        try:
            assert tpuop_cfg.main(["upgrade", "--values", vf]) == 0
        finally:
            os.unlink(vf)
        wait_for(ops, lambda: ops.get_or_none(
            "apps/v1", "DaemonSet", "libtpu-metrics-exporter", NS) is None,
            "upgraded spec disables the metrics exporter")

        # ---- uninstall: CRs torn down first (owner GC takes the
        # operands), then the operator stream; CRDs kept by default
        assert tpuop_cfg.main(["uninstall"]) == 0
        assert ops.list("tpu.graft.dev/v1", "TPUClusterPolicy") == []
        assert ops.list("apps/v1", "DaemonSet") == []
        assert ops.get_or_none("apps/v1", "Deployment", "tpu-operator",
                               NS) is None
        assert len(ops.list("apiextensions.k8s.io/v1",
                            "CustomResourceDefinition")) == 3
    finally:
        mgr.stop()
        mgr_client._stop.set()


def test_install_wait_blocks_until_ready(cluster):
    """--wait is the `helm install --wait` contract: rc 0 only once every
    TPUClusterPolicy reports ready, within the reference's 5-min budget."""
    import threading

    srv, ops = cluster
    rc_box = {}

    def run_install():
        rc_box["rc"] = tpuop_cfg.main(["install", "--wait",
                                       "--timeout", "120"])

    t = threading.Thread(target=run_install, daemon=True)
    t.start()
    mgr, mgr_client = boot_manager(srv)
    try:
        wait_for(ops, lambda: cr_state(ops) == "ready", "CR ready")
        t.join(timeout=60)
        assert not t.is_alive(), "--wait did not return after ready"
        assert rc_box["rc"] == 0
    finally:
        mgr.stop()
        mgr_client._stop.set()


def test_uninstall_purge_crds(cluster):
    srv, ops = cluster
    assert tpuop_cfg.main(["install"]) == 0
    assert tpuop_cfg.main(["uninstall", "--purge-crds"]) == 0
    assert ops.list("apiextensions.k8s.io/v1",
                    "CustomResourceDefinition") == []


def test_install_rejects_invalid_values(cluster, capsys):
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        yaml.safe_dump({"clusterPolicy": {"spec": {"validator": {
            "driver": {"enabled": False}}}}}, f)
        vf = f.name
    try:
        assert tpuop_cfg.main(["install", "--values", vf]) == 1
        err = capsys.readouterr().err
        assert "core proof 'driver'" in err
    finally:
        os.unlink(vf)
    # nothing was applied
    _, ops = cluster
    assert ops.list("apiextensions.k8s.io/v1",
                    "CustomResourceDefinition") == []


def test_status_verb_tracks_lifecycle(cluster, capsys):
    """`tpuop-cfg status` is the helm-status slot: NOT READY right after
    install (operator not yet reconciling), READY with per-operand,
    per-slice and cluster-facts detail once converged, rc 1 after
    uninstall."""
    from tpu_operator.api import labels as L

    srv, ops = cluster
    # a 2-host v5p slice on top of the fixture's single-host nodes:
    # 2x2x2 = 8 chips at 4 chips/host, one nodepool
    for i in range(2):
        node = tpu_node(f"slice-a-{i}")
        node["metadata"]["labels"].update({
            L.GKE_TPU_TOPOLOGY: "2x2x2",
            L.GKE_NODEPOOL: "pool-slice-a"})
        ops.create(node)
    assert tpuop_cfg.main(["status"]) == 1
    assert "no TPUClusterPolicy" in capsys.readouterr().out
    # the json shape is stable even with no CRs: consumers script
    # against nodes.tpu/upgradeStates in exactly the failure cases
    import json as _json

    assert tpuop_cfg.main(["status", "-o", "json"]) == 1
    empty = _json.loads(capsys.readouterr().out)
    assert empty["ready"] is False and empty["crs"] == []
    assert empty["nodes"] == {"tpu": 0, "upgradeStates": {}}

    assert tpuop_cfg.main(["install"]) == 0
    capsys.readouterr()
    assert tpuop_cfg.main(["status"]) == 1  # CR exists, nothing reconciles
    assert "NOT READY" in capsys.readouterr().out

    mgr, mgr_client = boot_manager(srv)
    try:
        wait_for(ops, lambda: cr_state(ops) == "ready", "ready")
        assert tpuop_cfg.main(["status"]) == 0
        out = capsys.readouterr().out
        assert "TPUClusterPolicy/tpu-cluster-policy: ready" in out
        assert "tpu-device-plugin-daemonset: 4/4 ready" in out
        assert "generations {'v5p': 4}" in out
        # the multi-host slice is one readable row (status.slices[])
        assert ("slice pool-slice-a [tpu-v5p-slice 2x2x2]: "
                "2/2 hosts validated") in out
        assert out.strip().splitlines()[-1] == "READY"
        # -o json: the same picture, machine-readable, same exit code
        import json

        assert tpuop_cfg.main(["status", "-o", "json"]) == 0
        jdoc = json.loads(capsys.readouterr().out)
        assert jdoc["ready"] is True
        assert any(cr["kind"] == "TPUClusterPolicy"
                   and cr["state"] == "ready" for cr in jdoc["crs"])
        [srow] = [s for cr in jdoc["crs"] for s in cr["slices"]]
        assert srow["validated"] is True and srow["hosts"] == 2
        assert any(op["name"] == "tpu-device-plugin-daemonset"
                   and op["ready"] for op in jdoc["operands"])
        assert jdoc["nodes"]["tpu"] == 4
    finally:
        mgr.stop()
        mgr_client._stop.set()
    assert tpuop_cfg.main(["uninstall"]) == 0
    capsys.readouterr()
    assert tpuop_cfg.main(["status"]) == 1


def test_diff_clean_after_install_then_flags_manual_edit(cluster, capsys):
    """The kubectl-diff/helm-diff slot composes with the install verb: a
    fresh install has zero drift; a manual kubectl-edit is flagged with
    rc 1 (ref: config drift the operator would revert)."""
    srv, ops = cluster
    assert tpuop_cfg.main(["install"]) == 0
    capsys.readouterr()
    assert tpuop_cfg.main(["diff"]) == 0, capsys.readouterr().out

    # a cluster-admin hand-edits the operator Deployment
    dep = ops.get("apps/v1", "Deployment", "tpu-operator", NS)
    dep["spec"]["replicas"] = 5
    ops.update(dep)
    assert tpuop_cfg.main(["diff"]) == 1
    out = capsys.readouterr().out
    assert "Deployment" in out


def test_install_wall_time_stays_inside_budget(cluster):
    """BASELINE target #1 measured end to end through the install verb:
    install + operator boot -> all-operands-ready under 5 minutes."""
    srv, ops = cluster
    t0 = time.time()
    assert tpuop_cfg.main(["install"]) == 0
    mgr, mgr_client = boot_manager(srv)
    try:
        wait_for(ops, lambda: cr_state(ops) == "ready", "ready")
        elapsed = time.time() - t0
        assert elapsed < 300.0, f"install->ready {elapsed:.1f}s"
    finally:
        mgr.stop()
        mgr_client._stop.set()
