"""Native libtpu-probe: build + JSON contract + dlopen verification."""

import json
import pathlib
import subprocess

import pytest

NATIVE_DIR = pathlib.Path(__file__).resolve().parents[1] / "native"
PROBE = NATIVE_DIR / "libtpu-probe"


@pytest.fixture(scope="module")
def probe_bin():
    subprocess.run(["make", "-C", str(NATIVE_DIR)], check=True,
                   capture_output=True)
    return str(PROBE)


def run_probe(probe_bin, env=None):
    import os

    full_env = dict(os.environ)
    full_env.update(env or {})
    proc = subprocess.run([probe_bin, "--json"], capture_output=True,
                          text=True, env=full_env)
    return proc.returncode, json.loads(proc.stdout)


class TestProbe:
    def test_json_contract(self, probe_bin):
        code, data = run_probe(probe_bin)
        assert set(data) == {"count", "devices", "source", "libtpu"}
        assert set(data["libtpu"]) == {"found", "path", "dlopen_ok",
                                       "version_symbol"}
        assert isinstance(data["count"], int)

    def test_no_devices_exits_nonzero(self, probe_bin):
        # this host has no /dev/accel* (TPU is tunneled)
        code, data = run_probe(probe_bin)
        if data["count"] == 0:
            assert code == 1

    def test_dlopen_real_shared_object(self, probe_bin, tmp_path):
        src = tmp_path / "fake.c"
        so = tmp_path / "libtpu.so"
        src.write_text("int GetPjrtApi(void){return 0;}\n")
        subprocess.run(["gcc", "-shared", "-fPIC", "-o", str(so), str(src)],
                       check=True)
        code, data = run_probe(probe_bin, env={"LIBTPU_PATH": str(so)})
        assert data["libtpu"]["found"]
        assert data["libtpu"]["dlopen_ok"]
        assert data["libtpu"]["version_symbol"]

    def test_corrupt_libtpu_detected(self, probe_bin, tmp_path):
        so = tmp_path / "libtpu.so"
        so.write_text("garbage")
        code, data = run_probe(probe_bin, env={"LIBTPU_PATH": str(so)})
        assert data["libtpu"]["found"]
        assert not data["libtpu"]["dlopen_ok"]
        assert code == 1  # broken libtpu => driver layer broken
