"""Property tier for the runtime's merge-patch and FakeClient.patch.

Every reconciler write that isn't a full replace rides
``merge_patch`` (runtime/client.py, RFC 7386 semantics) and the fake
apiserver's patch verb. Example tests pin known shapes; these
properties pin the algebra:

- diff/merge inversion: for any two None-free JSON objects a, b, the
  canonical RFC 7386 diff (implemented independently here) applied to
  ``a`` yields exactly ``b`` — a true inverse oracle, not the same
  algorithm run twice;
- idempotence and identity laws;
- FakeClient.patch bookkeeping: resourceVersion bumps only on
  effective change, generation bumps only on spec change, no-op
  patches publish no watch event (rules the hash-skip steady-state
  and the scale tier's write-free property depend on).
"""

import os
import string

from hypothesis import HealthCheck, given, settings, strategies as st

from tpu_operator.runtime import FakeClient
from tpu_operator.runtime.client import merge_patch

FUZZ = settings(
    max_examples=int(os.environ.get("TPU_FUZZ_EXAMPLES", "80")),
    deadline=None, derandomize=True,
    suppress_health_check=[HealthCheck.too_slow])

_KEYS = st.text(string.ascii_lowercase, min_size=1, max_size=5)

# RFC 7386 cannot represent storing a literal null, so model documents
# are None-free; patches MAY contain None (it means delete).
_VALUES = st.recursive(
    st.one_of(st.integers(-100, 100), st.booleans(),
              st.text(string.ascii_letters, max_size=8),
              st.lists(st.integers(0, 9), max_size=3)),
    lambda inner: st.dictionaries(_KEYS, inner, max_size=4),
    max_leaves=10)

_DOCS = st.dictionaries(_KEYS, _VALUES, max_size=5)

_PATCH_VALUES = st.recursive(
    st.one_of(st.none(), st.integers(-100, 100), st.booleans(),
              st.text(string.ascii_letters, max_size=8),
              st.lists(st.integers(0, 9), max_size=3)),
    lambda inner: st.dictionaries(_KEYS, inner, max_size=4),
    max_leaves=10)

_PATCHES = st.dictionaries(_KEYS, _PATCH_VALUES, max_size=5)


def rfc7386_diff(a, b):
    """Independent oracle: the canonical merge-patch turning a into b."""
    if not isinstance(a, dict) or not isinstance(b, dict):
        return b
    patch = {}
    for k in a:
        if k not in b:
            patch[k] = None
        elif a[k] != b[k]:
            patch[k] = rfc7386_diff(a[k], b[k])
    for k in b:
        if k not in a:
            patch[k] = b[k]
    return patch


class TestMergePatchAlgebra:
    @FUZZ
    @given(_DOCS, _DOCS)
    def test_diff_then_merge_is_identity(self, a, b):
        assert merge_patch(a, rfc7386_diff(a, b)) == b

    @FUZZ
    @given(_DOCS, _PATCHES)
    def test_idempotent(self, base, patch):
        once = merge_patch(base, patch)
        assert merge_patch(once, patch) == once

    @FUZZ
    @given(_DOCS)
    def test_empty_patch_is_identity(self, base):
        assert merge_patch(base, {}) == base

    @FUZZ
    @given(_DOCS, _PATCHES)
    def test_no_nulls_survive(self, base, patch):
        """A merged document never contains None anywhere — null is the
        delete marker, not a storable value."""
        def no_none(v):
            if isinstance(v, dict):
                return all(no_none(x) for x in v.values())
            return v is not None

        assert no_none(merge_patch(base, patch))

    @FUZZ
    @given(_DOCS, _PATCHES)
    def test_base_not_mutated(self, base, patch):
        import copy

        snapshot = copy.deepcopy(base)
        merge_patch(base, patch)
        assert base == snapshot


class TestFakeClientPatchBookkeeping:
    def _seed(self, spec):
        c = FakeClient()
        c.create({"apiVersion": "v1", "kind": "ConfigMap",
                  "metadata": {"name": "x", "namespace": "default"},
                  "data": {"k": "v"}, "spec": spec})
        return c

    @FUZZ
    @given(_DOCS, _PATCHES)
    def test_patch_matches_merge_model(self, spec, patch):
        """The stored result equals the RFC 7386 model applied to the
        stored object (metadata bookkeeping aside)."""
        c = self._seed(spec)
        before = c.get("v1", "ConfigMap", "x", "default")
        after = c.patch("v1", "ConfigMap", "x", {"spec": patch}, "default")
        expect = merge_patch(before.get("spec", {}), patch)
        assert after.get("spec", {}) == expect

    @FUZZ
    @given(_DOCS, _PATCHES)
    def test_rv_and_generation_rules(self, spec, patch):
        c = self._seed(spec)
        events = []
        c.watch("v1", "ConfigMap", events.append)
        del events[:]  # drop the initial ADDED replay
        before = c.get("v1", "ConfigMap", "x", "default")
        after = c.patch("v1", "ConfigMap", "x", {"spec": patch}, "default")
        changed = after.get("spec") != before.get("spec")
        rv_bumped = (after["metadata"]["resourceVersion"]
                     != before["metadata"]["resourceVersion"])
        gen_before = before["metadata"].get("generation", 1)
        gen_after = after["metadata"].get("generation", 1)
        if changed:
            assert rv_bumped, "spec changed but resourceVersion did not"
            assert gen_after == gen_before + 1
            assert [e.type for e in events] == ["MODIFIED"]
        else:
            assert not rv_bumped, "no-op patch bumped resourceVersion"
            assert gen_after == gen_before
            assert events == [], "no-op patch published a watch event"


# ---------------------------------------------------------------------------
# fleet-scale plane properties: rendezvous shard routing + lane discipline
# ---------------------------------------------------------------------------

_SHARD_KEYS = st.lists(
    st.text(string.ascii_lowercase + string.digits, min_size=1, max_size=12),
    min_size=1, max_size=60, unique=True)

_LANE_NAMES = st.sampled_from(["health", "placement", "bulk"])

_ADD_SEQS = st.lists(
    st.tuples(st.integers(0, 11), _LANE_NAMES), min_size=1, max_size=80)


class TestShardRoutingProperties:
    @FUZZ
    @given(_SHARD_KEYS, st.integers(2, 8))
    def test_rehash_moves_only_the_dead_shards_keys(self, keys, shards):
        """Rendezvous property: killing any one shard relocates exactly
        that shard's keys; every survivor keeps its assignment. This is
        the bound on failover churn — a modulo hash would reshuffle
        nearly everything."""
        from tpu_operator.runtime import shard_of

        live = list(range(shards))
        before = {k: shard_of(k, live) for k in keys}
        for dead in range(shards):
            survivors = [s for s in live if s != dead]
            for k in keys:
                after = shard_of(k, survivors)
                if before[k] == dead:
                    assert after in survivors
                else:
                    assert after == before[k]

    @FUZZ
    @given(_SHARD_KEYS, st.integers(2, 8))
    def test_every_key_routes_to_exactly_one_live_shard(self, keys, shards):
        from tpu_operator.runtime import shard_of

        live = list(range(shards))
        for k in keys:
            s = shard_of(k, live)
            assert s in live
            assert shard_of(k, live) == s  # deterministic


class TestLaneDisciplineProperties:
    @FUZZ
    @given(_ADD_SEQS)
    def test_drain_serves_each_key_once_in_lane_priority_order(self, seq):
        """For ANY add sequence (duplicate keys, mixed lanes — so
        promotions happen), a full drain yields every distinct key
        exactly once, and service order is monotone in lane rank: with
        no adds racing the drain, a bulk item is never served while a
        health item waits."""
        from tpu_operator.runtime.workqueue import LANES, WorkQueue

        rank = {lane: i for i, lane in enumerate(LANES)}
        q = WorkQueue()
        for key, lane in seq:
            q.add(key, lane=lane)
        served = []
        while True:
            item, _, lane, _ = q.get_with_info(timeout=0)
            if item is None:
                break
            served.append((item, lane))
            q.done(item)
        assert sorted(k for k, _ in served) == sorted({k for k, _ in seq})
        ranks = [rank[lane] for _, lane in served]
        assert ranks == sorted(ranks), (seq, served)

    @FUZZ
    @given(_ADD_SEQS)
    def test_shard_failover_drain_loses_no_key(self, seq):
        """Queued keys spread over K shard queues, one shard killed via
        freeze + drain_pending (the Controller.kill_shard path, minus
        threads): the union of queued keys afterwards equals the union
        before — no key lost, none duplicated."""
        from tpu_operator.runtime import shard_of
        from tpu_operator.runtime.workqueue import WorkQueue

        shards = 3
        live = list(range(shards))
        queues = {s: WorkQueue() for s in live}
        for key, lane in seq:
            queues[shard_of(key, live)].add(key, lane=lane)
        before = {k for k, _ in seq}
        dead = max(live, key=lambda s: len(queues[s]))  # busiest shard
        queues[dead].freeze()
        moved = queues[dead].drain_pending()
        survivors = [s for s in live if s != dead]
        for item, lane, causes in moved:
            queues[shard_of(item, survivors)].add(item, lane=lane,
                                                  cause=causes)
        after = set()
        for s in survivors:
            after |= set(queues[s].snapshot().queued)
        assert after == before
