"""Causal lineage plane: Cause stamping/merging on the workqueue,
the per-object TimelineRecorder, the SLO burn-rate engine, the
/debug/timeline + /debug/slo endpoints, and the `tpuop-cfg why` /
`tpuop-cfg slo` renderers.

The queue-side tests pin the semantics the manager and the chaos runner
both rely on: coalesced re-adds MERGE causes (bounded, earliest-wins,
dedup'd), `add()` reports fresh-vs-coalesced so timeline attribution
records each bought reconcile exactly once, and the satellite fix —
queue-wait attribution on a dirty re-add starts at the FIRST re-add,
not at done().
"""

import json
import time

import pytest

from tpu_operator.runtime.workqueue import (
    LANE_BULK,
    LANE_HEALTH,
    MAX_CAUSES,
    Cause,
    WorkQueue,
)


class TestCauseStamping:
    def test_fresh_add_carries_cause_through_dequeue(self):
        q = WorkQueue()
        c = Cause(reason="watch:ADDED", origin="Node/tpu-0", trace_id=3)
        assert q.add("a", cause=c) is True
        item, _, _, causes = q.get_with_info(timeout=0)
        assert item == "a"
        assert causes == (c,)
        # causes are popped with the item, not leaked for the next run
        q.done("a")
        q.add("a")
        assert q.get_with_info(timeout=0)[3] == ()

    def test_coalesce_merges_and_dedups_causes(self):
        q = WorkQueue()
        c1 = Cause(reason="watch:ADDED", origin="Node/tpu-0")
        c2 = Cause(reason="watch:MODIFIED", origin="Node/tpu-1")
        assert q.add("a", cause=c1) is True
        assert q.add("a", cause=c2) is False      # coalesced, cause kept
        assert q.add("a", cause=c1) is False      # exact dup collapses
        _, _, _, causes = q.get_with_info(timeout=0)
        assert causes == (c1, c2)

    def test_cause_list_is_bounded_earliest_win(self):
        q = WorkQueue()
        first = Cause(reason="r0", origin="o0")
        q.add("a", cause=first)
        for i in range(1, MAX_CAUSES + 5):
            q.add("a", cause=Cause(reason=f"r{i}", origin=f"o{i}"))
        _, _, _, causes = q.get_with_info(timeout=0)
        assert len(causes) == MAX_CAUSES
        # the earliest causes explain the re-run; late storm entries drop
        assert causes[0] == first
        assert causes[-1].reason == f"r{MAX_CAUSES - 1}"

    def test_delayed_add_stamps_cause_at_promotion(self):
        q = WorkQueue()
        c = Cause(reason="retry-backoff", origin="upgrade", trace_id=9)
        q.add_after("a", 0.01, cause=c)
        item, _, _, causes = q.get_with_info(timeout=1.0)
        assert item == "a"
        assert causes == (c,)

    def test_dirty_readd_of_inflight_key_reports_fresh_once(self):
        q = WorkQueue()
        q.add("a")
        assert q.get(timeout=0) == "a"            # in flight
        c = Cause(reason="watch:MODIFIED", origin="Node/tpu-2")
        assert q.add("a", cause=c) is True        # first dirty mark
        assert q.add("a", cause=c) is False       # coalesced behind it
        q.done("a")                               # dirty => re-filed
        _, _, _, causes = q.get_with_info(timeout=0)
        assert causes == (c,)

    def test_drain_pending_transfers_causes(self):
        # the shard-failover path: queued + delayed keys move with their
        # provenance, and re-adding (item, lane, causes) on the target
        # shard round-trips the whole list
        q = WorkQueue()
        c1 = Cause(reason="watch:ADDED", origin="Node/tpu-0")
        c2 = Cause(reason="requeue-after", origin="slicerequest")
        q.add("a", lane=LANE_HEALTH, cause=c1)
        q.add_after("b", 30.0, cause=c2)
        moved = q.drain_pending()
        assert sorted((i, lane, causes) for i, lane, causes in moved) == [
            ("a", LANE_HEALTH, (c1,)), ("b", LANE_BULK, (c2,))]
        assert len(q) == 0
        target = WorkQueue()
        xfer = Cause(reason="failover-transfer", origin="upgrade:shard0")
        for item, lane, causes in moved:
            target.add(item, lane=lane, cause=causes + (xfer,))
        _, _, lane, causes = target.get_with_info(timeout=0)
        assert lane == LANE_HEALTH and causes == (c1, xfer)

    def test_cause_to_dict_omits_empty_fields(self):
        assert Cause(reason="requeue").to_dict() == {"reason": "requeue"}
        assert Cause(reason="watch:ADDED", origin="Node/n", trace_id=4
                     ).to_dict() == {"reason": "watch:ADDED",
                                     "origin": "Node/n", "trace_id": 4}


class TestQueueWaitAttribution:
    """Satellite fix: a re-enqueue of an already-queued / in-flight key
    keeps the EARLIEST enqueue stamp, so the queue-time histogram
    charges the full wait, not just the tail after the last coalesce."""

    def test_coalesced_readd_keeps_earliest_stamp(self):
        q = WorkQueue()
        q.add("a")
        time.sleep(0.05)
        q.add("a")                                # coalesced duplicate
        _, waited, _, _ = q.get_with_info(timeout=0)
        assert waited >= 0.05

    def test_dirty_readd_waits_from_first_readd_not_done(self):
        q = WorkQueue()
        q.add("a")
        assert q.get(timeout=0) == "a"            # in flight
        q.add("a")                                # dirty mark: clock starts
        time.sleep(0.05)
        q.add("a")                                # later coalesce: no reset
        time.sleep(0.02)
        q.done("a")                               # re-filed now
        _, waited, _, _ = q.get_with_info(timeout=0)
        assert waited >= 0.07                     # from FIRST re-add


class TestTimelineRecorder:
    def _recorder(self, **kw):
        from tpu_operator.runtime.timeline import TimelineRecorder

        ticks = iter(range(1, 10_000))
        kw.setdefault("clock", lambda: float(next(ticks)))
        kw.setdefault("enabled", True)
        return TimelineRecorder(**kw)

    def test_record_and_timeline_round_trip(self):
        tl = self._recorder()
        c = Cause(reason="watch:ADDED", origin="Node/tpu-0", trace_id=1)
        tl.record("SliceRequest", "default/r1", "enqueue", causes=(c,))
        tl.record("SliceRequest", "default/r1", "placed",
                  {"pool": "p0", "score": "1.5"})
        events = tl.timeline("SliceRequest", "default/r1")
        assert [e["event"] for e in events] == ["enqueue", "placed"]
        assert events[0]["causes"] == [c.to_dict()]
        assert events[1]["detail"] == {"pool": "p0", "score": "1.5"}
        assert tl.timeline("SliceRequest", "missing") == []

    def test_ring_bounds_history_per_key(self):
        tl = self._recorder(ring=4)
        for i in range(10):
            tl.record("K", "n", f"e{i}")
        events = tl.timeline("K", "n")
        assert [e["event"] for e in events] == ["e6", "e7", "e8", "e9"]

    def test_lru_evicts_coldest_key(self):
        tl = self._recorder(max_keys=2)
        tl.record("K", "a", "e")
        tl.record("K", "b", "e")
        tl.record("K", "a", "e")                  # touch a => b coldest
        tl.record("K", "c", "e")                  # evicts b
        assert tl.keys() == [("K", "a"), ("K", "c")]

    def test_snapshot_is_sorted_and_json_safe(self):
        tl = self._recorder()
        tl.record("Zeta", "z", "e")
        tl.record("Alpha", "a", "e")
        snap = tl.snapshot()
        assert list(snap) == ["Alpha/a", "Zeta/z"]
        json.dumps(snap)                          # must serialize as-is

    def test_disabled_recorder_is_a_no_op(self):
        tl = self._recorder(enabled=False)
        tl.record("K", "n", "e")
        assert tl.keys() == []

    def test_reset_clears_and_swaps_clock(self):
        tl = self._recorder()
        tl.record("K", "n", "e")
        tl.reset(clock=lambda: 42.0)
        assert tl.keys() == []
        tl.record("K", "n", "e")
        assert tl.timeline("K", "n")[0]["ts"] == 42.0


class TestBurnVerdict:
    def test_burn_rate_math(self):
        from tpu_operator.metrics.slo import burn_verdict

        # 5% errors against a 1% budget burns 5x
        v = burn_verdict(95.0, 5.0, objective=0.99, threshold=2.0)
        assert v["error_rate"] == 0.05
        assert v["burn_rate"] == 5.0
        assert v["budget_remaining"] == 0.0
        assert v["breached"] is True
        # same split, laxer objective: under threshold
        v = burn_verdict(95.0, 5.0, objective=0.90, threshold=2.0)
        assert v["burn_rate"] == 0.5
        assert v["breached"] is False

    def test_no_events_is_trivially_met(self):
        from tpu_operator.metrics.slo import burn_verdict

        v = burn_verdict(0.0, 0.0, objective=0.99, threshold=0.0)
        assert v["burn_rate"] == 0.0 and v["breached"] is False


class TestSLOEngine:
    def _engine(self, clock):
        from prometheus_client import CollectorRegistry, Counter

        from tpu_operator.metrics.slo import SLOEngine, SLOSpec

        reg = CollectorRegistry()
        ctr = Counter("tpu_operator_demo", "demo", ["outcome"],
                      registry=reg)
        spec = SLOSpec(
            name="demo-success", description="demo", objective=0.90,
            sli="ratio", counter="tpu_operator_demo_total",
            label="outcome", good=("ok",), bad=("err",),
            windows=(("fast", 60.0, 2.0), ("slow", 600.0, 1.0)))
        return SLOEngine(specs=(spec,), registry=reg, clock=clock), ctr

    def test_windowed_burn_breaches_only_when_all_windows_burn(self):
        now = [0.0]
        engine, ctr = self._engine(lambda: now[0])
        # long healthy history fills the slow window with good events
        for _ in range(20):
            ctr.labels(outcome="ok").inc(10)
            engine.evaluate()
            now[0] += 30.0
        report = engine.evaluate()
        slo = report["slos"][0]
        assert slo["breached"] is False
        # a sudden error cliff: the fast window burns hot; the slow
        # window, diluted by history, decides whether it pages
        ctr.labels(outcome="err").inc(200)
        now[0] += 30.0
        report = engine.evaluate()
        slo = report["slos"][0]
        assert slo["windows"]["fast"]["breached"] is True
        assert slo["breached"] is slo["windows"]["slow"]["breached"]
        assert slo["windows"]["fast"]["burn_rate"] > \
            slo["windows"]["slow"]["burn_rate"]

    def test_query_window_rides_along(self):
        now = [0.0]
        engine, ctr = self._engine(lambda: now[0])
        ctr.labels(outcome="ok").inc(5)
        report = engine.evaluate(extra_window_s=7.5)
        w = report["slos"][0]["windows"]["query"]
        assert w["seconds"] == 7.5 and w["good"] == 5.0

    def test_default_engine_exports_gauges(self):
        from tpu_operator.metrics.registry import render_prometheus
        from tpu_operator.metrics.slo import SLO_ENGINE

        report = SLO_ENGINE.evaluate()
        assert {s["name"] for s in report["slos"]} >= {
            "convergence-latency", "health-lane-queue",
            "migration-success", "placement-latency"}
        text = render_prometheus()
        for series in ("tpu_operator_slo_burn_rate",
                       "tpu_operator_slo_error_budget_remaining",
                       "tpu_operator_slo_breached"):
            assert f'{series}{{slo="convergence-latency"' in text, series
        assert 'window="fast"' in text and 'window="slow"' in text


@pytest.fixture()
def health_port():
    from tpu_operator.runtime import FakeClient
    from tpu_operator.runtime.manager import Manager

    mgr = Manager(FakeClient(), namespace="tpu-operator", health_port=0)
    mgr.start()
    try:
        yield mgr._http.server_address[1]
    finally:
        mgr.stop()


def _get(port, path):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestDebugEndpoints:
    def test_timeline_endpoint_serves_recorded_events(self, health_port):
        from tpu_operator.runtime.timeline import TIMELINE

        prev = TIMELINE.enabled
        TIMELINE.reset(enabled=True)
        try:
            TIMELINE.record("SliceRequest", "default/r1", "placed",
                            {"pool": "p0"})
            status, doc = _get(
                health_port,
                "/debug/timeline?kind=SliceRequest&name=default/r1")
        finally:
            TIMELINE.reset(enabled=prev)
        assert status == 200
        assert doc["count"] == 1
        assert doc["events"][0]["event"] == "placed"

    @pytest.mark.parametrize("query", [
        "",                                   # both missing
        "kind=SliceRequest",                  # name missing
        "name=default/r1",                    # kind missing
        "kind=Slice%20Request&name=r1",       # space in kind
        "kind=K&name=a%0ab",                  # control char in name
    ])
    def test_timeline_endpoint_rejects_bad_params(self, health_port,
                                                  query):
        status, doc = _get(health_port, "/debug/timeline?" + query)
        assert status == 400
        assert "kind and name" in doc["error"]

    def test_slo_endpoint_serves_report(self, health_port):
        status, doc = _get(health_port, "/debug/slo?window=120")
        assert status == 200
        names = {s["name"] for s in doc["slos"]}
        assert "convergence-latency" in names
        assert all("query" in s["windows"] for s in doc["slos"])

    @pytest.mark.parametrize("query", ["window=bogus", "window=0",
                                       "window=-5"])
    def test_slo_endpoint_rejects_bad_window(self, health_port, query):
        status, doc = _get(health_port, "/debug/slo?" + query)
        assert status == 400
        assert "window" in doc["error"]


class TestWhyCLI:
    def _snapshot_file(self, tmp_path):
        snap = {"SliceRequest/default/r1": [
            {"ts": 1.0, "event": "enqueue",
             "causes": [{"reason": "watch:ADDED", "origin": "Node/tpu-0",
                         "trace_id": 3}]},
            {"ts": 2.0, "event": "placed",
             "detail": {"pool": "p0", "score": "1.500000"}},
            {"ts": 3.0, "event": "migration:Resumed",
             "detail": {"restoredStep": 40}},
        ]}
        f = tmp_path / "timeline.json"
        f.write_text(json.dumps(snap))
        return f

    def test_why_renders_causal_story_from_file(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        f = self._snapshot_file(tmp_path)
        rc = main(["why", "SliceRequest/default/r1", "-f", str(f)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "SliceRequest/default/r1 — 3 event(s)" in out
        assert "<- watch:ADDED Node/tpu-0 (trace #3)" in out
        assert "migration:Resumed" in out and "restoredStep=40" in out

    def test_why_json_output(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        f = self._snapshot_file(tmp_path)
        rc = main(["why", "SliceRequest/default/r1", "-f", str(f), "-o",
                   "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0 and doc["count"] == 3

    def test_why_rejects_bare_object(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        rc = main(["why", "just-a-name", "-f", "unused"])
        assert rc == 1
        assert "Kind" in capsys.readouterr().err

    def test_why_empty_timeline_exits_nonzero(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        f = self._snapshot_file(tmp_path)
        rc = main(["why", "SliceRequest/default/ghost", "-f", str(f)])
        assert rc == 1
        assert "no timeline recorded" in capsys.readouterr().out

    def test_why_against_live_endpoint(self, health_port, capsys):
        from tpu_operator.cli.tpuop_cfg import main
        from tpu_operator.runtime.timeline import TIMELINE

        prev = TIMELINE.enabled
        TIMELINE.reset(enabled=True)
        try:
            TIMELINE.record("TPUClusterPolicy", "p1", "reconcile",
                            {"outcome": "ok"})
            rc = main(["why", "TPUClusterPolicy/p1", "--url",
                       f"http://127.0.0.1:{health_port}"])
        finally:
            TIMELINE.reset(enabled=prev)
        assert rc == 0
        assert "reconcile" in capsys.readouterr().out


class TestSloCLI:
    def _report(self, breached):
        return {"evaluated_at": 1.0, "slos": [{
            "name": "migration-success", "description": "d",
            "objective": 0.90, "sli": "ratio", "breached": breached,
            "budget_remaining": 0.0 if breached else 1.0,
            "total": {"good": 2.0, "bad": 6.0 if breached else 0.0,
                      "error_rate": 0.75 if breached else 0.0},
            "windows": {"fast": {
                "burn_rate": 7.5 if breached else 0.0, "threshold": 2.0,
                "seconds": 300.0, "breached": breached}},
        }]}

    def test_slo_healthy_exits_zero(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        f = tmp_path / "slo.json"
        f.write_text(json.dumps(self._report(breached=False)))
        rc = main(["slo", "-f", str(f)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "migration-success" in out and "ok" in out
        assert "breached:" not in out

    def test_slo_breach_exits_two(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        f = tmp_path / "slo.json"
        f.write_text(json.dumps(self._report(breached=True)))
        rc = main(["slo", "-f", str(f)])
        out = capsys.readouterr().out
        assert rc == 2
        assert "BREACHED" in out
        assert "breached: migration-success" in out

    def test_slo_against_live_endpoint(self, health_port, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        rc = main(["slo", "--url", f"http://127.0.0.1:{health_port}",
                   "--window", "60"])
        out = capsys.readouterr().out
        assert rc in (0, 2)                   # registry state is shared
        assert "convergence-latency" in out


class TestMustGatherLineage:
    def test_bundle_carries_timeline_slo_and_cache(self, tmp_path):
        from tpu_operator.cli.must_gather import main
        from tpu_operator.runtime.timeline import TIMELINE

        prev = TIMELINE.enabled
        TIMELINE.reset(enabled=True)
        try:
            TIMELINE.record("TPUClusterPolicy", "tpu-cluster-policy",
                            "reconcile", {"outcome": "ok"})
            out = tmp_path / "mg"
            rc = main(["-o", str(out), "--fake-demo"])
        finally:
            TIMELINE.reset(enabled=prev)
        assert rc == 0
        snap = json.loads((out / "timeline" / "timeline.json").read_text())
        assert "TPUClusterPolicy/tpu-cluster-policy" in snap
        slo = json.loads((out / "slo" / "slo.json").read_text())
        assert {s["name"] for s in slo["slos"]} >= {"migration-success"}
        summary = json.loads((out / "summary.json").read_text())
        assert summary["timeline_objects"] >= 1
        assert summary["slo_rendered"] is True

    def test_bundle_carries_cache_stats_from_cached_client(self, tmp_path):
        # the PR 8 informer-cache picture the bundle used to miss:
        # gather() unwraps the client stack to find cache_stats()
        from tpu_operator.cli.must_gather import gather
        from tpu_operator.runtime import CachedClient, FakeClient

        fake = FakeClient()
        fake.add_node("tpu-0", labels={}, allocatable={})
        cached = CachedClient(fake)
        try:
            cached.list("v1", "Node")             # warm the informer
            out = tmp_path / "mg"
            summary = gather(cached, out)
        finally:
            cached.close()
        assert summary["cache_rendered"] is True
        stats = json.loads((out / "cache" / "cache.json").read_text())
        assert isinstance(stats, dict) and stats
