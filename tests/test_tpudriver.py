"""TPUDriver controller: node-pool partitioning, per-pool DaemonSets,
nodeSelector conflict validation (nvidiadriver_controller.go tier)."""

import pytest

from tpu_operator.api import (
    KIND_TPU_DRIVER,
    V1ALPHA1,
    new_cluster_policy,
    new_tpu_driver,
)
from tpu_operator.api import labels as L
from tpu_operator.api.conditions import COND_ERROR, COND_READY, get_condition
from tpu_operator.controllers.tpudriver_controller import TPUDriverReconciler
from tpu_operator.controllers.validation import (
    ValidationError,
    validate_node_selectors,
)
from tpu_operator.runtime import FakeClient, ListOptions, Request
from tpu_operator.state.nodepool import NodePool, get_node_pools


def v5p_node(c, name, topology="2x2x1", extra=None):
    return c.add_node(name, labels={
        L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
        L.GKE_TPU_TOPOLOGY: topology,
        L.GKE_ACCELERATOR_COUNT: "4", **(extra or {})},
        allocatable={"google.com/tpu": "4"})


def v5e_node(c, name, extra=None):
    return c.add_node(name, labels={
        L.GKE_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
        L.GKE_TPU_TOPOLOGY: "2x4", **(extra or {})},
        allocatable={"google.com/tpu": "8"})


class TestNodePools:
    def test_partition_by_generation_and_topology(self):
        c = FakeClient()
        v5p_node(c, "a")
        v5p_node(c, "b")
        v5e_node(c, "e0")
        pools = get_node_pools(c.list("v1", "Node"))
        assert [(p.name, p.nodes) for p in pools] == [
            ("v5e-2x4", ["e0"]), ("v5p-2x2x1", ["a", "b"])]

    def test_restrict_selector(self):
        c = FakeClient()
        v5p_node(c, "a", extra={"pool": "x"})
        v5e_node(c, "e0")
        pools = get_node_pools(c.list("v1", "Node"), restrict={"pool": "x"})
        assert len(pools) == 1 and pools[0].nodes == ["a"]

    def test_multi_host_detection(self):
        assert not NodePool("tpu-v5p-slice", "2x2x1").multi_host
        assert NodePool("tpu-v5p-slice", "4x4x4").multi_host  # 64 chips
        assert not NodePool("tpu-v5-lite-podslice", "2x4").multi_host  # 8/host
        assert NodePool("tpu-v5-lite-podslice", "4x4").multi_host

    def test_cpu_nodes_ignored(self):
        c = FakeClient()
        c.add_node("cpu-0")
        assert get_node_pools(c.list("v1", "Node")) == []


class TestValidation:
    def test_disjoint_selectors_ok(self):
        c = FakeClient()
        v5p_node(c, "a", extra={"pool": "x"})
        v5e_node(c, "e", extra={"pool": "y"})
        c.create(new_tpu_driver("dx", {"nodeSelector": {"pool": "x"}}))
        cr = c.create(new_tpu_driver("dy", {"nodeSelector": {"pool": "y"}}))
        validate_node_selectors(c, cr)  # no raise

    def test_overlap_rejected(self):
        c = FakeClient()
        v5p_node(c, "a")
        c.create(new_tpu_driver("d1", {"nodeSelector": {}}))
        cr = c.create(new_tpu_driver("d2", {
            "nodeSelector": {L.GKE_TPU_TOPOLOGY: "2x2x1"}}))
        with pytest.raises(ValidationError):
            validate_node_selectors(c, cr)


class TestTPUDriverReconcile:
    def _setup(self):
        c = FakeClient()
        v5p_node(c, "a")
        v5e_node(c, "e0")
        c.create(new_cluster_policy())
        rec = TPUDriverReconciler(client=c, namespace="tpu-operator")
        return c, rec

    def test_per_pool_daemonsets(self):
        c, rec = self._setup()
        c.create(new_tpu_driver("flavors"))
        result = rec.reconcile(Request(name="flavors"))
        names = {d["metadata"]["name"] for d in c.list("apps/v1", "DaemonSet")}
        assert "tpu-libtpu-driver-v5p-2x2x1" in names
        assert "tpu-libtpu-driver-v5e-2x4" in names
        assert result.requeue_after == 5.0  # pods pending
        c.simulate_kubelet(ready=True)
        rec.reconcile(Request(name="flavors"))
        got = c.get(V1ALPHA1, KIND_TPU_DRIVER, "flavors")
        assert got["status"]["state"] == "ready"
        assert get_condition(got, COND_READY)["status"] == "True"

    def test_pool_selector_on_daemonset(self):
        c, rec = self._setup()
        c.create(new_tpu_driver("flavors"))
        rec.reconcile(Request(name="flavors"))
        ds = c.get("apps/v1", "DaemonSet", "tpu-libtpu-driver-v5p-2x2x1",
                   "tpu-operator")
        sel = ds["spec"]["template"]["spec"]["nodeSelector"]
        assert sel[L.GKE_TPU_ACCELERATOR] == "tpu-v5p-slice"
        assert sel[L.GKE_TPU_TOPOLOGY] == "2x2x1"
        assert sel[L.deploy_label("libtpu-driver")] == "true"
        assert ds["spec"]["updateStrategy"]["type"] == "OnDelete"

    def test_stale_pool_cleanup(self):
        c, rec = self._setup()
        c.create(new_tpu_driver("flavors"))
        rec.reconcile(Request(name="flavors"))
        # the v5e pool disappears (nodepool deleted)
        c.delete("v1", "Node", "e0")
        rec.reconcile(Request(name="flavors"))
        names = {d["metadata"]["name"] for d in c.list("apps/v1", "DaemonSet")}
        assert "tpu-libtpu-driver-v5e-2x4" not in names
        assert "tpu-libtpu-driver-v5p-2x2x1" in names

    def test_conflict_sets_error_condition(self):
        c, rec = self._setup()
        c.create(new_tpu_driver("one"))
        c.create(new_tpu_driver("two"))
        rec.reconcile(Request(name="two"))
        got = c.get(V1ALPHA1, KIND_TPU_DRIVER, "two")
        assert get_condition(got, COND_ERROR)["status"] == "True"
        assert "disjoint" in get_condition(got, COND_ERROR)["message"]

    def test_requires_cluster_policy(self):
        c = FakeClient()
        v5p_node(c, "a")
        rec = TPUDriverReconciler(client=c, namespace="tpu-operator")
        c.create(new_tpu_driver("solo"))
        rec.reconcile(Request(name="solo"))
        got = c.get(V1ALPHA1, KIND_TPU_DRIVER, "solo")
        assert get_condition(got, COND_ERROR)["reason"] == "MissingClusterPolicy"

    def test_policy_driver_state_stands_down_in_crd_mode(self):
        from tpu_operator.controllers.clusterpolicy_controller import (
            ClusterPolicyReconciler,
        )
        c, rec = self._setup()
        prec = ClusterPolicyReconciler(client=c, namespace="tpu-operator")
        prec.reconcile(Request(name="tpu-cluster-policy"))
        assert any(d["metadata"]["name"] == "tpu-libtpu-driver-daemonset"
                   for d in c.list("apps/v1", "DaemonSet"))
        # creating a TPUDriver CR flips the policy state to CRD mode
        c.create(new_tpu_driver("flavors"))
        prec.reconcile(Request(name="tpu-cluster-policy"))
        assert not any(d["metadata"]["name"] == "tpu-libtpu-driver-daemonset"
                       for d in c.list("apps/v1", "DaemonSet"))
