"""Device plugin: real gRPC round trips over unix sockets with a fake
kubelet (the kubelet side of the v1beta1 contract)."""

import os
import threading
from concurrent import futures

import grpc
import pytest

from tpu_operator.deviceplugin import api_pb2 as pb
from tpu_operator.deviceplugin.plugin import (
    API_VERSION,
    TPUDevicePlugin,
    device_host_path,
    discover_devices,
)


class FakeKubelet:
    """Serves v1beta1.Registration on kubelet.sock like the real kubelet."""

    def __init__(self, socket_dir):
        self.socket_dir = socket_dir
        self.registrations = []
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))

        def register(request, context):
            self.registrations.append(request)
            return pb.Empty()

        handler = grpc.method_handlers_generic_handler(
            "v1beta1.Registration", {
                "Register": grpc.unary_unary_rpc_method_handler(
                    register,
                    request_deserializer=pb.RegisterRequest.FromString,
                    response_serializer=pb.Empty.SerializeToString),
            })
        self._server.add_generic_rpc_handlers((handler,))
        self._server.add_insecure_port(
            f"unix://{os.path.join(socket_dir, 'kubelet.sock')}")
        self._server.start()

    def stop(self):
        self._server.stop(grace=0.2)


@pytest.fixture
def plugin(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_FAKE_CHIPS", "4")
    p = TPUDevicePlugin(socket_dir=str(tmp_path), health_interval_s=0.1)
    p.start()
    yield p
    p.stop()


def plugin_channel(plugin):
    return grpc.insecure_channel(f"unix://{plugin.socket_path}")


def call(channel, method, req, req_cls, resp_cls):
    rpc = channel.unary_unary(
        f"/v1beta1.DevicePlugin/{method}",
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString)
    return rpc(req, timeout=5)


class TestDiscovery:
    def test_fake_chips(self, monkeypatch):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "4")
        devices = discover_devices()
        assert [d.ID for d in devices] == ["accel0", "accel1", "accel2",
                                          "accel3"]
        assert all(d.health == "Healthy" for d in devices)

    def test_device_host_path(self):
        assert device_host_path("accel2") == "/dev/accel2"
        assert device_host_path("17") == "/dev/vfio/17"


class TestDevicePluginRPC:
    def test_options(self, plugin):
        with plugin_channel(plugin) as ch:
            opts = call(ch, "GetDevicePluginOptions", pb.Empty(), pb.Empty,
                        pb.DevicePluginOptions)
        assert opts.get_preferred_allocation_available

    def test_list_and_watch_streams_inventory(self, plugin):
        with plugin_channel(plugin) as ch:
            rpc = ch.unary_stream(
                "/v1beta1.DevicePlugin/ListAndWatch",
                request_serializer=pb.Empty.SerializeToString,
                response_deserializer=pb.ListAndWatchResponse.FromString)
            stream = rpc(pb.Empty(), timeout=5)
            first = next(stream)
            assert len(first.devices) == 4
            # inventory change pushes an update
            os.environ["TPU_FAKE_CHIPS"] = "2"
            try:
                second = next(stream)
                assert len(second.devices) == 2
            finally:
                os.environ["TPU_FAKE_CHIPS"] = "4"
            stream.cancel()

    def test_allocate_returns_devices_and_env(self, plugin):
        req = pb.AllocateRequest()
        req.container_requests.add(devicesIDs=["accel0", "accel1"])
        with plugin_channel(plugin) as ch:
            resp = call(ch, "Allocate", req, pb.AllocateRequest,
                        pb.AllocateResponse)
        [cresp] = resp.container_responses
        assert [d.host_path for d in cresp.devices] == ["/dev/accel0",
                                                        "/dev/accel1"]
        assert cresp.envs["TPU_VISIBLE_CHIPS"] == "0,1"

    def test_preferred_allocation_contiguous(self, plugin):
        req = pb.PreferredAllocationRequest()
        req.container_requests.add(
            available_deviceIDs=["accel3", "accel1", "accel0", "accel2"],
            allocation_size=2)
        with plugin_channel(plugin) as ch:
            resp = call(ch, "GetPreferredAllocation", req,
                        pb.PreferredAllocationRequest,
                        pb.PreferredAllocationResponse)
        assert list(resp.container_responses[0].deviceIDs) == ["accel0",
                                                               "accel1"]


class TestSharing:
    """Time-shared chips (MPS/time-slicing slot): each unit advertised
    SHARING_REPLICAS times; replicas collapse back to their chip."""

    def test_replicated_inventory(self, monkeypatch):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "3")
        ids = [d.ID for d in discover_devices()]
        assert len(ids) == 6
        assert "accel0::r0" in ids and "accel1::r2" in ids

    def test_allocate_replicas_dedup_to_chip(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path), health_interval_s=0.1)
        p.start()
        try:
            req = pb.AllocateRequest()
            req.container_requests.add(devicesIDs=["accel0::r0", "accel0::r1"])
            with plugin_channel(p) as ch:
                resp = call(ch, "Allocate", req, pb.AllocateRequest,
                            pb.AllocateResponse)
            [cresp] = resp.container_responses
            # two replicas of one chip mount the device once
            assert [d.host_path for d in cresp.devices] == ["/dev/accel0"]
            assert cresp.envs["TPU_VISIBLE_CHIPS"] == "0"
        finally:
            p.stop()

    def test_preferred_allocation_spreads_across_units(self, monkeypatch,
                                                       tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path), health_interval_s=0.1)
        p.start()
        try:
            req = pb.PreferredAllocationRequest()
            req.container_requests.add(
                available_deviceIDs=["accel0::r0", "accel0::r1",
                                     "accel1::r0", "accel1::r1"],
                allocation_size=2)
            with plugin_channel(p) as ch:
                resp = call(ch, "GetPreferredAllocation", req,
                            pb.PreferredAllocationRequest,
                            pb.PreferredAllocationResponse)
            picked = list(resp.container_responses[0].deviceIDs)
            assert picked == ["accel0::r0", "accel1::r0"]
        finally:
            p.stop()

    def test_exclusive_default_unreplicated(self, monkeypatch):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "4")
        monkeypatch.delenv("SHARING_REPLICAS", raising=False)
        assert len(discover_devices()) == 4


class TestKubeletRegistration:
    def test_register_round_trip(self, tmp_path, plugin):
        kubelet = FakeKubelet(str(plugin.socket_dir))
        try:
            plugin.register_with_kubelet()
            [reg] = kubelet.registrations
            assert reg.version == API_VERSION
            assert reg.resource_name == "google.com/tpu"
            assert reg.endpoint == "tpu-device-plugin.sock"
        finally:
            kubelet.stop()
