"""Device plugin: real gRPC round trips over unix sockets with a fake
kubelet (the kubelet side of the v1beta1 contract)."""

import os
import threading
from concurrent import futures

import grpc
import pytest

from tpu_operator.deviceplugin import api_pb2 as pb
from tpu_operator.deviceplugin.plugin import (
    API_VERSION,
    TPUDevicePlugin,
    device_host_path,
    discover_devices,
)


class FakeKubelet:
    """Serves v1beta1.Registration on kubelet.sock like the real kubelet."""

    def __init__(self, socket_dir):
        self.socket_dir = socket_dir
        self.registrations = []
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))

        def register(request, context):
            self.registrations.append(request)
            return pb.Empty()

        handler = grpc.method_handlers_generic_handler(
            "v1beta1.Registration", {
                "Register": grpc.unary_unary_rpc_method_handler(
                    register,
                    request_deserializer=pb.RegisterRequest.FromString,
                    response_serializer=pb.Empty.SerializeToString),
            })
        self._server.add_generic_rpc_handlers((handler,))
        self._server.add_insecure_port(
            f"unix://{os.path.join(socket_dir, 'kubelet.sock')}")
        self._server.start()

    def stop(self):
        self._server.stop(grace=0.2)


@pytest.fixture
def plugin(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_FAKE_CHIPS", "4")
    p = TPUDevicePlugin(socket_dir=str(tmp_path), health_interval_s=0.1)
    p.start()
    yield p
    p.stop()


def plugin_channel(plugin):
    return grpc.insecure_channel(f"unix://{plugin.socket_path}")


def call(channel, method, req, req_cls, resp_cls):
    rpc = channel.unary_unary(
        f"/v1beta1.DevicePlugin/{method}",
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString)
    return rpc(req, timeout=5)


class TestDiscovery:
    def test_fake_chips(self, monkeypatch):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "4")
        devices = discover_devices()
        assert [d.ID for d in devices] == ["accel0", "accel1", "accel2",
                                          "accel3"]
        assert all(d.health == "Healthy" for d in devices)

    def test_device_host_path(self):
        assert device_host_path("accel2") == "/dev/accel2"
        assert device_host_path("17") == "/dev/vfio/17"


class TestDevicePluginRPC:
    def test_options(self, plugin):
        with plugin_channel(plugin) as ch:
            opts = call(ch, "GetDevicePluginOptions", pb.Empty(), pb.Empty,
                        pb.DevicePluginOptions)
        assert opts.get_preferred_allocation_available

    def test_list_and_watch_streams_inventory(self, plugin):
        with plugin_channel(plugin) as ch:
            rpc = ch.unary_stream(
                "/v1beta1.DevicePlugin/ListAndWatch",
                request_serializer=pb.Empty.SerializeToString,
                response_deserializer=pb.ListAndWatchResponse.FromString)
            stream = rpc(pb.Empty(), timeout=5)
            first = next(stream)
            assert len(first.devices) == 4
            # a chip falling off the bus must flip Unhealthy on the
            # stream (allocatable drops), NOT silently leave the list
            os.environ["TPU_FAKE_CHIPS"] = "2"
            try:
                second = next(stream)
                health = {d.ID: d.health for d in second.devices}
                assert len(second.devices) == 4
                assert health["accel0"] == "Healthy"
                assert health["accel1"] == "Healthy"
                assert health["accel2"] == "Unhealthy"
                assert health["accel3"] == "Unhealthy"
            finally:
                os.environ["TPU_FAKE_CHIPS"] = "4"
            # the chips coming back flips them Healthy again
            third = next(stream)
            assert len(third.devices) == 4
            assert all(d.health == "Healthy" for d in third.devices)
            stream.cancel()

    def test_allocate_returns_devices_and_env(self, plugin):
        req = pb.AllocateRequest()
        req.container_requests.add(devicesIDs=["accel0", "accel1"])
        with plugin_channel(plugin) as ch:
            resp = call(ch, "Allocate", req, pb.AllocateRequest,
                        pb.AllocateResponse)
        [cresp] = resp.container_responses
        assert [d.host_path for d in cresp.devices] == ["/dev/accel0",
                                                        "/dev/accel1"]
        assert cresp.envs["TPU_VISIBLE_CHIPS"] == "0,1"

    def test_preferred_allocation_contiguous(self, plugin):
        req = pb.PreferredAllocationRequest()
        req.container_requests.add(
            available_deviceIDs=["accel3", "accel1", "accel0", "accel2"],
            allocation_size=2)
        with plugin_channel(plugin) as ch:
            resp = call(ch, "GetPreferredAllocation", req,
                        pb.PreferredAllocationRequest,
                        pb.PreferredAllocationResponse)
        assert list(resp.container_responses[0].deviceIDs) == ["accel0",
                                                               "accel1"]


class TestSharing:
    """Time-shared chips (MPS/time-slicing slot): each unit advertised
    SHARING_REPLICAS times; replicas collapse back to their chip."""

    def test_replicated_inventory(self, monkeypatch):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "3")
        ids = [d.ID for d in discover_devices()]
        assert len(ids) == 6
        assert "accel0::r0" in ids and "accel1::r2" in ids

    def test_allocate_replicas_dedup_to_chip(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path), health_interval_s=0.1)
        p.start()
        try:
            req = pb.AllocateRequest()
            req.container_requests.add(devicesIDs=["accel0::r0", "accel0::r1"])
            with plugin_channel(p) as ch:
                resp = call(ch, "Allocate", req, pb.AllocateRequest,
                            pb.AllocateResponse)
            [cresp] = resp.container_responses
            # two replicas of one chip mount the device once
            assert [d.host_path for d in cresp.devices] == ["/dev/accel0"]
            assert cresp.envs["TPU_VISIBLE_CHIPS"] == "0"
        finally:
            p.stop()

    def test_preferred_allocation_spreads_across_units(self, monkeypatch,
                                                       tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path), health_interval_s=0.1)
        p.start()
        try:
            req = pb.PreferredAllocationRequest()
            req.container_requests.add(
                available_deviceIDs=["accel0::r0", "accel0::r1",
                                     "accel1::r0", "accel1::r1"],
                allocation_size=2)
            with plugin_channel(p) as ch:
                resp = call(ch, "GetPreferredAllocation", req,
                            pb.PreferredAllocationRequest,
                            pb.PreferredAllocationResponse)
            picked = list(resp.container_responses[0].deviceIDs)
            assert picked == ["accel0::r0", "accel1::r0"]
        finally:
            p.stop()

    def test_exclusive_default_unreplicated(self, monkeypatch):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "4")
        monkeypatch.delenv("SHARING_REPLICAS", raising=False)
        assert len(discover_devices()) == 4


class TestKubeletRegistration:
    def test_register_round_trip(self, tmp_path, plugin):
        kubelet = FakeKubelet(str(plugin.socket_dir))
        try:
            plugin.register_with_kubelet()
            [reg] = kubelet.registrations
            assert reg.version == API_VERSION
            assert reg.resource_name == "google.com/tpu"
            assert reg.endpoint == "tpu-device-plugin.sock"
        finally:
            kubelet.stop()


class TestPluginConfig:
    """Per-node plugin config (devicePlugin.config ConfigMap slot,
    object_controls.go:2442-2552): the plugin selects a named config by
    node label and live-reloads — sharing overrides change the
    advertised inventory without a restart."""

    @pytest.fixture
    def config_dir(self, tmp_path):
        d = tmp_path / "configs"
        d.mkdir()
        (d / "standard").write_text("sharingPolicy: exclusive\n")
        (d / "gold").write_text(
            "sharingPolicy: time-shared\nsharingReplicas: 3\n")
        return str(d)

    def test_parse_time_shared(self):
        from tpu_operator.deviceplugin.plugin import parse_plugin_config

        cfg = parse_plugin_config(
            "g", "sharingPolicy: time-shared\nsharingReplicas: 4\n")
        assert cfg.effective_replicas == 4

    def test_parse_exclusive_pins_one(self):
        from tpu_operator.deviceplugin.plugin import parse_plugin_config

        # replicas only take effect under time-shared (same rule the
        # operator applies to the spec-level knobs)
        cfg = parse_plugin_config(
            "s", "sharingPolicy: exclusive\nsharingReplicas: 4\n")
        assert cfg.effective_replicas == 1

    def test_parse_rejects_unknown_policy(self):
        from tpu_operator.deviceplugin.plugin import parse_plugin_config

        with pytest.raises(ValueError, match="sharingPolicy"):
            parse_plugin_config("b", "sharingPolicy: mps\n")

    def test_label_flip_changes_inventory(self, monkeypatch, tmp_path,
                                          config_dir):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.delenv("SHARING_REPLICAS", raising=False)
        selected = {"name": None}
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            health_interval_s=0.1,
                            config_dir=config_dir,
                            default_config="standard",
                            config_selector=lambda: selected["name"])
        # no label -> default config (exclusive): one device per chip
        p.refresh_devices()
        assert len(p._devices) == 2
        # label the node into the time-shared config: 2 chips x 3 replicas
        selected["name"] = "gold"
        p.refresh_devices()
        ids = [d.ID for d in p._devices]
        assert len(ids) == 6 and "accel1::r2" in ids
        # back to unlabeled -> default again
        selected["name"] = None
        p.refresh_devices()
        assert len(p._devices) == 2

    def test_invalid_config_keeps_last_good(self, monkeypatch, tmp_path,
                                            config_dir):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        selected = {"name": "gold"}
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            config_dir=config_dir,
                            default_config="standard",
                            config_selector=lambda: selected["name"])
        p.refresh_devices()
        assert len(p._devices) == 6
        # selecting a missing config must not brick the running plugin
        selected["name"] = "no-such-config"
        p.refresh_devices()
        assert len(p._devices) == 6
        assert p.plugin_config.name == "gold"

    def test_selector_failure_keeps_current_config(self, monkeypatch,
                                                   tmp_path, config_dir):
        """A transient apiserver read error must not flap the advertised
        inventory: the active config stays, whatever it is. Guessing the
        default while the label is unreadable could shrink kubelet
        capacity and reject pods over a pure read error."""
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.delenv("TPU_PLUGIN_CONFIG_SELECT", raising=False)
        calls = {"fail": False}

        def flaky():
            if calls["fail"]:
                raise RuntimeError("apiserver down")
            return "gold"

        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            config_dir=config_dir,
                            default_config="standard",
                            config_selector=flaky)
        p.refresh_devices()
        assert p.plugin_config.name == "gold" and len(p._devices) == 6
        calls["fail"] = True  # apiserver outage mid-run
        p.refresh_devices()
        assert p.plugin_config.name == "gold" and len(p._devices) == 6
        # startup-time failure: no last-good exists, so no config applies
        # (spec-level sharing settings, exactly as before the feature)
        p2 = TPUDevicePlugin(socket_dir=str(tmp_path),
                             config_dir=config_dir,
                             default_config="gold",
                             config_selector=flaky)
        p2.refresh_devices()
        assert p2.plugin_config is None and len(p2._devices) == 2

    def test_env_select_override(self, monkeypatch, tmp_path, config_dir):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "1")
        monkeypatch.setenv("TPU_PLUGIN_CONFIG_SELECT", "gold")
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            config_dir=config_dir,
                            default_config="standard")
        p.refresh_devices()
        assert len(p._devices) == 3

    def test_live_configmap_update_reloads(self, monkeypatch, tmp_path,
                                           config_dir):
        """kubelet refreshing the mounted ConfigMap is enough: the next
        reload sees the new content with no restart or SIGHUP."""
        import pathlib

        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            config_dir=config_dir,
                            default_config="gold")
        p.refresh_devices()
        assert len(p._devices) == 6
        pathlib.Path(config_dir, "gold").write_text(
            "sharingPolicy: time-shared\nsharingReplicas: 2\n")
        p.refresh_devices()
        assert len(p._devices) == 4

    def test_no_config_dir_is_inert(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.delenv("TPU_PLUGIN_CONFIG_DIR", raising=False)
        p = TPUDevicePlugin(socket_dir=str(tmp_path))
        assert p.reload_plugin_config() is False
        p.refresh_devices()
        assert len(p._devices) == 2 and p.plugin_config is None


class TestEnvContract:
    def test_template_env_names_match_plugin_reads(self, monkeypatch,
                                                   tmp_path):
        """The DaemonSet template sets TPU_PLUGIN_CONFIG_DIR/DEFAULT;
        the plugin constructed with NO args (the container entrypoint
        path) must pick exactly those env names up."""
        import pathlib

        cfgdir = tmp_path / "configs"
        cfgdir.mkdir()
        (cfgdir / "gold").write_text(
            "sharingPolicy: time-shared\nsharingReplicas: 3\n")
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("TPU_PLUGIN_CONFIG_DIR", str(cfgdir))
        monkeypatch.setenv("TPU_PLUGIN_CONFIG_DEFAULT", "gold")
        monkeypatch.delenv("TPU_PLUGIN_CONFIG_SELECT", raising=False)
        p = TPUDevicePlugin(socket_dir=str(tmp_path))
        p.refresh_devices()
        assert len(p._devices) == 6  # 2 chips x 3 replicas from env config
        # and the template really sets those names (cross-check)
        text = (pathlib.Path(__file__).resolve().parents[1] /
                "manifests/state-tpu-device-plugin/0500_daemonset.yaml"
                ).read_text()
        assert "TPU_PLUGIN_CONFIG_DIR" in text
        assert "TPU_PLUGIN_CONFIG_DEFAULT" in text


class TestPerDeviceHealth:
    """VERDICT r4 weak #4: health-engine verdicts must reach kubelet as
    per-device health, and a vanished chip goes Unhealthy first instead
    of silently leaving the list (the NVML/XID health slot behind the
    reference's object_controls.go:1310)."""

    def test_fail_verdict_flips_unhealthy_and_back(self, monkeypatch,
                                                   tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        verdicts = {}
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            health_source=lambda: dict(verdicts))
        p.refresh_devices()
        assert {d.ID: d.health for d in p._devices} == {
            "accel0": "Healthy", "accel1": "Healthy"}
        verdicts["accel1"] = "fail"
        p.refresh_devices()
        assert {d.ID: d.health for d in p._devices} == {
            "accel0": "Healthy", "accel1": "Unhealthy"}
        # recovery (engine verdict clears) flips it back
        verdicts.clear()
        p.refresh_devices()
        assert all(d.health == "Healthy" for d in p._devices)

    def test_warn_verdict_does_not_deschedule(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "1")
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            health_source=lambda: {"accel0": "warn"})
        p.refresh_devices()
        assert p._devices[0].health == "Healthy"

    def test_replicas_of_failed_unit_all_unhealthy(self, monkeypatch,
                                                   tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        monkeypatch.setenv("SHARING_REPLICAS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            health_source=lambda: {"accel0": "fail"})
        p.refresh_devices()
        health = {d.ID: d.health for d in p._devices}
        assert health["accel0::r0"] == "Unhealthy"
        assert health["accel0::r1"] == "Unhealthy"
        assert health["accel1::r0"] == "Healthy"

    def test_vanished_chip_advertised_unhealthy_then_returns(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_FAKE_CHIPS", "3")
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            health_source=lambda: {})
        p.refresh_devices()
        assert len(p._devices) == 3
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")  # accel2 falls off
        p.refresh_devices()
        health = {d.ID: d.health for d in p._devices}
        assert len(health) == 3
        assert health["accel2"] == "Unhealthy"
        monkeypatch.setenv("TPU_FAKE_CHIPS", "3")  # it comes back
        p.refresh_devices()
        assert all(d.health == "Healthy" for d in p._devices)

    def test_fenced_chip_vanishing_is_not_unhealthy(self, monkeypatch,
                                                    tmp_path):
        """A chip moved into the isolated pool legitimately leaves this
        plugin's inventory — it must NOT be ghost-advertised Unhealthy."""
        from tpu_operator.isolation import fencing

        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        fence_file = tmp_path / "fence.json"
        monkeypatch.setenv("TPU_FENCING_FILE", str(fence_file))
        p = TPUDevicePlugin(socket_dir=str(tmp_path),
                            health_source=lambda: {})
        p.refresh_devices()
        assert len(p._devices) == 2
        fencing.write_fencing_file(str(fence_file), ["accel1"], "all")
        p.refresh_devices()
        assert [d.ID for d in p._devices] == ["accel0"]
        assert p._devices[0].health == "Healthy"

    def test_health_engine_http_source(self, monkeypatch, tmp_path):
        """End-to-end against a live health engine: its 503 FAIL payload
        still carries per-chip verdicts the plugin consumes."""
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        doc = {"status": "fail", "reasons": [],
               "chips": [{"chip_id": "accel0", "status": "fail"},
                         {"chip_id": "accel1", "status": "ok"}]}

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                body = _json.dumps(doc).encode()
                self.send_response(503)  # engine answers 503 on FAIL
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            monkeypatch.setenv(
                "TPU_HEALTH_ENGINE_INFO",
                f"127.0.0.1:{srv.server_address[1]}")
            monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
            p = TPUDevicePlugin(socket_dir=str(tmp_path))
            p.refresh_devices()
            health = {d.ID: d.health for d in p._devices}
            assert health == {"accel0": "Unhealthy", "accel1": "Healthy"}
        finally:
            srv.shutdown()

    def test_unreachable_engine_keeps_devices_healthy(self, monkeypatch,
                                                      tmp_path):
        monkeypatch.setenv("TPU_HEALTH_ENGINE_INFO", "127.0.0.1:1")
        monkeypatch.setenv("TPU_FAKE_CHIPS", "2")
        p = TPUDevicePlugin(socket_dir=str(tmp_path))
        p.refresh_devices()
        assert all(d.health == "Healthy" for d in p._devices)
