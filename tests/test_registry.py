"""Image resolvability against a registry (VERDICT r2 item 7).

A stdlib fake OCI registry (v2 distribution API with token auth) drives
the REAL RegistryResolver — no network beyond 127.0.0.1 — and the
`tpuop-cfg validate --verify-images` CLI path end-to-end: a policy whose
tag exists passes, an unresolvable tag fails validation offline
(cmd/gpuop-cfg/validate/clusterpolicy/images.go:172 analog).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import yaml

from tpu_operator.api.registry import (
    ImageResolveError,
    RegistryResolver,
    collect_cr_images,
    parse_image_ref,
    resolve_cr_images,
)
from tpu_operator.cli.tpuop_cfg import main


class _FakeRegistry:
    """OCI distribution v2 endpoints: /v2/, token auth, manifests."""

    def __init__(self, repos, require_auth=False):
        self.repos = repos          # {"repo/name": {"tags"/"digests": [...]}}
        self.require_auth = require_auth
        self.requests = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body=b"{}", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                outer.requests.append(self.path)
                if self.path.startswith("/token"):
                    return self._send(200, json.dumps(
                        {"token": "fake-tok"}).encode())
                if not self.path.startswith("/v2/"):
                    return self._send(404)
                if outer.require_auth and \
                        "Bearer fake-tok" not in (
                            self.headers.get("Authorization") or ""):
                    host = self.headers.get("Host")
                    return self._send(401, b"{}", [(
                        "WWW-Authenticate",
                        f'Bearer realm="http://{host}/token",'
                        f'service="fake"')])
                # /v2/<repo...>/manifests/<ref>
                parts = self.path[len("/v2/"):].split("/manifests/")
                if len(parts) != 2:
                    return self._send(404)
                repo, ref = parts
                entry = outer.repos.get(repo)
                if entry and (ref in entry.get("tags", ())
                              or ref in entry.get("digests", ())):
                    return self._send(200, b'{"schemaVersion": 2}')
                return self._send(404)

            do_HEAD = do_GET

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.host = f"127.0.0.1:{self.server.server_address[1]}"

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def registry():
    reg = _FakeRegistry({
        "tpu-operator/libtpu": {
            "tags": ["v2.0.0"],
            "digests": ["sha256:" + "ab" * 32]},
        "tpu-operator/device-plugin": {"tags": ["stable"]},
    })
    yield reg
    reg.stop()


class TestParseImageRef:
    def test_full_reference(self):
        r = parse_image_ref("gcr.io/proj/img:v1.2.3")
        assert (r.registry, r.repository, r.tag) == \
            ("gcr.io", "proj/img", "v1.2.3")

    def test_port_is_not_a_tag(self):
        r = parse_image_ref("localhost:5000/img")
        assert (r.registry, r.repository, r.tag) == \
            ("localhost:5000", "img", None)
        assert r.reference == "latest"

    def test_digest_reference(self):
        d = "sha256:" + "cd" * 32
        r = parse_image_ref(f"gcr.io/proj/img@{d}")
        assert r.digest == d and r.reference == d

    def test_dockerhub_normalization(self):
        r = parse_image_ref("ubuntu:22.04")
        assert (r.registry, r.repository) == \
            ("registry-1.docker.io", "library/ubuntu")

    def test_malformed_tag_rejected(self):
        with pytest.raises(ImageResolveError):
            parse_image_ref("gcr.io/img:bad tag")
        with pytest.raises(ImageResolveError):
            parse_image_ref("gcr.io/img@sha256:short")


class TestRegistryResolver:
    def test_existing_tag_resolves(self, registry):
        RegistryResolver(plain_http=True).resolve(
            f"{registry.host}/tpu-operator/libtpu:v2.0.0")

    def test_existing_digest_resolves(self, registry):
        RegistryResolver(plain_http=True).resolve(
            f"{registry.host}/tpu-operator/libtpu@sha256:{'ab' * 32}")

    def test_missing_tag_fails(self, registry):
        with pytest.raises(ImageResolveError, match="not found"):
            RegistryResolver(plain_http=True).resolve(
                f"{registry.host}/tpu-operator/libtpu:v9.9.9-nope")

    def test_missing_repository_fails(self, registry):
        with pytest.raises(ImageResolveError, match="not found"):
            RegistryResolver(plain_http=True).resolve(
                f"{registry.host}/no/such-repo:v1")

    def test_unreachable_registry_fails(self):
        with pytest.raises(ImageResolveError, match="unreachable"):
            RegistryResolver(plain_http=True, timeout=1.0).resolve(
                "127.0.0.1:1/img:v1")

    def test_token_auth_dance(self):
        reg = _FakeRegistry(
            {"private/img": {"tags": ["v1"]}}, require_auth=True)
        try:
            RegistryResolver(plain_http=True).resolve(
                f"{reg.host}/private/img:v1")
            assert any(p.startswith("/token") for p in reg.requests)
        finally:
            reg.stop()


class TestCRImageCollection:
    def test_collects_only_explicitly_configured(self):
        cr = {"kind": "TPUClusterPolicy", "spec": {
            "libtpu": {"repository": "r.io/a", "image": "libtpu",
                       "version": "v1"},
            "devicePlugin": {"enabled": True},  # defaults: not collected
            "validator": {"matmulSize": 64},
        }}
        refs = collect_cr_images(cr)
        assert refs == [("/spec/libtpu", "r.io/a/libtpu:v1")]

    def test_resolve_cr_images_reports_per_component(self, registry):
        cr = {"kind": "TPUClusterPolicy", "spec": {
            "libtpu": {"repository": f"{registry.host}/tpu-operator",
                       "image": "libtpu", "version": "v2.0.0"},
            "devicePlugin": {"repository": f"{registry.host}/tpu-operator",
                             "image": "device-plugin",
                             "version": "v-broken"},
        }}
        errs = resolve_cr_images(cr, RegistryResolver(plain_http=True))
        assert len(errs) == 1 and errs[0].startswith("/spec/devicePlugin")


class TestTPUDriverImages:
    def test_tpudriver_cr_image_collected_and_resolved(self, registry):
        cr = {"kind": "TPUDriver", "spec": {
            "repository": f"{registry.host}/tpu-operator",
            "image": "libtpu", "version": "v2.0.0"}}
        refs = collect_cr_images(cr)
        assert refs and refs[0][1].endswith("/tpu-operator/libtpu:v2.0.0")
        assert resolve_cr_images(cr, RegistryResolver(plain_http=True)) == []

    def test_tpudriver_cli_verify_images(self, registry, tmp_path, capsys):
        f = tmp_path / "driver.yaml"
        f.write_text(yaml.safe_dump({
            "apiVersion": "tpu.graft.dev/v1alpha1", "kind": "TPUDriver",
            "metadata": {"name": "d"},
            "spec": {"repository": f"{registry.host}/tpu-operator",
                     "image": "libtpu", "version": "v-missing"}}))
        rc = main(["validate", "tpudriver", "-f", str(f),
                   "--verify-images", "--plain-http"])
        assert rc == 1
        assert "not found" in capsys.readouterr().err


class TestCLIVerifyImages:
    def policy(self, tmp_path, host, version):
        f = tmp_path / "policy.yaml"
        f.write_text(yaml.safe_dump({
            "apiVersion": "tpu.graft.dev/v1",
            "kind": "TPUClusterPolicy",
            "metadata": {"name": "p"},
            "spec": {"libtpu": {"repository": f"{host}/tpu-operator",
                                "image": "libtpu", "version": version}},
        }))
        return str(f)

    def test_resolvable_policy_passes(self, registry, tmp_path, capsys):
        rc = main(["validate", "clusterpolicy",
                   "-f", self.policy(tmp_path, registry.host, "v2.0.0"),
                   "--verify-images", "--plain-http"])
        assert rc == 0
        assert "is valid" in capsys.readouterr().out

    def test_unresolvable_tag_fails_offline(self, registry, tmp_path,
                                            capsys):
        rc = main(["validate", "clusterpolicy",
                   "-f", self.policy(tmp_path, registry.host, "v-typo"),
                   "--verify-images", "--plain-http"])
        assert rc == 1
        err = capsys.readouterr().err
        assert "INVALID /spec/libtpu" in err and "not found" in err

    def test_without_flag_no_network_touched(self, registry, tmp_path):
        rc = main(["validate", "clusterpolicy",
                   "-f", self.policy(tmp_path, registry.host, "v-typo")])
        assert rc == 0  # schema-valid; registry never contacted
        assert registry.requests == []
