"""Lifecycle hook commands (tpu-operator-maintenance) — the chart's
upgrade_crd.yaml / cleanup_crd.yaml hook Jobs re-done as first-class
API-server operations (the image ships no kubectl)."""

import pytest

from tpu_operator.api import (
    KIND_CLUSTER_POLICY,
    KIND_TPU_DRIVER,
    V1,
    new_cluster_policy,
)
from tpu_operator.api.tpudriver import V1ALPHA1
from tpu_operator.cli.maintenance import CRD_API, apply_crds, cleanup
from tpu_operator.runtime import FakeClient
from tpu_operator.runtime.objects import thaw_obj


class TestApplyCRDs:
    def test_creates_all_crds_fresh(self):
        c = FakeClient()
        assert apply_crds(c) == 3
        names = {o["metadata"]["name"]
                 for o in c.list(CRD_API, "CustomResourceDefinition")}
        assert names == {"tpuclusterpolicies.tpu.graft.dev",
                         "tpudrivers.tpu.graft.dev",
                         "slicerequests.tpu.graft.dev"}

    def test_updates_existing_schema_in_place(self):
        """The pre-upgrade scenario: an older CRD revision is live; the
        hook must replace its schema, not fail on AlreadyExists."""
        c = FakeClient()
        apply_crds(c)
        crd = thaw_obj(c.get(CRD_API, "CustomResourceDefinition",
                             "tpuclusterpolicies.tpu.graft.dev"))
        # simulate an old revision: strip the schema down
        crd["spec"]["versions"][0]["schema"] = {
            "openAPIV3Schema": {"type": "object"}}
        c.update(crd)
        assert apply_crds(c) == 3
        crd = c.get(CRD_API, "CustomResourceDefinition",
                    "tpuclusterpolicies.tpu.graft.dev")
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        assert "spec" in schema.get("properties", {}), \
            "pre-upgrade hook did not restore the full schema"

    def test_idempotent(self):
        c = FakeClient()
        apply_crds(c)
        assert apply_crds(c) == 3  # re-run on hook retry: no error


class TestCleanup:
    def _cluster_with_crs(self):
        c = FakeClient()
        apply_crds(c)
        c.create(new_cluster_policy())
        from tpu_operator.api.tpudriver import new_tpu_driver

        c.create(new_tpu_driver("pool-a"))
        return c

    def test_deletes_crs_then_crds(self):
        c = self._cluster_with_crs()
        assert cleanup(c, timeout_s=5.0, poll_s=0.01) is True
        assert c.list(V1, KIND_CLUSTER_POLICY) == []
        assert c.list(V1ALPHA1, KIND_TPU_DRIVER) == []
        assert c.list(CRD_API, "CustomResourceDefinition") == []

    def test_stuck_cr_leaves_crds_in_place(self):
        """A CR that won't go (finalizer still tearing operands down)
        must NOT take the CRDs with it — dropping a CRD with live CRs
        orphans the teardown."""

        class StickyClient(FakeClient):
            def delete(self, api_version, kind, name, namespace=None):
                if kind == KIND_TPU_DRIVER:
                    return None  # deletion blocked by a finalizer
                return super().delete(api_version, kind, name, namespace)

        c = StickyClient()
        apply_crds(c)
        c.create(new_cluster_policy())
        from tpu_operator.api.tpudriver import new_tpu_driver

        c.create(new_tpu_driver("pool-a"))
        assert cleanup(c, timeout_s=0.1, poll_s=0.02) is False
        assert len(c.list(CRD_API, "CustomResourceDefinition")) == 3
        assert len(c.list(V1ALPHA1, KIND_TPU_DRIVER)) == 1

    def test_cleanup_idempotent_on_empty_cluster(self):
        c = FakeClient()
        assert cleanup(c, timeout_s=1.0, poll_s=0.01) is True


class TestHookRendering:
    """The values knobs render the hook Jobs + scoped RBAC
    (operator.upgradeCRD / operator.cleanupCRD slots)."""

    @staticmethod
    def _bundle(overrides):
        from tpu_operator.deploy.values import default_values, deep_merge, render_bundle

        return render_bundle(deep_merge(default_values(), overrides),
                             include_crds=False)

    def test_defaults_render_no_hooks(self):
        docs = self._bundle({})
        assert not any(d["kind"] == "Job" for d in docs)

    def test_upgrade_knob_renders_hook_job_with_rbac(self):
        docs = self._bundle({"operator": {"upgradeCRD": True,
                                          "imagePullSecrets": ["regcred"]}})
        j = next(d for d in docs if d["kind"] == "Job")
        # name is image-versioned: a plain re-apply after a version bump
        # must create a FRESH Job (Jobs are immutable + run-once)
        assert j["metadata"]["name"].startswith("tpu-operator-upgrade-crd-")
        assert j["spec"]["ttlSecondsAfterFinished"] == 3600
        pod = j["spec"]["template"]["spec"]
        assert pod["containers"][0]["command"] == [
            "tpu-operator-maintenance", "apply-crds"]
        assert pod["serviceAccountName"] == "tpu-operator-upgrade-crd"
        assert pod["imagePullSecrets"] == [{"name": "regcred"}]
        assert j["metadata"]["annotations"]["helm.sh/hook"] == "pre-upgrade"
        role = next(d for d in docs if d["kind"] == "ClusterRole"
                    and d["metadata"]["name"] == "tpu-operator-upgrade-crd")
        groups = {g for r in role["rules"] for g in r["apiGroups"]}
        assert "apiextensions.k8s.io" in groups

    def test_cleanup_never_in_install_bundle(self):
        """Plain `kubectl apply` of the install stream ignores the
        helm.sh/hook annotations — a cleanup Job in it would delete the
        freshly installed CRs/CRDs. The knob must NOT pull it in."""
        docs = self._bundle({"operator": {"cleanupCRD": True}})
        assert not any(d["kind"] == "Job" for d in docs)

    def test_cleanup_stream_is_standalone(self):
        from tpu_operator.deploy.values import (
            deep_merge,
            default_values,
            render_cleanup,
        )

        docs = render_cleanup(deep_merge(default_values(), {}))
        j = next(d for d in docs if d["kind"] == "Job")
        assert j["metadata"]["name"] == "tpu-operator-cleanup-crd"
        assert j["metadata"]["annotations"]["helm.sh/hook"] == "pre-delete"
        pod = j["spec"]["template"]["spec"]
        assert pod["containers"][0]["command"] == [
            "tpu-operator-maintenance", "cleanup"]
        role = next(d for d in docs if d["kind"] == "ClusterRole")
        groups = {g for r in role["rules"] for g in r["apiGroups"]}
        assert {"apiextensions.k8s.io", "tpu.graft.dev"} <= groups

    def test_hook_jobs_inherit_operator_scheduling(self):
        """On clusters where every schedulable node is tainted, a hook
        Job without the operator's tolerations/nodeSelector would pend
        forever and hang the release operation."""
        sched = {"nodeSelector": {"pool": "infra"},
                 "tolerations": [{"key": "infra", "operator": "Exists"}],
                 "priorityClassName": "hooks-high"}
        docs = self._bundle({"operator": {"upgradeCRD": True, **sched}})
        pod = next(d for d in docs if d["kind"] == "Job"
                   )["spec"]["template"]["spec"]
        assert pod["nodeSelector"] == {"pool": "infra"}
        assert pod["tolerations"] == sched["tolerations"]
        assert pod["priorityClassName"] == "hooks-high"

    def test_generate_cleanup_cli_target(self, capsys):
        from tpu_operator.cli.tpuop_cfg import main
        import yaml as _yaml

        assert main(["generate", "cleanup"]) == 0
        docs = list(_yaml.safe_load_all(capsys.readouterr().out))
        kinds = [d["kind"] for d in docs if d]
        assert "Job" in kinds and "ClusterRole" in kinds


class TestPluginConfigMapRendering:
    """values pluginConfig.create/data ships the named-configs ConfigMap
    (templates/plugin_config.yaml slot) with render-time validation."""

    @staticmethod
    def _values(overrides):
        from tpu_operator.deploy.values import default_values, deep_merge

        return deep_merge(default_values(), overrides)

    def test_renders_configmap_with_validated_entries(self):
        from tpu_operator.deploy.values import render_bundle

        docs = render_bundle(self._values({
            "clusterPolicy": {"spec": {"devicePlugin": {
                "configMap": "plugin-configs",
                "defaultConfig": "standard"}}},
            "pluginConfig": {"create": True, "data": {
                "standard": "sharingPolicy: exclusive\n",
                "shared-4x": ("sharingPolicy: time-shared\n"
                              "sharingReplicas: 4\n")}},
        }), include_crds=False)
        cm = next(d for d in docs if d["kind"] == "ConfigMap"
                  and d["metadata"]["name"] == "plugin-configs")
        assert set(cm["data"]) == {"standard", "shared-4x"}

    def test_invalid_entry_fails_render(self):
        from tpu_operator.deploy.values import render_bundle

        with pytest.raises(ValueError, match="sharingPolicy"):
            render_bundle(self._values({
                "clusterPolicy": {"spec": {"devicePlugin": {
                    "configMap": "plugin-configs"}}},
                "pluginConfig": {"create": True, "data": {
                    "bad": "sharingPolicy: mps\n"}},
            }), include_crds=False)

    def test_create_without_name_fails_render(self):
        from tpu_operator.deploy.values import render_bundle

        with pytest.raises(ValueError, match="configMap"):
            render_bundle(self._values({
                "pluginConfig": {"create": True,
                                 "data": {"a": "sharingPolicy: exclusive"}},
            }), include_crds=False)

    def test_create_false_ships_nothing(self):
        from tpu_operator.deploy.values import render_bundle

        docs = render_bundle(self._values({}), include_crds=False)
        assert not any(d["kind"] == "ConfigMap" for d in docs)


    def test_upgrade_job_name_changes_with_image_version(self):
        from tpu_operator.deploy.values import render_bundle

        def job_name(version):
            docs = render_bundle(self._values(
                {"operator": {"upgradeCRD": True, "version": version}}),
                include_crds=False)
            return next(d for d in docs
                        if d["kind"] == "Job")["metadata"]["name"]

        assert job_name("v1.0.0") != job_name("v1.1.0")

    def test_replicas_null_is_treated_as_unset(self):
        """YAML `sharingReplicas: null` means unset, not a crash — the
        TypeError int(None) used to raise escaped both the render-time
        catch and the CLI's error handler as a raw traceback."""
        from tpu_operator.deviceplugin.plugin import parse_plugin_config

        cfg = parse_plugin_config(
            "x", "sharingPolicy: time-shared\nsharingReplicas: null\n")
        assert cfg.sharing_replicas == 1

    def test_bad_replicas_fails_render_with_key_context(self):
        from tpu_operator.deploy.values import render_bundle

        with pytest.raises(ValueError, match="pluginConfig.data.x"):
            render_bundle(self._values({
                "clusterPolicy": {"spec": {"devicePlugin": {
                    "configMap": "c"}}},
                "pluginConfig": {"create": True, "data": {
                    "x": "sharingPolicy: time-shared\n"
                         "sharingReplicas: four\n"
                }},
            }), include_crds=False)

    def test_default_config_must_name_shipped_entry(self):
        from tpu_operator.deploy.values import render_bundle

        with pytest.raises(ValueError, match="standrd"):
            render_bundle(self._values({
                "clusterPolicy": {"spec": {"devicePlugin": {
                    "configMap": "c", "defaultConfig": "standrd"}}},
                "pluginConfig": {"create": True, "data": {
                    "standard": "sharingPolicy: exclusive\n"}},
            }), include_crds=False)
