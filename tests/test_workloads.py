"""JAX workloads on the virtual 8-device CPU mesh: mesh shaping, matmul,
allreduce, sharded burn-in training step."""

import jax
import jax.numpy as jnp
import pytest

from tpu_operator.parallel.mesh import (
    build_mesh,
    factor_axes,
    parse_topology,
    ring_mesh,
)
from tpu_operator.workloads import collectives, matmul
from tpu_operator.workloads.burnin import (
    BurninConfig,
    forward,
    init_params,
    make_batch,
    make_train_step,
    run as burnin_run,
)
from tpu_operator.workloads.hardware import chip_spec_for


class TestMesh:
    def test_parse_topology(self):
        assert parse_topology("2x2x1") == (2, 2, 1)
        assert parse_topology("16x16") == (16, 16)
        assert parse_topology("") == (1,)

    def test_factor_axes(self):
        assert factor_axes(8) == (4, 2)
        assert factor_axes(8, model_parallel=4) == (2, 4)
        assert factor_axes(1) == (1, 1)
        with pytest.raises(ValueError):
            factor_axes(8, model_parallel=3)

    def test_build_mesh_axes(self):
        mesh = build_mesh()
        assert mesh.axis_names == ("data", "model")
        assert mesh.devices.size == 8

    def test_ring_mesh(self):
        assert ring_mesh().devices.shape == (8,)


class TestHardware:
    def test_chip_spec_mapping(self):
        assert chip_spec_for("TPU v5 lite").generation == "v5e"
        assert chip_spec_for("TPU v5p chip").generation == "v5p"
        assert chip_spec_for("TPU v4").generation == "v4"
        assert chip_spec_for("cpu") is None


class TestMatmul:
    def test_small_matmul_runs(self):
        res = matmul.run(size=64, iters=4, calls=2, repeats=1)
        assert res.checksum_ok
        assert res.tflops > 0
        assert res.utilization is None  # cpu has no ChipSpec


class TestCollectives:
    def test_allreduce_correct_on_mesh(self):
        res = collectives.run(size_mb=1.0, iters=2, repeats=1)
        assert res.devices == 8
        assert res.correct
        assert res.bus_bw_gbps > 0

    @pytest.mark.parametrize("op", sorted(collectives._BUS_FACTOR))
    def test_collective_suite_each_op_oracle_checked(self, op):
        """Every primitive of the fabric suite (the NCCL-tests slot)
        must move real data correctly over the 8-device ring."""
        res = collectives.run_collective(op, size_mb=0.5, iters=2,
                                         repeats=1)
        assert res.op == op and res.devices == 8
        assert res.correct, f"{op} diverged from its numpy oracle"
        assert res.bus_bw_gbps > 0

    def test_bus_accounting_factors(self):
        """Ring bus-bandwidth factors match the standard accounting,
        normalized by per-device INPUT size: all_gather receives n-1
        full shards (NCCL's (n-1)/n is relative to the total gathered
        size, i.e. the same traffic)."""
        f = collectives._BUS_FACTOR
        n = 8
        assert f["all_reduce"](n) == pytest.approx(2 * 7 / 8)
        assert f["all_gather"](n) == pytest.approx(7.0)
        assert f["reduce_scatter"](n) == f["all_to_all"](n) \
            == pytest.approx(7 / 8)
        assert f["ppermute"](n) == 1.0

    def test_run_suite_returns_all_ops(self):
        suite = collectives.run_suite(size_mb=0.25, iters=1, repeats=1)
        assert set(suite) == set(collectives._BUS_FACTOR)
        assert all(r.correct for r in suite.values())


class TestPallasProbe:
    def test_triad_correct_in_interpret_mode(self):
        from tpu_operator.workloads.pallas_probe import run, triad
        import jax.numpy as jnp

        out = triad(jnp.ones((128, 256), jnp.float32),
                    jnp.full((128, 256), 2.0, jnp.float32),
                    alpha=0.5, interpret=True)
        assert bool(jnp.allclose(out, 2.0))
        res = run(size_mb=2.0, iters=3, repeats=1, interpret=True)
        assert res.correct
        assert res.bandwidth_gbps > 0

    def test_triad_rejects_misaligned_shapes(self):
        from tpu_operator.workloads.pallas_probe import triad
        import jax.numpy as jnp

        with pytest.raises(AssertionError):
            triad(jnp.ones((128, 100), jnp.float32),
                  jnp.ones((128, 100), jnp.float32), interpret=True)


class TestBurnin:
    CFG = BurninConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                       d_ff=64, seq_len=16, batch=8)

    def test_forward_shape(self):
        params = init_params(self.CFG, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, self.CFG.seq_len), dtype=jnp.int32)
        logits = forward(params, tokens, self.CFG)
        assert logits.shape == (2, self.CFG.seq_len, self.CFG.vocab)

    def test_loss_falls_on_sharded_mesh(self):
        first, last = burnin_run(self.CFG, steps=8)
        assert last < first

    def test_gradients_flow_through_all_shards(self):
        mesh = build_mesh()  # 4x2
        step, init_state, _ = make_train_step(mesh, self.CFG)
        state = init_state(jax.random.PRNGKey(0))
        batch = make_batch(self.CFG, mesh, jax.random.PRNGKey(1))
        new_state, loss = step(state, batch)
        assert bool(jnp.isfinite(loss))
        # every parameter moved (grads were nonzero through tp shards)
        before = init_state(jax.random.PRNGKey(0))["params"]
        moved = jax.tree.map(
            lambda a, b: bool(jnp.any(a != b)), before,
            new_state["params"])
        assert all(jax.tree.leaves(moved))

    def test_explicit_model_parallel_dim(self):
        first, last = burnin_run(self.CFG, steps=3, model_parallel=4)
        assert last < first

    def test_fsdp_matches_tensor_parallel_oracle(self):
        """ZeRO-3/FSDP layout: parameters + optimizer moments fully
        sharded across the data axis (2D with tp). The fully-sharded
        step must produce the same loss stream as the replicated-params
        step — XLA's inserted all-gathers/reduce-scatters are pure
        layout, not math."""
        mesh = build_mesh()  # 4x2: data=4, model=2
        losses = {}
        for fsdp in (False, True):
            step, init_state, _ = make_train_step(mesh, self.CFG,
                                                  fsdp=fsdp)
            state = init_state(jax.random.PRNGKey(0))
            if fsdp:
                # parameters really are sharded over BOTH axes
                qkv = state["params"]["layers"][0]["qkv"]
                assert qkv.sharding.spec == jax.sharding.PartitionSpec(
                    "data", "model")
            ls = []
            for i in range(3):
                batch = make_batch(self.CFG, mesh,
                                   jax.random.PRNGKey(100 + i))
                state, loss = step(state, batch)
                ls.append(float(loss))
            losses[fsdp] = ls
        assert losses[True] == pytest.approx(losses[False], rel=2e-4)


class TestConvBurnin:
    """Conv model family (workloads/convburn.py): the conv half of the
    burn-in pair, channel-parallel over the model axis."""

    CFG = None  # built lazily so the import cost rides the jax tier

    @classmethod
    def cfg(cls):
        from tpu_operator.workloads.convburn import ConvBurninConfig

        if cls.CFG is None:
            cls.CFG = ConvBurninConfig(image_size=16, width=16,
                                       n_blocks=2, n_classes=8, batch=8)
        return cls.CFG

    def test_forward_shape_single_device(self):
        from tpu_operator.workloads import convburn

        cfg = self.cfg()
        params = convburn.init_params(cfg, jax.random.PRNGKey(0))
        images = jnp.zeros((2, cfg.image_size, cfg.image_size,
                            cfg.in_channels))
        logits = convburn.forward(params, images, cfg)
        assert logits.shape == (2, cfg.n_classes)

    def test_loss_falls_on_sharded_mesh(self):
        from tpu_operator.workloads.convburn import run as conv_run

        first, last = conv_run(self.cfg(), steps=8)
        assert last < first

    def test_channel_parallel_matches_replicated_oracle(self):
        """Channel-sharded convs are layout, not math: the sharded
        forward must match a fully-replicated single-device forward."""
        from tpu_operator.workloads import convburn

        cfg = self.cfg()
        mesh = build_mesh()  # 4x2 [data, model]
        params = convburn.init_params(cfg, jax.random.PRNGKey(0))
        images = jax.random.normal(
            jax.random.PRNGKey(1),
            (4, cfg.image_size, cfg.image_size, cfg.in_channels))
        expect = convburn.forward(params, images, cfg)
        sharded = convburn.shard_params(params, mesh, cfg)
        with mesh:
            got = jax.jit(
                lambda p, x: convburn.forward(p, x, cfg, mesh))(sharded,
                                                                images)
        assert jnp.allclose(expect, got, rtol=2e-2, atol=2e-2)

    def test_gradients_flow_through_all_shards(self):
        from tpu_operator.workloads import convburn

        cfg = self.cfg()
        mesh = build_mesh()
        step, init_state = convburn.make_train_step(mesh, cfg)
        state = init_state(jax.random.PRNGKey(0))
        batch = convburn.make_batch(cfg, mesh, jax.random.PRNGKey(1))
        new_state, loss = step(state, batch)
        assert bool(jnp.isfinite(loss))
        before = init_state(jax.random.PRNGKey(0))["params"]
        moved = jax.tree.map(lambda a, b: bool(jnp.any(a != b)),
                             before, new_state["params"])
        assert all(jax.tree.leaves(moved))
