"""Federation plane: cell digests, the circuit-breaking global router,
cross-cell elastic migration, and the router reconciler.

The chaos matrix (tests/test_chaos.py) pins the plane's end-to-end
behavior under seeded partitions; this file pins the units those
scenarios are built from — digest schema discipline, breaker
transitions and backoff arithmetic, the arrival-order-independence
property, snapshot round-trips, and the migration handshake's causal
record.
"""

import itertools
import json
import random

import pytest

from tpu_operator.api import labels as L
from tpu_operator.api.slicerequest import (
    KIND_SLICE_REQUEST,
    MIG_CHECKPOINTED,
    MIG_RESUMED,
    PHASE_PLACED,
    V1ALPHA1,
    new_slice_request,
)
from tpu_operator.benchmarks.controlplane import build_cluster
from tpu_operator.controllers.federation_controller import (
    FederationReconciler,
)
from tpu_operator.controllers.placement_controller import (
    PlacementReconciler,
)
from tpu_operator.federation.digest import (
    CELL_DIGEST_SCHEMA_VERSION,
    cell_digest,
    cell_digest_json,
    parse_cell_digest,
    publish_wait,
)
from tpu_operator.federation.router import (
    CELL_HEALTHY,
    CELL_OPEN,
    CELL_SUSPECT,
    GlobalRouter,
    cells_report,
)
from tpu_operator.runtime import Request
from tpu_operator.runtime.fake import FakeClient, simulate_kubelet
from tpu_operator.runtime.multicell import Cell, MultiCellHarness
from tpu_operator.runtime.objects import annotations_of, get_nested
from tpu_operator.runtime.timeline import TIMELINE
from tpu_operator.topology.index import FleetIndex
from tpu_operator.workloads.elastic import ElasticWorkload


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def mk_digest(cell, seq, at=0.0, chips_free=64, hosts=16,
              fragmentation=0.0, condemned=0, headroom=None):
    return {
        "v": CELL_DIGEST_SCHEMA_VERSION,
        "cell": cell,
        "seq": seq,
        "at": at,
        "hosts": hosts,
        "chips_free": chips_free,
        "chips_placed": hosts * 4 - chips_free,
        "utilization": 1.0 - chips_free / (hosts * 4.0),
        "headroom": headroom if headroom is not None
        else {"v5p": chips_free},
        "fragmentation": fragmentation,
        "condemned": condemned,
    }


class TestCellDigest:
    def test_digest_from_real_index_round_trips(self):
        nodes = build_cluster(n_tpu=12).list("v1", "Node")
        d = cell_digest(FleetIndex(nodes), "cell-a", 3, 42.0)
        assert d["v"] == CELL_DIGEST_SCHEMA_VERSION
        assert d["cell"] == "cell-a" and d["seq"] == 3
        assert d["chips_free"] > 0 and d["hosts"] > 0
        # the wire form parses back to the same dict
        assert parse_cell_digest(cell_digest_json(d)) == d

    def test_unknown_schema_version_parses_to_none(self):
        d = mk_digest("a", 1)
        d["v"] = CELL_DIGEST_SCHEMA_VERSION + 1
        assert parse_cell_digest(d) is None
        assert parse_cell_digest(json.dumps(d)) is None

    def test_malformed_payloads_parse_to_none(self):
        assert parse_cell_digest(None) is None
        assert parse_cell_digest("{not json") is None
        assert parse_cell_digest("[1,2]") is None
        no_cell = mk_digest("a", 1)
        no_cell.pop("cell")
        assert parse_cell_digest(no_cell) is None
        bad_seq = mk_digest("a", 1)
        bad_seq["seq"] = "three"
        assert parse_cell_digest(bad_seq) is None

    def test_publish_wait_is_seeded_and_bounded(self):
        assert publish_wait("cell-a") == publish_wait("cell-a")
        waits = {publish_wait(f"cell-{i}") for i in range(8)}
        assert len(waits) > 1  # cells don't publish in lockstep
        for w in waits:
            assert 15.0 * 0.8 <= w <= 15.0 * 1.2


class TestRouterBreaker:
    def test_streak_walks_healthy_suspect_open_and_heals(self):
        clock = Clock()
        r = GlobalRouter(["a"], now=clock, failure_threshold=3)
        assert r.cells["a"].state == CELL_HEALTHY
        r.record_failure("a")
        assert r.cells["a"].state == CELL_SUSPECT
        r.record_failure("a")
        assert r.cells["a"].state == CELL_SUSPECT
        r.record_failure("a")
        assert r.cells["a"].state == CELL_OPEN
        # one success is a full heal — streak, probes, Open clock gone
        r.record_success("a")
        cs = r.cells["a"]
        assert (cs.state, cs.failure_streak, cs.probes,
                cs.open_since) == (CELL_HEALTHY, 0, 0, None)

    def test_open_cell_probed_on_capped_exponential_backoff(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a", "b"], now=clock, failure_threshold=1,
                         probe_base_s=10.0, probe_cap_s=35.0)
        r.record_failure("a")  # straight to Open at threshold 1
        assert r.cells["a"].state == CELL_OPEN
        # not due before the base backoff; a healthy cell is always due
        assert r.cells_to_contact() == ["b"]
        clock.t = 10.0
        assert r.cells_to_contact() == ["a", "b"]
        r.record_failure("a")  # failed probe: backoff doubles
        assert "a" not in r.cells_to_contact()
        clock.t = 29.9
        assert "a" not in r.cells_to_contact()
        clock.t = 30.0
        assert "a" in r.cells_to_contact()
        r.record_failure("a")  # 40s would be next, capped at 35
        clock.t = 64.9
        assert "a" not in r.cells_to_contact()
        clock.t = 65.0
        assert "a" in r.cells_to_contact()

    def test_condemnation_waits_for_the_horizon(self):
        clock = Clock(t=100.0)
        r = GlobalRouter(["a"], now=clock, failure_threshold=1,
                         condemnation_horizon_s=60.0)
        r.record_failure("a")
        assert r.condemned_cells() == []  # Open, but not dead yet
        clock.t = 159.9
        assert r.condemned_cells() == []
        clock.t = 160.0
        assert r.condemned_cells() == ["a"]
        r.record_success("a")  # partition healed before anyone moved
        assert r.condemned_cells() == []


class TestRouterScoring:
    def test_open_and_digestless_cells_never_score(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a", "b", "c"], now=clock,
                         failure_threshold=1)
        r.observe_digest(mk_digest("a", 1))
        r.observe_digest(mk_digest("b", 1))
        r.record_failure("b")
        assert r.score("a", chips=4) > 0.0
        assert r.score("b", chips=4) == 0.0  # Open
        assert r.score("c", chips=4) == 0.0  # never heard from
        assert r.route(4)["cell"] == "a"

    def test_stale_digest_is_age_discounted(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a"], now=clock, digest_half_life_s=60.0)
        r.observe_digest(mk_digest("a", 1, at=0.0))
        fresh = r.score("a", chips=4)
        clock.t = 60.0  # one half-life
        assert r.score("a", chips=4) == pytest.approx(fresh / 2)
        clock.t = 120.0  # two half-lives -> a third
        assert r.score("a", chips=4) == pytest.approx(fresh / 3)

    def test_suspect_cell_scores_at_a_discount_not_zero(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a"], now=clock, failure_threshold=3)
        r.observe_digest(mk_digest("a", 1))
        healthy = r.score("a", chips=4)
        r.record_failure("a")
        assert r.cells["a"].state == CELL_SUSPECT
        assert r.score("a", chips=4) == pytest.approx(healthy / 2)

    def test_generation_headroom_gates_pinned_requests(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a", "b"], now=clock)
        r.observe_digest(mk_digest("a", 1, chips_free=64,
                                   headroom={"v5e": 64}))
        r.observe_digest(mk_digest("b", 1, chips_free=16,
                                   headroom={"v5p": 16}))
        # un-pinned: the bigger free pool wins
        assert r.route(4)["cell"] == "a"
        # v5p-pinned: only b has v5p headroom
        assert r.route(4, generation="v5p")["cell"] == "b"
        # pinned past the headroom: unroutable, stays queued
        assert r.route(32, generation="v5p") is None

    def test_routing_books_capacity_until_the_next_publish(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a"], now=clock)
        r.observe_digest(mk_digest("a", 1, chips_free=8,
                                   headroom={"v5p": 8}))
        assert r.route(8)["cell"] == "a"
        # the held digest says 8 free but the router just spent them
        assert r.route(8) is None
        # a fresh publish supersedes the booking ledger
        r.observe_digest(mk_digest("a", 2, chips_free=8,
                                   headroom={"v5p": 8}))
        assert r.route(8)["cell"] == "a"

    def test_locality_steers_between_comparable_cells_only(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a", "b"], now=clock)
        r.observe_digest(mk_digest("a", 1, chips_free=64))
        r.observe_digest(mk_digest("b", 1, chips_free=48))
        d = r.route(4, locality="b")
        assert (d["cell"], d["reason"]) == ("b", "locality")
        # a collapsed cell loses the preference: 4 free is far below
        # half of a's score, so the digest winner takes it
        r2 = GlobalRouter(["a", "b"], now=clock)
        r2.observe_digest(mk_digest("a", 1, chips_free=64))
        r2.observe_digest(mk_digest("b", 1, chips_free=4))
        d2 = r2.route(4, locality="b")
        assert (d2["cell"], d2["reason"]) == ("a", "digest-score")


class TestArrivalOrderIndependence:
    def test_seeded_permutations_reach_identical_decisions(self):
        """The split-brain property as a unit test: routers fed the
        same digest SET in different orders (seeded shuffles, plus a
        duplicate echo of every digest) make byte-identical decisions
        for the same request stream."""
        rng = random.Random(1513)
        cells = [f"cell-{i}" for i in range(4)]
        digests = [mk_digest(c, seq,
                             at=float(seq),
                             chips_free=rng.randrange(8, 96, 4),
                             fragmentation=rng.random() / 2,
                             headroom={"v5p": rng.randrange(4, 64, 4)})
                   for c in cells for seq in (1, 2, 3)]
        stream = [(rng.choice((4, 8, 16)),
                   rng.choice((None, "v5p")),
                   rng.choice((None, rng.choice(cells))))
                  for _ in range(30)]

        def decisions(order_seed):
            clock = Clock(t=10.0)
            r = GlobalRouter(cells, now=clock)
            batch = digests + digests  # echoes must dedupe by seq
            random.Random(order_seed).shuffle(batch)
            for d in batch:
                r.observe_digest(dict(d))
            return [r.route(chips, generation=gen, locality=loc)
                    for chips, gen, loc in stream]

        baseline = decisions(0)
        assert any(d is not None for d in baseline)
        for order_seed in range(1, 6):
            assert decisions(order_seed) == baseline

    def test_stale_echo_never_regresses_the_held_view(self):
        clock = Clock(t=0.0)
        r = GlobalRouter(["a"], now=clock)
        assert r.observe_digest(mk_digest("a", 5, chips_free=32))
        assert not r.observe_digest(mk_digest("a", 4, chips_free=99))
        assert not r.observe_digest(mk_digest("a", 5, chips_free=99))
        assert r.cells["a"].digest["chips_free"] == 32


class TestRouterSnapshot:
    def test_breaker_ledger_survives_the_json_round_trip(self):
        clock = Clock(t=50.0)
        r = GlobalRouter(["a", "b"], now=clock, failure_threshold=2)
        r.observe_digest(mk_digest("a", 7, at=40.0))
        r.record_failure("b")
        r.record_failure("b")  # Open
        r.record_failure("b")  # one failed probe
        r.route(4)
        snap = json.loads(json.dumps(r.snapshot(), sort_keys=True))
        clock2 = Clock(t=50.0)
        r2 = GlobalRouter.restore(snap, ["a", "b"], now=clock2,
                                  failure_threshold=2)
        b = r2.cells["b"]
        assert (b.state, b.probes) == (CELL_OPEN, 1)
        assert b.open_since == 50.0
        a = r2.cells["a"]
        assert a.digest["seq"] == 7 and a.booked == 4
        # the successor keeps routing around the Open cell
        assert r2.route(4)["cell"] == "a"

    def test_adopt_refuses_foreign_or_malformed_state(self):
        r = GlobalRouter(["a"], now=Clock())
        assert not r.adopt(None)
        assert not r.adopt({"cells": {}})  # no version stamp
        assert not r.adopt({"v": 999, "cells": {}})
        assert not r.adopt({"v": 1, "cells": "nope"})
        assert r.adopt({"v": 1, "cells": {}})


class _Ctx:
    """TIMELINE needs the virtual clock for the migration tests; keep
    the process-global recorder's state out of other tests."""

    def __init__(self, clock):
        self.clock = clock

    def __enter__(self):
        self._prev = (TIMELINE.clock, TIMELINE.enabled)
        TIMELINE.reset(clock=self.clock, enabled=True)
        return self

    def __exit__(self, *exc):
        TIMELINE.reset(clock=self._prev[0], enabled=self._prev[1])


class TestMultiCellMigration:
    def _harness(self, clock):
        fakes = {name: build_cluster(n_tpu=8)
                 for name in ("cell-a", "cell-b")}
        cells = {}
        for name, fake in fakes.items():
            recon = PlacementReconciler(fake, namespace="default",
                                        preemption=False, now=clock,
                                        cell=name)
            cells[name] = Cell(name, fake, reconciler=recon)
        router = GlobalRouter(
            ["cell-a", "cell-b"], now=clock, failure_threshold=1,
            condemnation_horizon_s=30.0)
        harness = MultiCellHarness(
            router, cells, now=clock,
            shim_factory=lambda cell, name, ns, store: ElasticWorkload(
                fakes[cell.name], name, ns, clock=clock, store=store))
        return fakes, cells, router, harness

    def _settle(self, fakes, cells, harness, shims_for=()):
        for _ in range(6):
            for name in sorted(cells):
                fake = fakes[name]
                for cr in fake.list(V1ALPHA1, KIND_SLICE_REQUEST):
                    cells[name].reconciler.reconcile(Request(
                        name=cr["metadata"]["name"],
                        namespace="default"))
                simulate_kubelet(fake, ready=True)
                for key in shims_for:
                    ns, _, nm = key.partition("/")
                    cr = fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST,
                                          nm, ns)
                    owned = {k for c in cells.values()
                             for k in c.shims}
                    if (cr is not None and key not in owned
                            and get_nested(cr, "status",
                                           "phase") == PHASE_PLACED):
                        cells[name].shims[key] = ElasticWorkload(
                            fake, nm, ns, clock=harness.now)
                for key in sorted(cells[name].shims):
                    cells[name].shims[key].tick()
            harness.migration_pass()

    def test_condemned_cell_slices_hop_with_their_checkpoints(self):
        clock = Clock(t=0.0)
        with _Ctx(clock):
            fakes, cells, router, harness = self._harness(clock)
            router.observe_digest(cell_digest(
                cells["cell-a"].fleet_index(), "cell-a", 1, clock()))
            router.observe_digest(cell_digest(
                cells["cell-b"].fleet_index(), "cell-b", 1, clock()))
            harness.submit(new_slice_request("job", {"chips": 4}))
            assert harness.route_pass() == 1
            key = "default/job"
            src = next(n for n in fakes
                       if fakes[n].list(V1ALPHA1, KIND_SLICE_REQUEST))
            dst = "cell-b" if src == "cell-a" else "cell-a"
            self._settle(fakes, cells, harness, shims_for=(key,))
            assert get_nested(
                fakes[src].get_or_none(V1ALPHA1, KIND_SLICE_REQUEST,
                                       "job", "default"),
                "status", "phase") == PHASE_PLACED
            # let the workload bank some acked-able progress
            for _ in range(4):
                clock.t += 10.0
                for k in sorted(cells[src].shims):
                    cells[src].shims[k].tick()
            # partition: the source cell drops off the global plane
            router.record_failure(src)
            assert router.cells[src].state == CELL_OPEN
            clock.t += 31.0  # past the condemnation horizon
            assert router.condemned_cells() == [src]
            self._settle(fakes, cells, harness, shims_for=())
            # the slice now lives in the destination, resumed
            twin = fakes[dst].get_or_none(V1ALPHA1, KIND_SLICE_REQUEST,
                                          "job", "default")
            assert get_nested(twin, "status", "phase") == PHASE_PLACED
            mig = get_nested(twin, "status", "migration", default={})
            assert mig["phase"] == MIG_RESUMED
            assert mig["from"] == f"cell/{src}"
            assert int(mig["restoredStep"]) >= int(mig["ackedStep"])
            # the source copy is gone, the shim (and its checkpoint
            # store) crossed with the slice
            assert fakes[src].get_or_none(
                V1ALPHA1, KIND_SLICE_REQUEST, "job", "default") is None
            assert key in cells[dst].shims
            assert key not in cells[src].shims
            assert harness.migrations == {}
            # the causal record tells the cross-cluster story
            events = TIMELINE.timeline("SliceRequest", key)
            hop = next(e for e in events
                       if e["event"] == "migration:CrossCellHop")
            assert any(c["origin"] == f"cell/{src}"
                       for c in hop["causes"])

    def test_recover_migrations_rebuilds_from_request_status(self):
        clock = Clock(t=0.0)
        with _Ctx(clock):
            fakes, cells, router, harness = self._harness(clock)
            # a dst-side twin mid-hop: Checkpointed, from cell-a
            body = new_slice_request("moving", {"chips": 4})
            body["metadata"]["annotations"] = {L.CELL_PIN: "cell-b"}
            fakes["cell-b"].create(body)
            live = fakes["cell-b"].get_or_none(
                V1ALPHA1, KIND_SLICE_REQUEST, "moving", "default")
            cr = json.loads(json.dumps(live))
            cr.setdefault("status", {})["migration"] = {
                "phase": MIG_CHECKPOINTED, "from": "cell/cell-a",
                "ackedStep": 12}
            fakes["cell-b"].update_status(cr)
            assert harness.migrations == {}
            assert harness.recover_migrations() == 1
            assert harness.migrations["default/moving"] == {
                "src": "cell-a", "dst": "cell-b", "stage": "hop"}


class TestFederationReconciler:
    def test_unpinned_request_gets_routed_and_stamped(self):
        clock = Clock(t=0.0)
        with _Ctx(clock):
            fake = FakeClient()
            router = GlobalRouter(["east", "west"], now=clock)
            router.observe_digest(mk_digest("east", 1, chips_free=64))
            router.observe_digest(mk_digest("west", 1, chips_free=8))
            fake.create(new_slice_request("train", {"chips": 16}))
            rec = FederationReconciler(fake, router)
            res = rec.reconcile(Request(name="train",
                                        namespace="default"))
            assert not res.requeue and res.requeue_after == 0.0
            live = fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST,
                                    "train", "default")
            assert annotations_of(live)[L.CELL_PIN] == "east"
            events = TIMELINE.timeline("SliceRequest", "default/train")
            routed = next(e for e in events if e["event"] == "routed")
            assert any(c["origin"] == "cell/east"
                       for c in routed["causes"])

    def test_pinned_request_is_left_alone(self):
        fake = FakeClient()
        body = new_slice_request("pinned", {"chips": 4})
        body["metadata"]["annotations"] = {L.CELL_PIN: "west"}
        fake.create(body)
        router = GlobalRouter(["east", "west"], now=Clock())
        router.observe_digest(mk_digest("east", 1))
        rec = FederationReconciler(fake, router)
        rec.reconcile(Request(name="pinned", namespace="default"))
        live = fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST,
                                "pinned", "default")
        assert annotations_of(live)[L.CELL_PIN] == "west"
        assert router.cells["east"].routed_total == 0

    def test_unroutable_request_requeues_on_the_retry_cadence(self):
        from tpu_operator.controllers.federation_controller import (
            ROUTE_RETRY_S,
        )

        fake = FakeClient()
        fake.create(new_slice_request("stuck", {"chips": 4}))
        router = GlobalRouter(["east"], now=Clock(),
                              failure_threshold=1)
        router.record_failure("east")  # every cell Open
        rec = FederationReconciler(fake, router)
        res = rec.reconcile(Request(name="stuck", namespace="default"))
        assert res.requeue_after == ROUTE_RETRY_S
        live = fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST,
                                "stuck", "default")
        assert L.CELL_PIN not in annotations_of(live)

    def test_cells_report_groups_by_pin(self):
        fake = FakeClient()
        for name, pin in (("a1", "east"), ("a2", "east"),
                          ("b1", "west"), ("q1", None)):
            body = new_slice_request(name, {"chips": 4})
            if pin:
                body["metadata"]["annotations"] = {L.CELL_PIN: pin}
            fake.create(body)
        router = GlobalRouter(["east", "west"], now=Clock())
        rep = cells_report(fake, "default", router=router)
        assert sorted(rep["cells"]) == ["east", "west"]
        assert rep["cells"]["east"]["chips"] == 8
        assert [r["name"] for r in rep["unrouted"]] == ["q1"]
        assert rep["router"]["cells"]["east"]["state"] == CELL_HEALTHY


class TestCrossCellWorkChecker:
    def _client_with(self, name, status, anns=None):
        fake = FakeClient()
        body = new_slice_request(name, {"chips": 4})
        if anns:
            body["metadata"]["annotations"] = dict(anns)
        fake.create(body)
        live = fake.get_or_none(V1ALPHA1, KIND_SLICE_REQUEST, name,
                                "default")
        cr = json.loads(json.dumps(live))
        cr["status"] = status
        fake.update_status(cr)
        return fake

    def test_restore_below_acked_high_water_is_a_violation(self):
        from tpu_operator.chaos.invariants import CrossCellWorkChecker

        checker = CrossCellWorkChecker()
        a = self._client_with("job", {"phase": "Placed", "migration": {
            "phase": MIG_CHECKPOINTED, "ackedStep": 40,
            "toCell": "b"}})
        checker.observe(0, {"a": a})
        b = self._client_with("job", {"phase": "Placed", "migration": {
            "phase": MIG_RESUMED, "from": "cell/a",
            "restoredStep": 30}})
        checker.observe(1, {"b": b})
        assert [v.invariant for v in checker.violations] == [
            "no-lost-work-cross-cell"]
        # the same stale marker is judged once, not every observation
        checker.observe(2, {"b": b})
        assert len(checker.violations) == 1

    def test_double_placement_flagged_but_handoff_window_exempt(self):
        from tpu_operator.chaos.invariants import CrossCellWorkChecker

        checker = CrossCellWorkChecker()
        # outbound handoff: src copy carries toCell -> by design
        src = self._client_with("job", {"phase": "Placed", "migration": {
            "phase": MIG_CHECKPOINTED, "toCell": "b"}})
        dst = self._client_with("job", {"phase": "Placed"})
        checker.observe(0, {"a": src, "b": dst})
        assert checker.violations == []
        # two full bindings with no handoff in flight: a double-spend
        rogue = self._client_with("job", {"phase": "Placed"})
        checker.observe(1, {"a": rogue, "b": dst})
        assert [v.invariant for v in checker.violations] == [
            "single-binding"]


class TestCellsEndpoint:
    def _manager(self, controllers=()):
        from types import SimpleNamespace

        from tpu_operator.runtime.manager import Manager

        mgr = Manager(FakeClient(), namespace="tpu-operator",
                      health_port=0)
        for rec in controllers:
            mgr.controllers.append(SimpleNamespace(
                reconciler=rec, start=lambda: None, stop=lambda: None))
        mgr.start()
        return mgr

    def _get(self, port, path):
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as resp:
            return resp.status, json.loads(resp.read())

    def test_serves_the_federation_report(self):
        fake = FakeClient()
        body = new_slice_request("a1", {"chips": 8})
        body["metadata"]["annotations"] = {L.CELL_PIN: "east"}
        fake.create(body)
        router = GlobalRouter(["east"], now=Clock())
        mgr = self._manager([FederationReconciler(fake, router)])
        try:
            status, doc = self._get(
                mgr._http.server_address[1], "/debug/cells")
        finally:
            mgr.stop()
        assert status == 200
        assert doc["cells"]["east"]["chips"] == 8
        assert doc["router"]["cells"]["east"]["state"] == CELL_HEALTHY

    def test_no_federation_plane_is_explicit_not_404(self):
        mgr = self._manager()
        try:
            status, doc = self._get(
                mgr._http.server_address[1], "/debug/cells")
        finally:
            mgr.stop()
        assert status == 200
        assert doc == {"cells": {}, "unrouted": [], "router": None}
