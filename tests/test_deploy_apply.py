"""Unit tests for deploy/apply.py — the Helm-verb engine underneath
tpuop-cfg install/upgrade/uninstall (the e2e lifecycle lives in
test_install_e2e.py; these pin the edge semantics)."""

import pytest

from tpu_operator.deploy import apply as apply_mod
from tpu_operator.runtime.client import NotFoundError
from tpu_operator.runtime.fake import FakeClient
from tpu_operator.runtime.objects import thaw_obj


def doc(kind, name, api="v1", ns=None, **spec):
    d = {"apiVersion": api, "kind": kind,
         "metadata": {"name": name}, "spec": spec or {}}
    if ns:
        d["metadata"]["namespace"] = ns
    return d


class TestApplyDocs:
    def test_create_then_configure(self):
        c = FakeClient()
        stream = [doc("ConfigMap", "a", ns="x")]
        out = apply_mod.apply_docs(c, stream)
        assert out == [("created", "ConfigMap", "a")]
        stream[0]["spec"] = {"k": "v2"}
        out = apply_mod.apply_docs(c, stream)
        assert out == [("configured", "ConfigMap", "a")]
        assert c.get("v1", "ConfigMap", "a", "x")["spec"] == {"k": "v2"}

    def test_configure_carries_live_resource_version(self):
        c = FakeClient()
        apply_mod.apply_docs(c, [doc("ConfigMap", "a", ns="x")])
        live = c.get("v1", "ConfigMap", "a", "x")
        rv = live["metadata"]["resourceVersion"]
        apply_mod.apply_docs(c, [doc("ConfigMap", "a", ns="x", k="v2")])
        live2 = c.get("v1", "ConfigMap", "a", "x")
        assert live2["metadata"]["resourceVersion"] != rv

    def test_cr_create_retries_when_its_crd_ships_in_stream(self,
                                                            monkeypatch):
        """A CR POSTed right after its CRD 404s on a real apiserver until
        discovery catches up; apply rides it out — but ONLY for groups
        whose CRD is part of the same stream."""
        calls = []

        class Flaky:
            def get_or_none(self, *a, **kw):
                return None

            def create(self, d):
                if d.get("kind") == "CustomResourceDefinition":
                    return d
                calls.append(1)
                if len(calls) < 3:
                    raise NotFoundError("no matches for kind")
                return d

        monkeypatch.setattr(apply_mod.time, "sleep", lambda s: None)
        crd = {"apiVersion": "apiextensions.k8s.io/v1",
               "kind": "CustomResourceDefinition",
               "metadata": {"name": "tpudrivers.tpu.graft.dev"},
               "spec": {"group": "tpu.graft.dev",
                        "names": {"plural": "tpudrivers"}}}
        out = apply_mod.apply_docs(
            Flaky(),
            [crd, doc("TPUDriver", "d", api="tpu.graft.dev/v1alpha1")])
        assert ("created", "TPUDriver", "d") in out
        assert len(calls) == 3

    def test_404_without_stream_crd_is_immediate(self, monkeypatch):
        """Built-in kinds AND dotted groups whose CRD is absent from the
        stream (rbac.authorization.k8s.io, missing third-party CRDs)
        fail immediately — no establishment window applies to them."""
        calls = []

        class Flaky:
            def get_or_none(self, *a, **kw):
                return None

            def create(self, d):
                calls.append(1)
                raise NotFoundError("nope")

        monkeypatch.setattr(
            apply_mod.time, "sleep",
            lambda s: pytest.fail("must not sleep without a stream CRD"))
        for d in (doc("ConfigMap", "a"),
                  doc("ServiceMonitor", "m",
                      api="monitoring.coreos.com/v1")):
            calls.clear()
            with pytest.raises(NotFoundError):
                apply_mod.apply_docs(Flaky(), [d])
            assert len(calls) == 1

    def test_apply_does_not_mutate_caller_docs(self):
        """The rendered stream may be reused (reinstall after delete); a
        resourceVersion stamped into the caller's doc would poison the
        later create."""
        c = FakeClient()
        stream = [doc("ConfigMap", "a", ns="x")]
        apply_mod.apply_docs(c, stream)
        stream[0]["spec"] = {"k": "v2"}
        apply_mod.apply_docs(c, stream)  # configure path
        assert "resourceVersion" not in stream[0]["metadata"]


class TestDeleteDocs:
    def test_reverse_order_and_keep_kinds(self):
        c = FakeClient()
        stream = [doc("Namespace", "ns1"),
                  doc("ConfigMap", "a", ns="ns1"),
                  doc("Service", "s", ns="ns1")]
        apply_mod.apply_docs(c, stream)
        deleted = apply_mod.delete_docs(c, stream,
                                        keep_kinds=("Namespace",))
        assert deleted == 2
        assert c.get_or_none("v1", "Namespace", "ns1") is not None
        assert c.get_or_none("v1", "ConfigMap", "a", "ns1") is None

    def test_already_gone_is_fine(self):
        c = FakeClient()
        assert apply_mod.delete_docs(c, [doc("ConfigMap", "a")]) == 0


def wait_policy_ready_short(c):
    return apply_mod.wait_policy_ready(c, timeout_s=0.3, poll_s=0.05)


class TestWaitPolicyReady:
    def test_ready_cr_returns_true(self):
        from tpu_operator.api.clusterpolicy import new_cluster_policy

        c = FakeClient()
        cr = new_cluster_policy()
        cr["status"] = {"state": "ready"}
        c.create(cr)
        assert apply_mod.wait_policy_ready(c, timeout_s=2.0,
                                           poll_s=0.05) is True

    def test_pending_tpudriver_blocks_wait(self):
        """A ready policy with TPUDriver CRs still rolling must NOT count
        as installed: the drivers stood the policy's libtpu state down,
        so only their own status proves rollout."""
        from tpu_operator.api.clusterpolicy import new_cluster_policy
        from tpu_operator.api.tpudriver import new_tpu_driver

        c = FakeClient()
        cr = new_cluster_policy()
        cr["status"] = {"state": "ready"}
        c.create(cr)
        c.create(new_tpu_driver("pool-a"))  # no status yet
        assert wait_policy_ready_short(c) is False
        live = thaw_obj(c.get("tpu.graft.dev/v1alpha1", "TPUDriver", "pool-a"))
        live["status"] = {"state": "ready"}
        c.update(live)
        assert apply_mod.wait_policy_ready(c, timeout_s=2.0,
                                           poll_s=0.05) is True

    def test_never_ready_times_out_false(self):
        from tpu_operator.api.clusterpolicy import new_cluster_policy

        c = FakeClient()
        c.create(new_cluster_policy())
        assert apply_mod.wait_policy_ready(c, timeout_s=0.3,
                                           poll_s=0.05) is False

    def test_no_cr_times_out_false(self):
        assert apply_mod.wait_policy_ready(FakeClient(), timeout_s=0.2,
                                           poll_s=0.05) is False
