"""End-to-end lifecycle suite on the fake cluster — the analog of the
reference's shell e2e case list (tests/scripts/end-to-end.sh: install ->
verify-operator -> operand-restart check -> workload -> policy mutations ->
operator restart -> disable/enable -> uninstall), which the reference runs
on a real AWS GPU node and we run against the simulated cluster tier."""

import time

import pytest
from conftest import load_factor

from tpu_operator.api import KIND_CLUSTER_POLICY, V1, new_cluster_policy
from tpu_operator.api import labels as L
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
)
from tpu_operator.controllers.upgrade_controller import UpgradeReconciler
from tpu_operator.runtime import FakeClient, ListOptions, Manager, Request
from tpu_operator.runtime.objects import thaw_obj


def build_cluster(n_tpu=2):
    c = FakeClient()
    for i in range(n_tpu):
        c.add_node(f"tpu-{i}", labels={
            L.GKE_TPU_ACCELERATOR: "tpu-v5p-slice",
            L.GKE_TPU_TOPOLOGY: "2x2x1",
            L.GKE_ACCELERATOR_COUNT: "4"},
            allocatable={"google.com/tpu": "4"})
    return c


def wait_ready(c, mgr, timeout=45):
    """Deadlines here exist to fail a genuinely stuck operator, not to be
    tight: a healthy run converges in seconds, and an xdist worker on
    this 1-CPU box can be starved for minutes by concurrent JAX
    compiles, so the base is generous and still scales by load_factor.
    On failure the message carries the cluster state that would
    otherwise need a rerun to capture."""
    deadline = time.monotonic() + timeout * load_factor()
    cr = None
    while time.monotonic() < deadline:
        c.simulate_kubelet(ready=True)
        cr = c.get_or_none(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
        if cr and (cr.get("status") or {}).get("state") == "ready":
            return cr
        time.sleep(0.05)
    ds = {d["metadata"]["name"]:
          (d.get("status") or {}).get("numberReady")
          for d in c.list("apps/v1", "DaemonSet")}
    raise AssertionError(
        f"policy never reached ready; status={(cr or {}).get('status')} "
        f"daemonsets={ds} load_factor={load_factor():.1f}")


def wait_for(c, pred, desc, timeout=30, kinds=(("apps/v1", "DaemonSet"),)):
    """Watch-driven wait (VERDICT r4 #5, replacing the fixed 10s polls):
    re-check ``pred`` whenever a relevant cluster event fires instead of
    busy-polling, with the deadline scaled to CI contention. The 0.25s
    fallback tick guards against a predicate whose trigger isn't one of
    ``kinds``."""
    import threading

    fired = threading.Event()
    cancels = [c.hub.subscribe(av, kind, lambda evt: fired.set())
               for av, kind in kinds]
    try:
        deadline = time.monotonic() + timeout * load_factor()
        while True:
            if pred():
                return
            if time.monotonic() > deadline:
                raise AssertionError(desc)
            fired.wait(timeout=0.25)
            fired.clear()
    finally:
        for cancel in cancels:
            cancel()


def make_manager(c):
    mgr = Manager(c, namespace="tpu-operator")
    mgr.add_reconciler(ClusterPolicyReconciler(client=c,
                                               namespace="tpu-operator"))
    # the driver DS rolls OnDelete, so spec changes only propagate through
    # the upgrade controller's cordon/drain/restart FSM — run it too
    mgr.add_reconciler(UpgradeReconciler(client=c, namespace="tpu-operator"))
    mgr.start()
    return mgr


@pytest.fixture
def cluster():
    c = build_cluster()
    mgr = make_manager(c)
    yield c, mgr
    mgr.stop()


class TestEndToEnd:
    def test_full_lifecycle(self, cluster):
        c, mgr = cluster

        # -- install + verify-operator ---------------------------------
        c.create(new_cluster_policy(spec={
            "upgradePolicy": {"autoUpgrade": True,
                              "maxParallelUpgrades": 2}}))
        wait_ready(c, mgr)
        ds_names = {d["metadata"]["name"]
                    for d in c.list("apps/v1", "DaemonSet")}
        assert len(ds_names) >= 7

        # -- verify-operand-restarts: steady state must not churn -------
        rvs = {d["metadata"]["name"]: d["metadata"]["resourceVersion"]
               for d in c.list("apps/v1", "DaemonSet")}
        # drain the work queues instead of napping a fixed 0.5s: idle
        # means every queued reconcile (and its near-term requeues)
        # actually ran, so the no-churn assertion below checks real
        # cycles, not luck. horizon=1 skips the 120s periodic resync
        # the steady-state upgrade controller always keeps parked.
        assert mgr.wait_idle(timeout=30 * load_factor(), horizon=1.0)
        c.simulate_kubelet(ready=True)
        assert mgr.wait_idle(timeout=30 * load_factor(), horizon=1.0)
        rvs2 = {d["metadata"]["name"]: d["metadata"]["resourceVersion"]
                for d in c.list("apps/v1", "DaemonSet")}
        assert rvs == rvs2, "DaemonSets churned with no spec change"

        # -- update-clusterpolicy mutation ------------------------------
        cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
        cr["spec"]["libtpu"] = {"installDir": "/opt/mutated"}
        c.update(cr)

        def mutation_landed():
            ds = c.get("apps/v1", "DaemonSet", "tpu-libtpu-driver-daemonset",
                       "tpu-operator")
            mounts = ds["spec"]["template"]["spec"]["containers"][0][
                "volumeMounts"]
            return any(m["mountPath"] == "/opt/mutated" for m in mounts)

        wait_for(c, mutation_landed,
                 "spec mutation never reached the DaemonSet")
        # OnDelete: ready returns only after the upgrade FSM rolls every
        # node (cordon -> drain -> pod restart -> validate -> uncordon) —
        # the slowest wait in the test, so it gets the largest budget
        wait_ready(c, mgr, timeout=90)
        # CR readiness tracks operands; the final uncordon pass of the
        # upgrade FSM lands on the next controller cycle — wait for it
        # (the kubelet must keep ticking here: pod restarts gate the FSM)
        deadline = time.monotonic() + 45 * load_factor()
        while time.monotonic() < deadline:
            c.simulate_kubelet(ready=True)
            if all(not n["spec"].get("unschedulable", False)
                   for n in c.list("v1", "Node")):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("upgrade FSM left nodes cordoned")

        # -- restart-operator: fresh manager converges with no churn ----
        mgr.stop()
        # tick the fake kubelet to a status fixpoint first: its DS status
        # (updatedNumberScheduled) can lag the FSM's last pod restarts,
        # and a post-restart catch-up write would read as operator churn
        while True:
            before = {d["metadata"]["name"]: d["metadata"]["resourceVersion"]
                      for d in c.list("apps/v1", "DaemonSet")}
            c.simulate_kubelet(ready=True)
            rvs = {d["metadata"]["name"]: d["metadata"]["resourceVersion"]
                   for d in c.list("apps/v1", "DaemonSet")}
            if rvs == before:
                break
        mgr2 = make_manager(c)
        try:
            wait_ready(c, mgr2)
            rvs2 = {d["metadata"]["name"]: d["metadata"]["resourceVersion"]
                    for d in c.list("apps/v1", "DaemonSet")}
            assert rvs == rvs2, "operator restart rewrote unchanged operands"

            # -- disable/enable operand --------------------------------
            def exporter_exists():
                return any(d["metadata"]["name"] == "libtpu-metrics-exporter"
                           for d in c.list("apps/v1", "DaemonSet"))

            cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
            cr["spec"]["metricsExporter"] = {"enabled": False}
            c.update(cr)
            wait_for(c, lambda: not exporter_exists(),
                     "disabled operand was not removed")
            cr = thaw_obj(c.get(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy"))
            cr["spec"]["metricsExporter"] = {"enabled": True}
            c.update(cr)
            wait_for(c, exporter_exists,
                     "re-enabled operand never came back")
            wait_ready(c, mgr2)

            # -- uninstall: CR deletion garbage-collects operands -------
            c.delete(V1, KIND_CLUSTER_POLICY, "tpu-cluster-policy")
            assert c.list("apps/v1", "DaemonSet") == []
        finally:
            mgr2.stop()
