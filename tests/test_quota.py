"""Fair-share admission: quota-tree math, batch-ordering policies, the
priority kill switch's byte-identity, deficit clocks / preemption
budgets, and the controller-level starvation-rescue arc.

The chaos plane (tests/test_chaos.py saturation-storm) owns the
end-to-end starvation-freedom verdict; this file owns the unit
contracts those verdicts are built from."""

import json
import random

import pytest

from tpu_operator.api import labels as L
from tpu_operator.api.slicerequest import (
    KIND_SLICE_REQUEST,
    PHASE_PLACED,
    PHASE_UNSCHEDULABLE,
    V1ALPHA1,
    SliceRequestSpec,
    new_slice_request,
)
from tpu_operator.runtime import FakeClient, Request
from tpu_operator.runtime.objects import get_nested
from tpu_operator.scheduling.quota import (
    POLICY_BASELINE,
    POLICY_FINISH_TIME,
    POLICY_THROUGHPUT,
    AdmissionState,
    QuotaClass,
    QuotaTree,
    baseline_key,
    created_epoch,
    order_batch,
)


def tree_of(*rows):
    return QuotaTree.from_config({"classes": list(rows)})


def item(name, cls, chips=4, priority=0, ns="default", stamp=None):
    cr = new_slice_request(name, {"chips": chips, "priority": priority},
                           namespace=ns)
    cr["metadata"].setdefault("annotations", {})[L.QUOTA_CLASS] = cls
    if stamp is not None:
        cr["metadata"]["creationTimestamp"] = stamp
    return (f"{ns}/{name}", cr, None, SliceRequestSpec.from_obj(cr))


class TestQuotaTreeMath:
    def test_weighted_shares_split_capacity(self):
        t = tree_of({"name": "a", "weight": 3}, {"name": "b", "weight": 1})
        assert t.shares(100, {"a": 100, "b": 100}) == \
            {"a": 75, "b": 25, "default": 0}

    def test_min_guarantee_granted_first(self):
        t = tree_of({"name": "a", "weight": 1, "minChips": 50},
                    {"name": "b", "weight": 1})
        s = t.shares(60, {"a": 100, "b": 100})
        assert s["a"] == 55 and s["b"] == 5

    def test_max_cap_leftover_is_borrowed(self):
        t = tree_of({"name": "a", "weight": 1, "maxChips": 30},
                    {"name": "b", "weight": 1})
        s = t.shares(100, {"a": 100, "b": 100})
        assert s["a"] == 30 and s["b"] == 70

    def test_demand_light_class_donates(self):
        t = tree_of({"name": "a", "weight": 1}, {"name": "b", "weight": 1})
        s = t.shares(100, {"a": 10, "b": 200})
        assert s["a"] == 10 and s["b"] == 90

    def test_hierarchical_borrow_within_parent(self):
        t = tree_of({"name": "team", "weight": 1},
                    {"name": "x", "parent": "team", "weight": 1},
                    {"name": "y", "parent": "team", "weight": 3},
                    {"name": "other", "weight": 1})
        s = t.shares(100, {"x": 100, "y": 100, "other": 0})
        # `other` has no demand: the whole 100 flows to team, then
        # splits 1:3 between its children
        assert s["other"] == 0
        assert s["x"] == 25 and s["y"] == 75

    def test_config_rejects_duplicates_unknown_parent_and_cycles(self):
        with pytest.raises(ValueError, match="duplicate"):
            tree_of({"name": "a"}, {"name": "a"})
        with pytest.raises(ValueError, match="unknown"):
            tree_of({"name": "a", "parent": "ghost"})
        with pytest.raises(ValueError, match="cycle"):
            tree_of({"name": "a", "parent": "b"},
                    {"name": "b", "parent": "a"})
        with pytest.raises(ValueError, match="classes"):
            QuotaTree.from_config({"classes": []})

    def test_default_leaf_always_exists(self):
        t = tree_of({"name": "a"})
        assert "default" in t.leaf_names()
        assert t.get("never-configured").name == "default"

    def test_class_resolution_annotation_then_namespace(self):
        t = tree_of({"name": "prod"}, {"name": "team-ns"})
        ann = item("r", "prod", ns="team-ns")[1]
        assert t.class_of(ann) == "prod"
        plain = new_slice_request("r", {"chips": 4}, namespace="team-ns")
        assert t.class_of(plain) == "team-ns"
        other = new_slice_request("r", {"chips": 4}, namespace="elsewhere")
        assert t.class_of(other) == "default"


class TestBaselineOrder:
    def test_fractional_seconds_order_numerically(self):
        """The legacy sort compared raw strings: '...10.5Z' < '...10Z'
        lexically ('.' < 'Z') even though 10.5s is LATER — the gang
        pass drained the younger request first. Epoch parsing must get
        this right."""
        younger = "2024-01-01T00:00:10.5Z"
        older = "2024-01-01T00:00:10Z"
        assert younger < older  # the lexical trap this guards against
        assert created_epoch({"metadata": {"creationTimestamp": younger}}) \
            > created_epoch({"metadata": {"creationTimestamp": older}})

    def test_offset_suffix_parses_like_zulu(self):
        z = created_epoch(
            {"metadata": {"creationTimestamp": "2024-01-01T00:00:10Z"}})
        off = created_epoch(
            {"metadata": {"creationTimestamp":
                          "2024-01-01T00:00:10+00:00"}})
        assert z == off

    def test_unparseable_sorts_last_with_name_tiebreak(self):
        good = item("a", "x", stamp="2024-01-01T00:00:00Z")
        bad_b = item("b", "x", stamp="not-a-timestamp")
        bad_c = item("c", "x", stamp="not-a-timestamp")
        keys = sorted([baseline_key(*[it[0], it[1], it[3]])
                       for it in (bad_c, bad_b, good)])
        assert [k[3] for k in keys] == ["a", "b", "c"]

    def test_priority_outranks_age(self):
        old = item("old", "x", priority=0, stamp="2024-01-01T00:00:00Z")
        new = item("new", "x", priority=5, stamp="2024-06-01T00:00:00Z")
        assert baseline_key(new[0], new[1], new[3]) < \
            baseline_key(old[0], old[1], old[3])


class TestOrderBatch:
    def test_kill_switch_is_identity_property(self):
        """The parity the chaos plane's byte-identical verdicts rest
        on: under the `priority` policy — or with no quota tree at all —
        order_batch returns the batch UNCHANGED, for any batch."""
        t = tree_of({"name": "a", "weight": 5}, {"name": "b"})
        rng = random.Random(0)
        for _ in range(50):
            items = [item(f"r{i}", rng.choice(("a", "b", "zzz")),
                          chips=rng.choice((4, 8, 16)),
                          priority=rng.randrange(3),
                          stamp=f"2024-01-01T00:00:{rng.randrange(60):02d}Z")
                     for i in range(rng.randrange(12))]
            rng.shuffle(items)
            assert order_batch(items, POLICY_BASELINE, t,
                               usage={"a": 99}) == items
            assert order_batch(items, POLICY_FINISH_TIME, None) == items

    def test_least_attained_class_drains_first(self):
        t = tree_of({"name": "a", "weight": 1}, {"name": "b", "weight": 1})
        items = [item("a1", "a"), item("a2", "a"), item("b1", "b")]
        out = order_batch(items, POLICY_FINISH_TIME, t,
                          usage={"a": 8, "b": 0})
        assert [it[0].split("/")[1] for it in out] == ["b1", "a1", "a2"]

    def test_interleave_charges_admitted_work(self):
        """Admitting an item charges its class immediately, so one
        backlogged class cannot monopolize the head of the batch."""
        t = tree_of({"name": "a", "weight": 1}, {"name": "b", "weight": 1})
        items = [item(f"a{i}", "a", chips=4) for i in range(3)] + \
                [item(f"b{i}", "b", chips=4) for i in range(3)]
        out = order_batch(items, POLICY_FINISH_TIME, t, usage={})
        classes = [it[0].split("/")[1][0] for it in out]
        assert classes == ["a", "b", "a", "b", "a", "b"]

    def test_weight_scales_attainment(self):
        t = tree_of({"name": "a", "weight": 4}, {"name": "b", "weight": 1})
        items = [item(f"a{i}", "a", chips=4) for i in range(4)] + \
                [item("b0", "b", chips=4)]
        out = order_batch(items, POLICY_FINISH_TIME, t, usage={})
        # one b item charges b 4 attained-per-weight; at w4, a has to
        # admit FOUR items to reach the same attainment — so after the
        # opening tie-break, all of a's backlog drains before b is due
        # again
        assert [it[0].split("/")[1] for it in out] == \
            ["a0", "b0", "a1", "a2", "a3"]

    def test_throughput_policy_uses_tflops_attainment(self):
        t = tree_of({"name": "a", "weight": 1}, {"name": "b", "weight": 1})
        items = [item("a1", "a"), item("b1", "b")]
        # equal chips usage, but a's chips are on a faster generation:
        # throughput-normalized fairness serves b first
        out = order_batch(items, POLICY_THROUGHPUT, t,
                          usage={"a": 8, "b": 8},
                          usage_tflops={"a": 8000.0, "b": 10.0})
        assert out[0][0] == "default/b1"


class TestAdmissionState:
    def test_deficit_clock_anchors_and_resets(self):
        t = tree_of({"name": "p", "minChips": 8})
        s = AdmissionState()
        assert s.observe(t, {"p": 0}, {"p": 8}, 100.0)["p"] == 0.0
        assert s.observe(t, {"p": 0}, {"p": 8}, 160.0)["p"] == 60.0
        # served to its floor: the clock resets, not pauses
        assert s.observe(t, {"p": 8}, {"p": 8}, 200.0)["p"] == 0.0
        assert s.observe(t, {"p": 0}, {"p": 8}, 220.0)["p"] == 0.0

    def test_floor_is_bounded_by_actual_demand(self):
        """A class queuing less than its min-guarantee is satisfied by
        what it asked for — no deficit for capacity it never wanted."""
        t = tree_of({"name": "p", "minChips": 32})
        s = AdmissionState()
        s.observe(t, {"p": 4}, {"p": 4}, 0.0)
        assert s.observe(t, {"p": 8}, {"p": 0}, 50.0)["p"] == 0.0

    def test_token_bucket_exhausts_and_rolls(self):
        qc = QuotaClass(name="b", preempt_tokens=2, preempt_window_s=600)
        s = AdmissionState()
        assert s.take_token(qc, 0.0)
        assert s.take_token(qc, 1.0)
        assert not s.take_token(qc, 2.0)
        assert s.remaining(qc, 2.0) == 0.0
        # a new window refills the bucket
        assert s.take_token(qc, 601.0)
        assert s.remaining(qc, 601.0) == 1.0

    def test_snapshot_roundtrip_preserves_accounting(self):
        qc = QuotaClass(name="b", preempt_tokens=3)
        s = AdmissionState()
        s.take_token(qc, 10.0)
        s.deficit_since["p"] = 42.0
        restored = AdmissionState.from_dict(
            json.loads(json.dumps(s.to_dict())))
        assert restored.deficit_since == {"p": 42.0}
        assert restored.remaining(qc, 11.0) == 2.0
        assert AdmissionState.from_dict(None).to_dict() == \
            AdmissionState().to_dict()


def add_tpu(c, name, accel="tpu-v5e-slice", topo="2x4", chips=4):
    return c.add_node(name, labels={
        L.GKE_TPU_ACCELERATOR: accel,
        L.GKE_TPU_TOPOLOGY: topo,
        L.GKE_ACCELERATOR_COUNT: str(chips)},
        allocatable={"google.com/tpu": str(chips)})


class TestStarvationRescueArc:
    """The controller-level tentpole contract: a starving class's
    min-guarantee is reclaimed through budget-bounded elastic MIGRATE
    intents — never a hard kill, never past the victim class's budget
    or its own floor."""

    def make(self, quota_rows, policy=POLICY_FINISH_TIME, n_nodes=4):
        from tpu_operator.controllers.placement_controller import (
            PlacementReconciler,
        )

        c = FakeClient()
        for i in range(n_nodes):  # 2x4 => two-node domains of 8 chips
            add_tpu(c, f"v5e-{i}")
        clock = [1000.0]
        rec = PlacementReconciler(
            client=c, namespace="default",
            quota=QuotaTree.from_config({"classes": quota_rows}),
            admission_policy=policy, now=lambda: clock[0])
        return c, rec, clock

    def seed(self, c, rec, clock, name, cls, chips=8, priority=0):
        cr = new_slice_request(
            name, {"chips": chips, "priority": priority},
            namespace="default")
        cr["metadata"].setdefault("annotations", {})[L.QUOTA_CLASS] = cls
        c.create(cr)
        clock[0] += 1.0
        rec.reconcile(Request(name=name, namespace="default"))
        return c.get(V1ALPHA1, KIND_SLICE_REQUEST, name, "default")

    def rows(self):
        return [{"name": "prod", "weight": 6, "minChips": 8,
                 "starvationBoundSeconds": 240},
                {"name": "batch", "weight": 1, "preemptTokens": 2}]

    def test_starving_min_posts_one_shape_matched_intent(self):
        from tpu_operator.controllers.slices import migration_of

        c, rec, clock = self.make(self.rows())
        assert get_nested(self.seed(c, rec, clock, "batch-a", "batch"),
                          "status", "phase") == PHASE_PLACED
        assert get_nested(self.seed(c, rec, clock, "batch-b", "batch"),
                          "status", "phase") == PHASE_PLACED
        prod = self.seed(c, rec, clock, "prod-1", "prod")
        # the fleet was full: prod parks while the rescue is in flight
        assert get_nested(prod, "status", "phase") == PHASE_UNSCHEDULABLE
        intents = [n for n in ("batch-a", "batch-b")
                   if migration_of(c.get(V1ALPHA1, KIND_SLICE_REQUEST, n,
                                         "default")).get("intent")]
        assert len(intents) == 1  # shape-matched: ONE 8-chip victim
        mig = migration_of(c.get(V1ALPHA1, KIND_SLICE_REQUEST,
                                 intents[0], "default"))
        assert mig["intent"] == "migrate"
        assert mig["preemptedFor"] == "prod"
        # the victim class paid exactly one budget token
        assert rec._admission.remaining(
            rec.quota.get("batch"), clock[0]) == 1.0

    def test_preemption_exempt_class_is_never_drained(self):
        from tpu_operator.controllers.slices import migration_of

        rows = [{"name": "prod", "weight": 6, "minChips": 8,
                 "starvationBoundSeconds": 240},
                {"name": "batch", "weight": 1}]  # preemptTokens 0
        c, rec, clock = self.make(rows)
        self.seed(c, rec, clock, "batch-a", "batch")
        self.seed(c, rec, clock, "batch-b", "batch")
        self.seed(c, rec, clock, "prod-1", "prod")
        assert not any(
            migration_of(c.get(V1ALPHA1, KIND_SLICE_REQUEST, n,
                               "default")).get("intent")
            for n in ("batch-a", "batch-b"))

    def test_drain_never_breaches_victim_floor(self):
        from tpu_operator.controllers.slices import migration_of

        rows = [{"name": "prod", "weight": 6, "minChips": 8,
                 "starvationBoundSeconds": 240},
                {"name": "batch", "weight": 1, "minChips": 16,
                 "preemptTokens": 4}]
        c, rec, clock = self.make(rows)
        self.seed(c, rec, clock, "batch-a", "batch")
        self.seed(c, rec, clock, "batch-b", "batch")
        self.seed(c, rec, clock, "prod-1", "prod")
        # batch sits exactly at its own 16-chip floor: draining 8 would
        # breach it, so prod's min must NOT be served by force here
        assert not any(
            migration_of(c.get(V1ALPHA1, KIND_SLICE_REQUEST, n,
                               "default")).get("intent")
            for n in ("batch-a", "batch-b"))

    def test_non_elastic_victim_is_skipped(self):
        from tpu_operator.controllers.slices import migration_of

        c, rec, clock = self.make(self.rows())
        self.seed(c, rec, clock, "batch-a", "batch")
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "batch-a", "default")
        c.patch(V1ALPHA1, KIND_SLICE_REQUEST, "batch-a",
                {"metadata": {"annotations": {L.SLICE_ELASTIC: "false"}}},
                namespace="default")
        self.seed(c, rec, clock, "batch-b", "batch")
        cr_b = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "batch-b", "default")
        c.patch(V1ALPHA1, KIND_SLICE_REQUEST, "batch-b",
                {"metadata": {"annotations": {L.SLICE_ELASTIC: "false"}}},
                namespace="default")
        del cr, cr_b
        self.seed(c, rec, clock, "prod-1", "prod")
        # both victims pinned non-elastic: quota NEVER hard-kills
        assert not any(
            migration_of(c.get(V1ALPHA1, KIND_SLICE_REQUEST, n,
                               "default")).get("intent")
            for n in ("batch-a", "batch-b"))

    def test_starvation_gauge_fires_before_the_bound(self):
        from tpu_operator.metrics.registry import render_prometheus

        c, rec, clock = self.make(self.rows())
        self.seed(c, rec, clock, "batch-a", "batch")
        self.seed(c, rec, clock, "batch-b", "batch")
        self.seed(c, rec, clock, "prod-1", "prod")  # anchors the clock
        clock[0] += 60.0
        rec.reconcile(Request(name="prod-1", namespace="default"))
        text = render_prometheus()
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("tpu_operator_admission_starvation_seconds")
            and 'class="prod"' in ln)
        assert 0.0 < float(line.rsplit(" ", 1)[1]) < 240.0

    def test_escalation_targets_starving_class_queue(self):
        c, rec, clock = self.make(self.rows())
        seen = []
        rec._escalate_fn = lambda req, cause=None: seen.append(
            (str(req), getattr(cause, "reason", None)))
        self.seed(c, rec, clock, "batch-a", "batch")
        self.seed(c, rec, clock, "batch-b", "batch")
        self.seed(c, rec, clock, "prod-1", "prod")
        assert ("default/prod-1", "admission-starvation") in seen

    def test_kill_switch_matches_legacy_byte_for_byte(self):
        """No quota config + the baseline policy must leave the gang
        pass BYTE-identical to a reconciler that has never heard of
        admission — same statuses, same leases, same everything."""
        from tpu_operator.controllers.placement_controller import (
            PlacementReconciler,
        )

        def drive(policy):
            c = FakeClient()
            for i in range(6):
                add_tpu(c, f"v5e-{i}")
            rec = PlacementReconciler(client=c, namespace="default",
                                      admission_policy=policy,
                                      now=lambda: 1000.0)
            for i, (chips, prio) in enumerate(
                    ((8, 0), (4, 2), (8, 1), (4, 0), (8, 2))):
                cr = new_slice_request(
                    f"r{i}", {"chips": chips, "priority": prio},
                    namespace="default")
                cr["metadata"]["creationTimestamp"] = \
                    f"2024-01-01T00:00:{i:02d}Z"
                c.create(cr)
            for i in range(5):
                rec.reconcile(Request(name=f"r{i}", namespace="default"))

            def scrub(obj):
                # uids are random per FakeClient run; everything else
                # (phases, nodes, reasons, versions) must be identical
                if isinstance(obj, dict):
                    return {k: scrub(v) for k, v in obj.items()
                            if k != "uid"}
                if isinstance(obj, (list, tuple)):
                    return [scrub(v) for v in obj]
                return obj

            return json.dumps(
                [scrub(c.get(V1ALPHA1, KIND_SLICE_REQUEST, f"r{i}",
                             "default")) for i in range(5)],
                sort_keys=True, default=str)

        assert drive(None) == drive(POLICY_BASELINE)
        # and with no config present, even the fair policy cannot
        # diverge: no tree means the admission layer is a strict no-op
        assert drive(None) == drive(POLICY_FINISH_TIME)


class TestQuotaDebugEndpoint:
    """/debug/quota over the live health server: Manager.find_admission
    unwraps the controller stack to the reconciler owning the report,
    and its absence is an explicit "not configured", never a 404."""

    @staticmethod
    def _get(port, path):
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as resp:
            return resp.status, json.loads(resp.read())

    def test_no_admission_controller_is_explicit(self):
        from tpu_operator.runtime.manager import Manager

        mgr = Manager(FakeClient(), namespace="default", health_port=0)
        mgr.start()
        try:
            status, doc = self._get(
                mgr._http.server_address[1], "/debug/quota")
        finally:
            mgr.stop()
        assert status == 200
        assert doc == {"configured": False, "classes": []}

    def test_serves_live_admission_report(self):
        from tpu_operator.controllers.placement_controller import (
            PlacementReconciler,
        )
        from tpu_operator.runtime.manager import Manager

        c = FakeClient()
        for i in range(2):
            add_tpu(c, f"tpu-{i}")
        tree = tree_of({"name": "prod", "weight": 3, "minChips": 4},
                       {"name": "batch", "weight": 1})
        mgr = Manager(c, namespace="default", health_port=0)
        mgr.add_reconciler(PlacementReconciler(
            client=c, namespace="default", quota=tree,
            admission_policy=POLICY_FINISH_TIME))
        mgr.start()
        try:
            status, doc = self._get(
                mgr._http.server_address[1], "/debug/quota")
        finally:
            mgr.stop()
        assert status == 200
        assert doc["configured"] is True
        assert doc["policy"] == POLICY_FINISH_TIME
        assert doc["capacityChips"] == 8
        rows = {row["class"]: row for row in doc["classes"]}
        assert set(rows) == {"prod", "batch", "default"}
        assert rows["prod"]["minChips"] == 4
        # the manager-side report folds in live admission state, so
        # deficit clocks and token buckets are present (not unknown)
        assert "deficitSeconds" in rows["prod"]
        assert "tokensRemaining" in rows["prod"]
