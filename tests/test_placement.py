"""Heterogeneity-aware slice placement engine + SliceRequest controller.

Four layers under test:

1. The pure engine (topology/placement.py): fleet partitioning into ICI
   domains, scoring (throughput / adjacency / domain tightness /
   preference), deterministic ranking, and the unschedulable explainer.
2. The controller (controllers/placement_controller.py): the
   Pending -> Placed -> (evicted) lifecycle, lease soundness, and
   priority preemption with its feasibility gate.
3. The chaos integration: placement-contention is byte-deterministic
   per seed (convergence of every scenario is test_chaos.py's
   parametrized sweep).
4. The tooling: run_placement_bench keys and the ``tpuop-cfg place
   --explain`` golden output.
"""

import json

import pytest

from tpu_operator.api import labels as L
from tpu_operator.api.slicerequest import (
    KIND_SLICE_REQUEST,
    PHASE_PENDING,
    PHASE_PLACED,
    PHASE_UNSCHEDULABLE,
    V1ALPHA1,
    SliceRequestSpec,
    new_slice_request,
)
from tpu_operator.controllers.placement_controller import PlacementReconciler
from tpu_operator.runtime import FakeClient, Request
from tpu_operator.runtime.objects import annotations_of, get_nested
from tpu_operator.topology.placement import (
    FleetState,
    first_fit,
    place,
    rank_candidates,
    unschedulable_reason,
)


def add_tpu(c, name, accel="tpu-v5e-slice", topo="2x4", chips=4,
            worker_id=None, pool=None):
    labels = {
        L.GKE_TPU_ACCELERATOR: accel,
        L.GKE_TPU_TOPOLOGY: topo,
        L.GKE_ACCELERATOR_COUNT: str(chips),
    }
    if worker_id is not None:
        labels[L.GKE_TPU_WORKER_ID] = str(worker_id)
    if pool is not None:
        labels[L.GKE_NODEPOOL] = pool
    return c.add_node(name, labels=labels,
                      allocatable={"google.com/tpu": str(chips)})


def mixed_fleet():
    """2 v5e 2-host slices, 1 v5p 4-host 4x4 slice, 2 v4 single-host
    slices — enough heterogeneity to exercise every scoring term."""
    c = FakeClient()
    for i in range(4):
        add_tpu(c, f"v5e-{i}")
    for i in range(4):
        add_tpu(c, f"v5p-{i}", accel="tpu-v5p-slice", topo="4x4",
                worker_id=i)
    for i in range(2):
        add_tpu(c, f"v4-{i}", accel="tpu-v4-podslice", topo="2x2x1")
    return c


class TestFleetPartitioning:
    def test_unlabeled_pool_chunks_by_topology(self):
        """Without worker-id labels a 2x4 pool (2 hosts/slice) must
        split into 2-host domains, not weld into one pseudo-domain."""
        c = FakeClient()
        for i in range(6):
            add_tpu(c, f"v5e-{i}")
        fleet = FleetState(c.list("v1", "Node"))
        assert sorted(len(g.hosts) for g in fleet.slices) == [2, 2, 2]

    def test_node_count_not_multiple_of_hosts_per_slice(self):
        """5 nodes at 2 hosts/slice: two full domains plus a short
        orphan — the orphan still serves single-host requests but can
        never host a 2-host slice."""
        c = FakeClient()
        for i in range(5):
            add_tpu(c, f"v5e-{i}")
        fleet = FleetState(c.list("v1", "Node"))
        assert sorted(len(g.hosts) for g in fleet.slices) == [1, 2, 2]
        # 8 chips (2 hosts) fits the full domains, never the orphan
        best = place(SliceRequestSpec(chips=8), fleet)
        assert best is not None and len(best.nodes) == 2

    def test_single_node_multi_host_topology(self):
        """One node labeled with a 16-host topology: a 1-host domain —
        placeable for a host-sized request, with no phantom capacity."""
        c = FakeClient()
        add_tpu(c, "lone", accel="tpu-v5p-slice", topo="4x4x4")
        fleet = FleetState(c.list("v1", "Node"))
        [group] = fleet.slices
        assert len(group.hosts) == 1
        assert place(SliceRequestSpec(chips=4), fleet) is not None
        # 8 chips needs 2 hosts; the domain has 1 — unschedulable, and
        # the reason names the real free capacity
        assert place(SliceRequestSpec(chips=8), fleet) is None

    def test_worker_id_collisions_split_subslices(self):
        """Two physical 4x4 slices sharing a grouping key (worker ids
        0..3 twice) are recovered as two 4-host domains."""
        c = FakeClient()
        for i in range(8):
            add_tpu(c, f"v5p-{i}", accel="tpu-v5p-slice", topo="4x4",
                    worker_id=i % 4)
        fleet = FleetState(c.list("v1", "Node"))
        assert sorted(len(g.hosts) for g in fleet.slices) == [4, 4]


class TestScoring:
    def test_exact_fit_beats_big_domain_nibble(self):
        """The heterogeneity claim in one assertion: an 8-chip request
        takes a v5e 2-host slice whole rather than carving 2 hosts out
        of the faster v5p 4-host domain."""
        fleet = FleetState(mixed_fleet().list("v1", "Node"))
        best = place(SliceRequestSpec(chips=8), fleet)
        assert best.generation == "v5e"
        assert best.breakdown["fragmentation"] == 1.0
        # ...while first-fit ordering happens to agree here, the v5p
        # candidates exist and rank strictly below
        v5p = [cand for cand in rank_candidates(SliceRequestSpec(chips=8),
                                                fleet)
               if cand.generation == "v5p"]
        assert v5p and all(cand.score < best.score for cand in v5p)

    def test_throughput_breaks_ties_between_exact_fits(self):
        """4-chip request, v4 and v5p single-host exact fits both free:
        the faster generation wins."""
        c = FakeClient()
        add_tpu(c, "v4-0", accel="tpu-v4-podslice", topo="2x2x1")
        add_tpu(c, "v5p-0", accel="tpu-v5p-slice", topo="2x2x1")
        best = place(SliceRequestSpec(chips=4),
                     FleetState(c.list("v1", "Node")))
        assert best.generation == "v5p"

    def test_preference_steers_but_never_overrides_domain_protection(self):
        fleet = FleetState(mixed_fleet().list("v1", "Node"))
        # soft preference for v4 wins among exact fits
        best = place(SliceRequestSpec(
            chips=4, preferred_generations=["v4"]), fleet)
        assert best.generation == "v4"
        # but preferring v5p cannot push an 8-chip request into
        # nibbling a big v5p domain while a v5e exact fit exists: the
        # bonus ceiling sits below the tightness gap of a 16-host slice
        c = FakeClient()
        for i in range(2):
            add_tpu(c, f"v5e-{i}")
        for i in range(16):
            add_tpu(c, f"v5p-{i}", accel="tpu-v5p-slice", topo="4x4x4",
                    worker_id=i)
        best = place(SliceRequestSpec(
            chips=8, preferred_generations=["v5p"]),
            FleetState(c.list("v1", "Node")))
        assert best.generation == "v5e"

    def test_accelerator_pin_filters_hard(self):
        fleet = FleetState(mixed_fleet().list("v1", "Node"))
        best = place(SliceRequestSpec(chips=8,
                                      accelerator="tpu-v5p-slice"), fleet)
        assert best.generation == "v5p"
        assert place(SliceRequestSpec(chips=8,
                                      accelerator="tpu-v6e-slice"),
                     fleet) is None

    def test_ranking_is_deterministic(self):
        nodes = mixed_fleet().list("v1", "Node")
        spec = SliceRequestSpec(chips=8)
        a = rank_candidates(spec, FleetState(nodes))
        b = rank_candidates(spec, FleetState(list(reversed(nodes))))
        assert [(c.score, c.nodes) for c in a] == \
               [(c.score, c.nodes) for c in b]

    def test_booked_nodes_leave_the_pool(self):
        fleet = FleetState(mixed_fleet().list("v1", "Node"))
        first = place(SliceRequestSpec(chips=8), fleet)
        fleet.book(first.nodes, "default/a")
        second = place(SliceRequestSpec(chips=8), fleet)
        assert set(first.nodes).isdisjoint(second.nodes)
        fleet.release(node_names=first.nodes)
        third = place(SliceRequestSpec(chips=8), fleet)
        assert third.nodes == first.nodes

    def test_unschedulable_reasons(self):
        fleet = FleetState(mixed_fleet().list("v1", "Node"))
        assert "0 chips" in unschedulable_reason(SliceRequestSpec(), fleet)
        assert "accelerator pin" in unschedulable_reason(
            SliceRequestSpec(chips=4, accelerator="tpu-v6e-slice"), fleet)
        assert "no pool topology admits" in unschedulable_reason(
            SliceRequestSpec(topology="8x8x8"), fleet)
        # 64 chips: the largest admitting domain (v5p 4x4) offers 16
        assert "largest ICI domain offers 16" in unschedulable_reason(
            SliceRequestSpec(chips=64), fleet)

    def test_first_fit_shares_validity_not_scoring(self):
        fleet = FleetState(mixed_fleet().list("v1", "Node"))
        naive = first_fit(SliceRequestSpec(chips=8), fleet)
        assert naive is not None and naive.score == 0.0
        assert first_fit(SliceRequestSpec(chips=64), fleet) is None


class TestControllerLifecycle:
    def make(self, preemption=False):
        c = mixed_fleet()
        rec = PlacementReconciler(client=c, namespace="default",
                                  preemption=preemption)
        return c, rec

    def req(self, c, name, **kw):
        c.create(new_slice_request(
            name, spec=SliceRequestSpec(**kw).to_obj(),
            namespace="default"))
        return Request(name=name, namespace="default")

    def test_place_writes_leases_then_status(self):
        c, rec = self.make()
        rec.reconcile(self.req(c, "a", chips=8))
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr, "status", "phase") == PHASE_PLACED
        bound = get_nested(cr, "status", "nodes")
        assert len(bound) == 2
        for n in bound:
            node = c.get("v1", "Node", n)
            assert annotations_of(node).get(L.PLACED_BY) == "default/a"
        assert get_nested(cr, "status", "score") == \
            f"{place(SliceRequestSpec(chips=8), FleetState(c.list('v1', 'Node')), reclaim='default/a').score:.6f}"

    def test_unschedulable_sets_reason_and_requeues(self):
        c, rec = self.make()
        result = rec.reconcile(self.req(c, "big", chips=64))
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "big", "default")
        assert get_nested(cr, "status", "phase") == PHASE_UNSCHEDULABLE
        assert "largest ICI domain" in get_nested(cr, "status", "reason")
        assert result.requeue_after is not None

    def test_node_removal_evicts_then_replaces(self):
        c, rec = self.make()
        rec.reconcile(self.req(c, "a", chips=4))
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        [bound] = get_nested(cr, "status", "nodes")
        c.delete("v1", "Node", bound)
        req = Request(name="a", namespace="default")
        rec.reconcile(req)          # detects the broken binding
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr, "status", "phase") == PHASE_PENDING
        assert get_nested(cr, "status", "evictions") == 1
        assert bound in get_nested(cr, "status", "lastEvictionReason")
        rec.reconcile(req)          # re-places elsewhere
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr, "status", "phase") == PHASE_PLACED
        assert bound not in get_nested(cr, "status", "nodes")

    def test_deletion_releases_leases(self):
        c, rec = self.make()
        rec.reconcile(self.req(c, "a", chips=8))
        c.delete(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        rec.reconcile(Request(name="a", namespace="default"))
        assert not any(annotations_of(n).get(L.PLACED_BY)
                       for n in c.list("v1", "Node"))

    def test_lease_theft_breaks_binding(self):
        c, rec = self.make()
        rec.reconcile(self.req(c, "a", chips=4))
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        [bound] = get_nested(cr, "status", "nodes")
        c.patch("v1", "Node", bound,
                {"metadata": {"annotations": {L.PLACED_BY: "default/thief"}}})
        rec.reconcile(Request(name="a", namespace="default"))
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr, "status", "phase") == PHASE_PENDING
        assert "taken by default/thief" in \
            get_nested(cr, "status", "lastEvictionReason")

    def test_preemption_drains_lowest_priority_and_binds(self):
        c, rec = self.make(preemption=True)
        # fill both v5e slices at priority 0
        rec.reconcile(self.req(c, "low-a", chips=8, priority=0))
        rec.reconcile(self.req(c, "low-b", chips=8, priority=0))
        # pin the high-priority request to v5e so nothing else fits
        rec.reconcile(self.req(c, "high", chips=8, priority=5,
                               accelerator="tpu-v5e-slice"))
        high = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "high", "default")
        assert get_nested(high, "status", "phase") == PHASE_PLACED
        drained = [n for n in ("low-a", "low-b")
                   if get_nested(c.get(V1ALPHA1, KIND_SLICE_REQUEST, n,
                                       "default"),
                                 "status", "phase") == PHASE_PENDING]
        assert len(drained) == 1
        victim = c.get(V1ALPHA1, KIND_SLICE_REQUEST, drained[0], "default")
        assert "preempted by default/high" in \
            get_nested(victim, "status", "lastEvictionReason")

    def test_preemption_feasibility_gate(self):
        """An infeasible request (no domain big enough even empty) must
        not drain anything — the anti-thrash gate."""
        c, rec = self.make(preemption=True)
        rec.reconcile(self.req(c, "low", chips=8, priority=0))
        rec.reconcile(self.req(c, "huge", chips=64, priority=9))
        huge = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "huge", "default")
        assert get_nested(huge, "status", "phase") == PHASE_UNSCHEDULABLE
        low = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "low", "default")
        assert get_nested(low, "status", "phase") == PHASE_PLACED
        assert not get_nested(low, "status", "evictions", default=0)

    def test_preemption_off_by_default(self):
        c, rec = self.make()          # preemption=False
        rec.reconcile(self.req(c, "low-a", chips=8, priority=0))
        rec.reconcile(self.req(c, "low-b", chips=8, priority=0))
        rec.reconcile(self.req(c, "high", chips=8, priority=5,
                               accelerator="tpu-v5e-slice"))
        high = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "high", "default")
        assert get_nested(high, "status", "phase") == PHASE_UNSCHEDULABLE

    def test_steady_state_is_zero_write(self):
        """Re-reconciling a sound Placed request writes nothing — the
        zero-write steady state extends to placements."""
        c, rec = self.make()
        req = self.req(c, "a", chips=8)
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        rv = get_nested(cr, "metadata", "resourceVersion")
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr, "metadata", "resourceVersion") == rv


class TestChaosPlacement:
    @pytest.mark.slow
    def test_placement_contention_byte_identical(self):
        from tpu_operator.chaos.runner import run_scenario

        runs = [run_scenario("placement-contention", nodes=60, seed=7)
                for _ in range(2)]
        payloads = [json.dumps(v, indent=2, sort_keys=True) for v in runs]
        assert payloads[0] == payloads[1]
        assert runs[0]["ok"] is True
        summary = runs[0]["placement"]
        assert summary["requests"] > 0
        assert set(summary["phases"]) <= {"Placed", "Unschedulable",
                                          "Pending"}

    def test_placement_contention_small_deterministic(self):
        """Tier-1-sized determinism check (the 60-node run above is
        marked slow; convergence at 100 nodes is test_chaos.py's
        parametrized sweep)."""
        from tpu_operator.chaos.runner import run_scenario

        runs = [run_scenario("placement-contention", nodes=24, seed=3,
                             steps=6)
                for _ in range(2)]
        payloads = [json.dumps(v, indent=2, sort_keys=True) for v in runs]
        assert payloads[0] == payloads[1]
        assert runs[0]["violations"] == []


class TestPlacementBench:
    def test_bench_smoke(self):
        from tpu_operator.benchmarks.controlplane import run_placement_bench

        r = run_placement_bench(n_tpu=60, n_requests=120, lifetime=30)
        assert r["placed"] + r["unschedulable"] == 120
        assert 0.0 < r["fleet_utilization"] <= 1.0
        assert 0.0 < r["fleet_utilization_first_fit"] <= 1.0
        assert r["placement_p99_ms"] < 50.0
        assert r["placement_p50_ms"] <= r["placement_p99_ms"]

    @pytest.mark.slow
    def test_scored_beats_first_fit_at_scale(self):
        """The acceptance criterion itself: at the official bench shape
        the heterogeneity-aware scorer sustains measurably higher
        steady-state utilization than naive first-fit."""
        from tpu_operator.benchmarks.controlplane import run_placement_bench

        r = run_placement_bench()
        assert r["placement_p99_ms"] < 50.0
        assert r["fleet_utilization"] > r["fleet_utilization_first_fit"]


FIXTURE_YAML = """\
pools:
  - accelerator: tpu-v5p-slice
    topology: 4x4
    chips: 4
    count: 4
  - accelerator: tpu-v5e-slice
    topology: 2x4
    chips: 4
    count: 2
  - accelerator: tpu-v4-podslice
    topology: 2x2x1
    chips: 4
    count: 1
"""

GOLDEN_EXPLAIN = """\
fleet: 3 slices, free chips v4:4/4 v5e:8/8 v5p:16/16
request: chips=8
3 candidates (top 3):
  1. 0.646569  v5e-2x4/v5e-2x4  8 chips on 2 host(s)
     throughput=0.214597 adjacency=1.000000 fragmentation=1.000000 preference=0.000000
     nodes: v5e-2x4-0, v5e-2x4-1
  2. 0.625000  v5p-4x4/v5p-4x4  8 chips on 2 host(s)
     throughput=0.500000 adjacency=1.000000 fragmentation=0.500000 preference=0.000000
     nodes: v5p-4x4-0, v5p-4x4-1
  3. 0.625000  v5p-4x4/v5p-4x4  8 chips on 2 host(s)
     throughput=0.500000 adjacency=1.000000 fragmentation=0.500000 preference=0.000000
     nodes: v5p-4x4-2, v5p-4x4-3
"""


class TestPlaceCli:
    def run_cli(self, tmp_path, capsys, *argv):
        from tpu_operator.cli.tpuop_cfg import main

        fixture = tmp_path / "fleet.yaml"
        fixture.write_text(FIXTURE_YAML)
        rc = main(["place", "--fleet", str(fixture), *argv])
        return rc, capsys.readouterr().out

    def test_explain_golden(self, tmp_path, capsys):
        """Byte-stable ranked-candidate output: the explainer is part of
        the operational contract — support reads these scores."""
        rc, out = self.run_cli(tmp_path, capsys, "--chips", "8",
                               "--explain")
        assert rc == 0
        assert out == GOLDEN_EXPLAIN
        # and byte-stable across runs
        rc2, out2 = self.run_cli(tmp_path, capsys, "--chips", "8",
                                 "--explain")
        assert out2 == out

    def test_json_output_parses_and_sorts(self, tmp_path, capsys):
        rc, out = self.run_cli(tmp_path, capsys, "--chips", "8", "-o",
                               "json")
        assert rc == 0
        doc = json.loads(out)
        assert doc["reason"] is None
        scores = [c["score"] for c in doc["candidates"]]
        assert scores == sorted(scores, reverse=True)

    def test_unschedulable_exit_code_and_reason(self, tmp_path, capsys):
        rc, out = self.run_cli(tmp_path, capsys, "--chips", "999")
        assert rc == 1
        assert "UNSCHEDULABLE" in out and "largest ICI domain" in out

    def test_bad_fixture_is_a_clean_error(self, tmp_path, capsys):
        from tpu_operator.cli.tpuop_cfg import main

        bad = tmp_path / "bad.yaml"
        bad.write_text("just a string")
        rc = main(["place", "--fleet", str(bad), "--chips", "8"])
        assert rc == 2


class TestUnschedulableBackoff:
    """Satellite: the fixed 30s Unschedulable requeue became a capped
    exponential backoff with deterministic per-(key, attempt) jitter."""

    def test_schedule_doubles_to_cap_deterministically(self):
        from tpu_operator.controllers.placement_controller import (
            REQUEUE_UNSCHEDULABLE_BASE_S,
            REQUEUE_UNSCHEDULABLE_CAP_S,
            unschedulable_backoff,
        )

        for attempt in range(12):
            d1 = unschedulable_backoff("default/a", attempt)
            d2 = unschedulable_backoff("default/a", attempt)
            assert d1 == d2  # seeded jitter: byte-identical chaos verdicts
            base = min(REQUEUE_UNSCHEDULABLE_CAP_S,
                       REQUEUE_UNSCHEDULABLE_BASE_S * 2 ** attempt)
            assert base <= d1 <= base * 1.25
        # different keys de-synchronize (the thundering-herd fix)
        assert unschedulable_backoff("default/a", 3) != \
            unschedulable_backoff("default/b", 3)

    def test_attempts_escalate_and_reset_on_placement(self):
        from tpu_operator.controllers.placement_controller import (
            REQUEUE_UNSCHEDULABLE_BASE_S,
        )
        from tpu_operator.metrics.operator_metrics import OPERATOR_METRICS

        c = mixed_fleet()
        rec = PlacementReconciler(client=c, namespace="default")
        c.create(new_slice_request(
            "big", spec=SliceRequestSpec(chips=32).to_obj(),
            namespace="default"))
        req = Request(name="big", namespace="default")
        before = OPERATOR_METRICS.placement_requeues._value.get()
        delays = [rec.reconcile(req).requeue_after for _ in range(4)]
        after = OPERATOR_METRICS.placement_requeues._value.get()
        assert after == before + 4
        assert delays[0] < delays[1] < delays[2] < delays[3]
        assert delays[0] < REQUEUE_UNSCHEDULABLE_BASE_S * 1.25
        # grow the fleet so the request fits: attempt counter resets
        for i in range(8):
            add_tpu(c, f"grow-{i}", accel="tpu-v5p-slice", topo="4x8",
                    chips=4, worker_id=i, pool="grown")
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "big", "default")
        assert get_nested(cr, "status", "phase") == PHASE_PLACED
        assert rec._unsched_attempts.get("default/big", 0) == 0


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestResizeProtocol:
    """Elastic resize on a Placed request: a spec.chips edit drives the
    same intent/ack/rebind handshake as a migration, with the old
    binding kept on every degradation path."""

    def make(self, resize_timeout=120.0):
        c = mixed_fleet()
        clock = _Clock()
        rec = PlacementReconciler(client=c, namespace="default",
                                  now=clock, resize_timeout=resize_timeout)
        c.create(new_slice_request(
            "a", spec=SliceRequestSpec(chips=8).to_obj(),
            namespace="default"))
        req = Request(name="a", namespace="default")
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr, "status", "phase") == PHASE_PLACED
        assert get_nested(cr, "status", "chips") == 8
        return c, rec, clock, req

    def _shrink(self, c, chips=4):
        from tpu_operator.runtime.objects import set_nested, thaw_obj

        cr = thaw_obj(c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default"))
        set_nested(cr, chips, "spec", "chips")
        c.update(cr)

    def test_spec_edit_posts_intent_then_ack_rebinds(self):
        from tpu_operator.api.slicerequest import (
            INTENT_SHRINK,
            MIG_CHECKPOINTED,
            MIG_MIGRATING,
            MIG_REBOUND,
        )
        from tpu_operator.runtime.objects import set_nested, thaw_obj

        c, rec, clock, req = self.make()
        old_nodes = set(get_nested(
            c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default"),
            "status", "nodes"))
        self._shrink(c, 4)
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert annotations_of(cr).get(L.SLICE_INTENT) == INTENT_SHRINK
        mig = get_nested(cr, "status", "migration")
        assert mig["phase"] == MIG_MIGRATING
        assert float(mig["deadline"]) == clock.t + 120.0
        # binding untouched until the workload acks
        assert set(get_nested(cr, "status", "nodes")) == old_nodes
        # the workload checkpoints and acks
        cr = thaw_obj(cr)
        mig = dict(get_nested(cr, "status", "migration"))
        mig.update({"phase": MIG_CHECKPOINTED, "ackedStep": 7})
        set_nested(cr, mig, "status", "migration")
        c.update_status(cr)
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        mig = get_nested(cr, "status", "migration")
        assert mig["phase"] == MIG_REBOUND
        assert get_nested(cr, "status", "chips") == 4
        assert len(get_nested(cr, "status", "nodes")) == 1
        assert get_nested(cr, "status", "migrations") == 1
        # intent annotations cleared; released nodes lost their lease
        assert L.SLICE_INTENT not in annotations_of(cr)
        for n in old_nodes - set(get_nested(cr, "status", "nodes")):
            node = c.get("v1", "Node", n)
            assert L.PLACED_BY not in annotations_of(node)

    def test_timeout_aborts_once_per_generation_and_keeps_binding(self):
        from tpu_operator.api.slicerequest import MIG_ABORTED

        c, rec, clock, req = self.make()
        old_nodes = set(get_nested(
            c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default"),
            "status", "nodes"))
        self._shrink(c, 4)
        rec.reconcile(req)
        clock.t += 121.0          # never acked: deadline passes
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        mig = get_nested(cr, "status", "migration")
        assert mig["phase"] == MIG_ABORTED
        assert "deadline" in mig["reason"]
        assert set(get_nested(cr, "status", "nodes")) == old_nodes
        # same generation never retries: the next pass posts nothing
        rec.reconcile(req)
        cr2 = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr2, "status", "migration")["phase"] == \
            MIG_ABORTED
        # a fresh spec edit (new generation) opens a fresh attempt
        self._shrink(c, 2)
        rec.reconcile(req)
        cr3 = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        assert get_nested(cr3, "status", "migration")["phase"] != \
            MIG_ABORTED

    def test_non_elastic_workload_aborts_immediately(self):
        from tpu_operator.api.slicerequest import MIG_ABORTED
        from tpu_operator.runtime.objects import thaw_obj

        c, rec, clock, req = self.make()
        cr = thaw_obj(c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default"))
        cr.setdefault("metadata", {}).setdefault(
            "annotations", {})[L.SLICE_ELASTIC] = "false"
        c.update(cr)
        old_nodes = set(get_nested(
            c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default"),
            "status", "nodes"))
        self._shrink(c, 4)
        rec.reconcile(req)
        cr = c.get(V1ALPHA1, KIND_SLICE_REQUEST, "a", "default")
        mig = get_nested(cr, "status", "migration")
        assert mig["phase"] == MIG_ABORTED
        assert "not elastic" in mig["reason"]
        assert set(get_nested(cr, "status", "nodes")) == old_nodes
