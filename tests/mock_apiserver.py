"""A live mock Kubernetes apiserver for the HTTP e2e tier.

Where tests/test_kubeclient.py uses a minimal stub to pin HTTPClient's
wire behavior, this server is complete enough to run the WHOLE operator
(Manager + all reconcilers) over real HTTP — the reference's live-cluster
e2e slot (tests/e2e/gpu_operator_test.go:36-100) without the cloud:

- path-shaped store with uids, resourceVersions, generation bumps on
  spec change, and status as a subresource;
- collection GETs (namespaced, all-namespaces, cluster-scoped) with
  label-selector filtering;
- LIVE watch streams: every mutation fans out to matching watchers
  (namespaced objects also reach all-namespaces watchers), and streams
  can be force-dropped to exercise client reconnect;
- owner-reference cascade deletion (the GC controller's job);
- the pods/eviction subresource with PodDisruptionBudget enforcement;
- fault injection: `fail_next_writes` answers the next N PUT/PATCH with
  a 409 Conflict (mid-reconcile conflict path).
"""

from __future__ import annotations

import copy
import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def _segments(path: str):
    return [s for s in path.strip("/").split("/") if s]


def is_collection_path(path: str) -> bool:
    segs = _segments(path)
    if not segs:
        return False
    if segs[0] == "api":
        return len(segs) == 3 or (len(segs) == 5 and segs[2] == "namespaces")
    if segs[0] == "apis":
        return len(segs) == 4 or (len(segs) == 6 and segs[3] == "namespaces")
    return False


def all_namespaces_collection(obj_path: str):
    """For a namespaced object path, the all-namespaces collection path
    (watchers on /api/v1/pods see /api/v1/namespaces/x/pods/y events)."""
    segs = _segments(obj_path)
    if segs[0] == "api" and len(segs) == 6 and segs[2] == "namespaces":
        return "/" + "/".join(segs[:2] + segs[4:5])
    if segs[0] == "apis" and len(segs) == 7 and segs[3] == "namespaces":
        return "/" + "/".join(segs[:3] + segs[5:6])
    return None


def collection_of(obj_path: str) -> str:
    return obj_path.rsplit("/", 1)[0]


def _matches_selector(obj: dict, selector: str) -> bool:
    labels = (obj.get("metadata") or {}).get("labels") or {}
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("!"):
            if part[1:] in labels:
                return False
        elif "=" in part:
            k, v = part.split("=", 1)
            if labels.get(k) != v:
                return False
        else:
            if part not in labels:
                return False
    return True


class MockApiServer:
    def __init__(self):
        self.lock = threading.RLock()
        self.objects: dict[str, dict] = {}   # object path -> dict
        self.rv = 100
        self.uid = 0
        self.fail_next_writes = 0            # inject N 409s on PUT/PATCH
        # (group, version, plural) -> openAPIV3Schema for registered CRDs;
        # writes to matching CR collections run admission (CEL + types)
        self.crd_schemas: dict[tuple, dict] = {}
        # (group, version, plural) CRDs declaring subresources.status —
        # main-resource writes must preserve stored status for these
        self.crd_status_sub: set[tuple] = set()
        self.watchers: list[tuple[str, queue.Queue, threading.Event]] = []
        # (rv, coll, alt_coll, event) log so a watch carrying
        # ?resourceVersion=X replays everything newer than X — real
        # apiserver semantics, required by informer-style clients that
        # RESUME after a stream drop instead of re-listing
        self.event_log: list[tuple[int, str, str, dict]] = []
        handler = type("H", (_Handler,), {"server_state": self})
        self.http = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.thread = threading.Thread(target=self.http.serve_forever,
                                       daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MockApiServer":
        self.thread.start()
        self.url = f"http://127.0.0.1:{self.http.server_address[1]}"
        return self

    def stop(self):
        self.drop_watch_streams()
        self.http.shutdown()
        self.http.server_close()

    # -- store helpers (also used by tests to seed/inspect) ----------------

    def next_rv(self) -> str:
        with self.lock:
            self.rv += 1
            return str(self.rv)

    def next_uid(self) -> str:
        with self.lock:
            self.uid += 1
            return f"uid-{self.uid}"

    def put_object(self, path: str, obj: dict, event: str = "ADDED"):
        """Seed/replace an object directly (bypasses conflict checks)."""
        meta = obj.setdefault("metadata", {})
        meta.setdefault("uid", self.next_uid())
        meta["resourceVersion"] = self.next_rv()
        meta.setdefault("generation", 1)
        self.maybe_register_crd(obj)
        with self.lock:
            self.objects[path] = obj
        self.publish(event, path, obj)

    # -- CRD admission (the real apiserver's CEL/schema gate) --------------

    def maybe_register_crd(self, obj: dict):
        """Storing a CustomResourceDefinition activates admission for its
        collections, like a real apiserver establishing the CR endpoint."""
        if obj.get("kind") != "CustomResourceDefinition":
            return
        spec = obj.get("spec") or {}
        group = spec.get("group", "")
        plural = (spec.get("names") or {}).get("plural", "")
        with self.lock:
            for ver in spec.get("versions") or []:
                schema = ((ver.get("schema") or {})
                          .get("openAPIV3Schema") or {})
                key = (group, ver.get("name", ""), plural)
                self.crd_schemas[key] = schema
                if "status" in (ver.get("subresources") or {}):
                    self.crd_status_sub.add(key)
                else:
                    self.crd_status_sub.discard(key)

    def schema_for_collection(self, coll_path: str):
        """openAPIV3Schema for a CR collection path, else None. Handles
        cluster-scoped (/apis/g/v/plural) and namespaced
        (/apis/g/v/namespaces/ns/plural) shapes."""
        key = self._crd_key(coll_path)
        if key is None:
            return None
        with self.lock:
            return self.crd_schemas.get(key)

    def has_status_subresource(self, coll_path: str) -> bool:
        key = self._crd_key(coll_path)
        with self.lock:
            return key in self.crd_status_sub

    @staticmethod
    def _crd_key(coll_path: str):
        segs = _segments(coll_path)
        if not segs or segs[0] != "apis" or len(segs) < 4:
            return None
        return (segs[1], segs[2], segs[-1])

    def publish(self, type_: str, obj_path: str, obj: dict):
        coll = collection_of(obj_path)
        alt = all_namespaces_collection(obj_path)
        evt = {"type": type_, "object": copy.deepcopy(obj)}
        try:
            evt_rv = int((obj.get("metadata") or {}).get(
                "resourceVersion") or 0)
        except (TypeError, ValueError):
            evt_rv = self.rv
        with self.lock:
            self.event_log.append((evt_rv, coll, alt, evt))
            for prefix, q, _closed in self.watchers:
                if prefix in (coll, alt):
                    q.put(evt)

    def drop_watch_streams(self):
        """Force-close every open watch stream (reconnect testing)."""
        with self.lock:
            for _, q, closed in self.watchers:
                closed.set()
                q.put(None)  # wake the stream loop

    def cascade_delete(self, path: str):
        with self.lock:
            obj = self.objects.pop(path, None)
        if obj is None:
            return None
        # real apiserver bumps rv on delete; the event log needs it so a
        # resuming watcher (rv = last MODIFIED it saw) gets the DELETED
        obj.setdefault("metadata", {})["resourceVersion"] = self.next_rv()
        self.publish("DELETED", path, obj)
        uid = (obj.get("metadata") or {}).get("uid")
        if uid:
            with self.lock:
                owned = [p for p, o in self.objects.items()
                         if any(r.get("uid") == uid for r in
                                (o.get("metadata") or {}).get(
                                    "ownerReferences") or [])]
            for p in owned:
                self.cascade_delete(p)
        return obj


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_state: MockApiServer = None

    def log_message(self, *a):
        pass

    @property
    def st(self) -> MockApiServer:
        return self.server_state

    def _read_body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(n)) if n else None

    def _send(self, code, doc):
        payload = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        try:
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _not_found(self):
        self._send(404, {"kind": "Status", "status": "Failure",
                         "reason": "NotFound", "code": 404})

    def _conflict(self, reason="Conflict"):
        self._send(409, {"kind": "Status", "status": "Failure",
                         "reason": reason, "code": 409})

    # -- GET: object / collection / watch ----------------------------------

    def do_GET(self):
        u = urlparse(self.path)
        q = parse_qs(u.query)
        if q.get("watch") == ["true"]:
            since = (q.get("resourceVersion") or [""])[0]
            return self._serve_watch(u.path, since)
        with self.st.lock:
            if u.path in self.st.objects:
                return self._send(200, copy.deepcopy(self.st.objects[u.path]))
        if is_collection_path(u.path):
            # items and rv must be captured under ONE lock: an rv read
            # after a concurrent write would be newer than the snapshot,
            # and a watch resuming from it would never see that write
            with self.st.lock:
                items = self._collect(u.path, q)
                rv = str(self.st.rv)
            return self._send(200, {
                "kind": "List",
                "items": items,
                "metadata": {"resourceVersion": rv}})
        self._not_found()

    def _collect(self, coll_path: str, q):
        selector = (q.get("labelSelector") or [""])[0]
        prefix = coll_path.rstrip("/") + "/"
        items = []
        with self.st.lock:
            entries = sorted(self.st.objects.items())
        for p, o in entries:
            direct = p.startswith(prefix) and "/" not in p[len(prefix):]
            fan_in = all_namespaces_collection(p) == coll_path
            # /api/v1/namespaces is both the Namespace collection and the
            # parent of every namespaced core path — only real Namespace
            # objects (exactly one extra segment) match `direct`
            if not (direct or fan_in):
                continue
            if selector and not _matches_selector(o, selector):
                continue
            item = copy.deepcopy(o)
            item.pop("apiVersion", None)
            item.pop("kind", None)
            items.append(item)
        return items

    def _serve_watch(self, coll_path: str, since_rv: str = ""):
        q: queue.Queue = queue.Queue()
        closed = threading.Event()
        with self.st.lock:
            # replay events newer than the client's resourceVersion FIRST
            # (registered under the lock, so nothing can slip between the
            # replay snapshot and live delivery)
            if since_rv:
                try:
                    since = int(since_rv)
                except ValueError:
                    since = 0
                for rv, coll, alt, evt in self.st.event_log:
                    if rv > since and coll_path in (coll, alt):
                        q.put(evt)
            self.st.watchers.append((coll_path, q, closed))
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            # chunked framing like a real apiserver: without it the
            # client's buffered reads sit on small events until more
            # bytes arrive (watch then only "works" on a busy cluster)
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.flush()
            while not closed.is_set():
                try:
                    evt = q.get(timeout=0.25)
                except queue.Empty:
                    continue
                if evt is None:
                    break
                try:
                    payload = (json.dumps(evt) + "\n").encode()
                    self.wfile.write(f"{len(payload):x}\r\n".encode())
                    self.wfile.write(payload + b"\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    break
            try:
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass
        finally:
            with self.st.lock:
                try:
                    self.st.watchers.remove((coll_path, q, closed))
                except ValueError:
                    pass
            self.close_connection = True

    # -- POST: create / eviction -------------------------------------------

    def do_POST(self):
        body = self._read_body()
        u = urlparse(self.path)
        if u.path.endswith("/eviction"):
            return self._serve_eviction(u.path[:-len("/eviction")])
        name = ((body or {}).get("metadata") or {}).get("name")
        path = f"{u.path.rstrip('/')}/{name}"
        with self.st.lock:
            exists = path in self.st.objects
        if exists:
            return self._conflict("AlreadyExists")
        errs = self._admission(u.path.rstrip("/"), body, None)
        if errs:
            return self._invalid(errs)
        meta = body.setdefault("metadata", {})
        meta["uid"] = self.st.next_uid()
        meta["resourceVersion"] = self.st.next_rv()
        meta.setdefault("generation", 1)
        self.st.maybe_register_crd(body)
        with self.st.lock:
            self.st.objects[path] = body
        self.st.publish("ADDED", path, body)
        # the GC controller's job: an object created with an ownerRef to
        # an already-deleted owner (an in-flight reconcile racing a
        # cascade delete) is accepted and then collected, like a real
        # cluster — without this, such orphans live forever in the mock
        if self._dangling_owner(body):
            self.st.cascade_delete(path)
        self._send(201, body)

    def _dangling_owner(self, obj: dict) -> bool:
        refs = (obj.get("metadata") or {}).get("ownerReferences") or []
        if not refs:
            return False
        with self.st.lock:
            live = {(o.get("metadata") or {}).get("uid")
                    for o in self.st.objects.values()}
        return any(r.get("uid") and r["uid"] not in live for r in refs)

    def _admission(self, coll_path: str, new: dict, old):
        """Registered-CRD admission: structural schema + CEL transition
        rules, exactly what bounces at `kubectl apply` on a real
        apiserver (nvidiadriver_types.go:40-186 parity)."""
        schema = self.st.schema_for_collection(coll_path)
        if schema is None:
            return []
        from tpu_operator.api.validate import admission_errors

        return admission_errors(new, old, schema)

    def _invalid(self, errs):
        self._send(422, {"kind": "Status", "status": "Failure",
                         "reason": "Invalid",
                         "message": "; ".join(errs), "code": 422})

    def _serve_eviction(self, pod_path):
        with self.st.lock:
            target = self.st.objects.get(pod_path)
        if target is None:
            return self._not_found()
        ns = (target.get("metadata") or {}).get("namespace", "")
        pod_labels = (target.get("metadata") or {}).get("labels") or {}
        pdb_prefix = f"/apis/policy/v1/namespaces/{ns}/poddisruptionbudgets/"

        def ready(p):
            return any(c.get("type") == "Ready" and c.get("status") == "True"
                       for c in (p.get("status") or {}).get(
                           "conditions") or [])

        with self.st.lock:
            entries = list(self.st.objects.items())
        from tpu_operator.runtime.objects import match_labels

        for path, pdb in entries:
            if not path.startswith(pdb_prefix):
                continue
            # full LabelSelector (matchLabels + matchExpressions), same
            # semantics the client-side _blocking_pdb enforces
            sel = (pdb.get("spec") or {}).get("selector") or {}
            if not sel or not match_labels(pod_labels, sel):
                continue
            allowed = (pdb.get("status") or {}).get("disruptionsAllowed")
            if allowed is None:
                pods = [o for p, o in entries
                        if p.startswith(f"/api/v1/namespaces/{ns}/pods/")
                        and match_labels((o.get("metadata") or {}).get(
                            "labels") or {}, sel)]
                healthy = sum(1 for p in pods if ready(p))
                allowed = healthy - int(
                    (pdb.get("spec") or {}).get("minAvailable", 0))
            if allowed <= 0:
                return self._send(429, {
                    "kind": "Status", "status": "Failure",
                    "reason": "TooManyRequests", "code": 429,
                    "message": "Cannot evict pod as it would violate the "
                               "pod's disruption budget."})
        self.st.cascade_delete(pod_path)
        self._send(201, {"kind": "Status", "status": "Success"})

    # -- PUT: replace / status ---------------------------------------------

    def do_PUT(self):
        body = self._read_body()
        u = urlparse(self.path)
        with self.st.lock:
            if self.st.fail_next_writes > 0:
                self.st.fail_next_writes -= 1
                return self._conflict()
        is_status = u.path.endswith("/status")
        target = u.path[:-len("/status")] if is_status else u.path
        with self.st.lock:
            current = self.st.objects.get(target)
        if current is None:
            return self._not_found()
        sent_rv = (body.get("metadata") or {}).get("resourceVersion")
        have_rv = (current.get("metadata") or {}).get("resourceVersion")
        if sent_rv and have_rv and sent_rv != have_rv:
            return self._conflict()
        if is_status:
            merged = copy.deepcopy(current)
            merged["status"] = body.get("status")
        else:
            errs = self._admission(collection_of(target), body, current)
            if errs:
                return self._invalid(errs)
            merged = body
            # CRDs with a status subresource: main-resource PUT cannot
            # touch status on a real apiserver — stored status survives
            # the replace (else `tpuop-cfg upgrade` would wipe CR status
            # here while leaving it intact on a real cluster)
            if self.st.has_status_subresource(collection_of(target)):
                if "status" in current:
                    merged["status"] = copy.deepcopy(current["status"])
                else:
                    merged.pop("status", None)
            meta = merged.setdefault("metadata", {})
            meta["uid"] = (current.get("metadata") or {}).get("uid")
            cur_gen = (current.get("metadata") or {}).get("generation", 1)
            meta["generation"] = (
                cur_gen + 1
                if merged.get("spec") != current.get("spec") else cur_gen)
            self.st.maybe_register_crd(merged)
        if self._noop(current, merged):
            return self._send(200, copy.deepcopy(current))
        merged.setdefault("metadata", {})["resourceVersion"] = \
            self.st.next_rv()
        with self.st.lock:
            self.st.objects[target] = merged
        self.st.publish("MODIFIED", target, merged)
        self._send(200, merged)

    @staticmethod
    def _noop(current: dict, merged: dict) -> bool:
        """True when the write changes nothing but the resourceVersion —
        real apiservers don't bump RV or emit events for no-op writes,
        and without this the kubelet ticker becomes an event storm."""
        a, b = copy.deepcopy(current), copy.deepcopy(merged)
        for o in (a, b):
            (o.get("metadata") or {}).pop("resourceVersion", None)
        return a == b

    # -- PATCH (merge) ------------------------------------------------------

    def do_PATCH(self):
        body = self._read_body()
        u = urlparse(self.path)
        with self.st.lock:
            if self.st.fail_next_writes > 0:
                self.st.fail_next_writes -= 1
                return self._conflict()
            current = self.st.objects.get(u.path)
        if current is None:
            return self._not_found()

        from tpu_operator.runtime.client import merge_patch

        # merge over a deep copy: merge_patch reuses subtrees the patch
        # does not touch, and admission defaulting mutates the new object
        # in place — without the copy a rejected or no-op PATCH would
        # default the STORED object with no RV bump or watch event
        merged = merge_patch(copy.deepcopy(current), body)
        # status subresource: a main-resource merge-patch cannot change
        # status (same apiserver rule the PUT path enforces)
        if self.st.has_status_subresource(collection_of(u.path)):
            if "status" in current:
                merged["status"] = copy.deepcopy(current["status"])
            else:
                merged.pop("status", None)
        # real apiservers run CEL/schema admission on every write verb —
        # a merge-patch must not slip past what PUT would bounce
        errs = self._admission(collection_of(u.path), merged, current)
        if errs:
            return self._invalid(errs)
        if self._noop(current, merged):
            return self._send(200, copy.deepcopy(current))
        merged.setdefault("metadata", {})["resourceVersion"] = \
            self.st.next_rv()
        with self.st.lock:
            self.st.objects[u.path] = merged
        self.st.publish("MODIFIED", u.path, merged)
        self._send(200, merged)

    # -- DELETE (with ownerReference cascade) -------------------------------

    def do_DELETE(self):
        u = urlparse(self.path)
        obj = self.st.cascade_delete(u.path)
        if obj is None:
            return self._not_found()
        self._send(200, {"kind": "Status", "status": "Success"})
